"""Channels: local futures-based channels + distributed ping-pong.

Reference analog: examples/quickstart/channel.cpp and
local_channel_docs — `hpx::lcos::local::channel` generator-style
consumption, and `hpx::distributed::channel` for cross-locality
handoff (1d_stencil_8's halo pattern).

Single process:  python examples/channel_demo.py
Multi-locality:  python -m hpx_tpu.run examples/channel_demo.py -l 2
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

setup_platform()

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.lcos import Channel  # noqa: E402
from hpx_tpu.svc.iostreams import cout  # noqa: E402


def local_demo() -> None:
    ch = Channel()

    def producer() -> None:
        for i in range(5):
            ch.set(i * i)
        ch.close()

    hpx.post(producer)
    got = list(ch)
    cout.println(f"local channel drained: {got}")
    assert got == [0, 1, 4, 9, 16]


def distributed_demo() -> None:
    here = hpx.find_here()
    comm = hpx.create_channel_communicator("pingpong", 2)
    if here == 0:
        comm.set(1, "ping")
        reply = comm.get(1).get()
        cout.println(f"locality 0 got: {reply}")
        assert reply == "pong"
    else:
        msg = comm.get(0).get()
        comm.set(0, "pong" if msg == "ping" else "???")
    hpx.get_runtime().barrier("pingpong-done")


def main() -> int:
    hpx.init()
    if hpx.find_here() == 0:
        local_demo()
    if hpx.get_num_localities() >= 2:
        distributed_demo()
    cout.flush().get()
    hpx.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
