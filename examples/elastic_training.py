"""Elastic training: checkpoint a sharded train state mid-run, restore
onto a DIFFERENTLY-SHAPED mesh, and continue — the next losses match
the uninterrupted run exactly.

Reference analog: checkpoint/restart across a changed locality count
(libs/full/checkpoint + the batch-environment restart story, SURVEY.md
§5.3/§5.4). TPU-native form: every leaf of the train-state pytree
records its PartitionSpec; restore re-places it over whatever mesh the
resuming run built (same axis NAMES, any device count whose shape still
divides the arrays).

Flow:
  1. build a tiny MLP train state sharded over mesh A = (dp=4, tp=2)
  2. train k steps; save_sharded_state_to_file
  3. throw everything away ("the job was preempted")
  4. rebuild on mesh B = (dp=2, tp=4); restore_sharded_state_from_file
  5. train the remaining steps on BOTH paths; compare losses

Usage: python examples/elastic_training.py [steps]
       (--cpu-mesh 8 for the virtual-device run the tests use)
"""

import sys
import tempfile

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import numpy as np  # noqa: E402


def main() -> int:
    steps = int(argv[0]) if argv else 6
    half = steps // 2

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import hpx_tpu as hpx

    devs = np.array(jax.devices())
    if devs.size < 8:
        print(f"elastic_training: need 8 devices, have {devs.size} — "
              "run with --cpu-mesh 8")
        return 0
    mesh_a = Mesh(devs[:8].reshape(4, 2), ("dp", "tp"))
    mesh_b = Mesh(devs[:8].reshape(2, 4), ("dp", "tp"))

    d_in, d_hid = 16, 32
    rng = np.random.default_rng(0)
    x_host = rng.standard_normal((8, d_in)).astype(np.float32)
    y_host = rng.standard_normal((8, 1)).astype(np.float32)

    def place(mesh, state):
        return {
            "w1": jax.device_put(state["w1"],
                                 NamedSharding(mesh, P(None, "tp"))),
            "w2": jax.device_put(state["w2"],
                                 NamedSharding(mesh, P("tp", None))),
            "step": state["step"],
        }

    def data(mesh):
        return (jax.device_put(x_host, NamedSharding(mesh, P("dp"))),
                jax.device_put(y_host, NamedSharding(mesh, P("dp"))))

    def loss_fn(params, x, y):
        h = jnp.tanh(x @ params["w1"])
        return ((h @ params["w2"] - y) ** 2).mean()

    @jax.jit
    def step_fn(state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(
            {"w1": state["w1"], "w2": state["w2"]}, x, y)
        return {"w1": state["w1"] - 0.05 * grads["w1"],
                "w2": state["w2"] - 0.05 * grads["w2"],
                "step": state["step"] + 1}, loss

    init = {"w1": rng.standard_normal((d_in, d_hid)).astype(np.float32),
            "w2": rng.standard_normal((d_hid, 1)).astype(np.float32),
            "step": 0}

    # ---- uninterrupted reference on mesh A
    ref = place(mesh_a, init)
    xa, ya = data(mesh_a)
    ref_losses = []
    for _ in range(steps):
        ref, lo = step_fn(ref, xa, ya)
        ref_losses.append(float(lo))

    # ---- elastic run: half on A, checkpoint, restore on B, finish
    state = place(mesh_a, init)
    for _ in range(half):
        state, _ = step_fn(state, xa, ya)

    with tempfile.NamedTemporaryFile(suffix=".ckpt") as f:
        hpx.save_sharded_state_to_file(f.name, state).get(timeout=120)
        del state                                   # "preempted"
        resumed = hpx.restore_sharded_state_from_file(f.name,
                                                      mesh=mesh_b)

    xb, yb = data(mesh_b)
    res_losses = []
    for _ in range(steps - half):
        resumed, lo = step_fn(resumed, xb, yb)
        res_losses.append(float(lo))

    tail = ref_losses[half:]
    ok = np.allclose(res_losses, tail, rtol=1e-5)
    print(f"ref tail    : {[round(v, 6) for v in tail]}")
    print(f"resumed (B) : {[round(v, 6) for v in res_losses]}")
    print(f"mesh A {dict(mesh_a.shape)} -> mesh B {dict(mesh_b.shape)}; "
          f"steps {int(resumed['step'])}/{steps}; "
          f"match={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
