"""2D Jacobi — config #5's workload family.

Reference analog: examples/jacobi/ + examples/jacobi_smp/ (2-D heat
relaxation with dataflow block dependencies; distributed variant
exchanges halos).

Variants: serial sweep loop, dataflow block DAG, and the sharded 2-D
mesh form (halo2d: ppermute halos in both axes, whole step one XLA
program).

Usage: python examples/jacobi2d.py [n] [blocks] [iters]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import numpy as np  # noqa: E402

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.models.jacobi2d import (  # noqa: E402
    JacobiParams, gather_blocks, init_grid, jacobi_dataflow,
    jacobi_serial, jacobi_sharded)


def main() -> int:
    import jax
    n = int(argv[0]) if argv else 256
    nb = int(argv[1]) if len(argv) > 1 else 4
    it = int(argv[2]) if len(argv) > 2 else 20
    p = JacobiParams(nx=n, ny=n, nb=nb, iterations=it)

    t = hpx.HighResolutionTimer()
    ref = np.asarray(jacobi_serial(p))
    t_serial = t.elapsed()

    t.restart()
    df = np.asarray(gather_blocks(jacobi_dataflow(p)))
    t_df = t.elapsed()
    np.testing.assert_allclose(df, ref, rtol=1e-4, atol=1e-5)

    ndev = len(jax.devices())
    gx = 2 if ndev % 2 == 0 else 1
    gy = max(1, ndev // gx)
    from hpx_tpu.parallel import make_mesh
    mesh = make_mesh((gx, gy), ("x", "y"))
    t.restart()
    u_sh, res = jacobi_sharded(p, mesh)
    sh = np.asarray(u_sh)
    t_sh = t.elapsed()
    np.testing.assert_allclose(sh, ref, rtol=1e-4, atol=1e-5)

    mc = n * n * it / 1e6
    print(f"jacobi {n}x{n}, {it} iters "
          f"({nb}x{nb} blocks, {gx}x{gy} mesh):")
    print(f"  serial:   {t_serial:.3f} s  ({mc / t_serial:8.1f} Mcells/s)")
    print(f"  dataflow: {t_df:.3f} s  ({mc / t_df:8.1f} Mcells/s)")
    print(f"  sharded:  {t_sh:.3f} s  ({mc / t_sh:8.1f} Mcells/s)")
    print("all variants agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
