"""Long-context attention over the device ring.

Shows the sequence-parallel substrate (SURVEY.md §5.7: the halo-ring /
all_to_all patterns) carrying real attention: a sequence too big to
attend on one device's memory budget is sharded over the mesh; ring
attention streams K/V chunks around the ICI ring with online softmax
(O(S/P) memory per chip), Ulysses swaps to head-parallel with one
all_to_all each way.

Usage: python examples/ring_attention_demo.py [seq] [--cpu-mesh 8]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.ops.attention import (reference_attention, ring_attention,  # noqa: E402
                                   ulysses_attention)
from hpx_tpu.parallel import make_mesh  # noqa: E402


def main() -> int:
    ndev = len(jax.devices())
    seq = int(argv[0]) if argv else 512
    seq -= seq % ndev
    b, n, h = 1, 8, 32
    mesh = make_mesh((ndev,), ("sp",))

    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, seq, n, h),
                                               np.float32))
               for _ in range(3))

    t = hpx.HighResolutionTimer()
    out_ring = ring_attention(q, k, v, mesh, "sp", causal=True)
    out_ring.block_until_ready()
    t_ring = t.elapsed()

    t.restart()
    out_striped = ring_attention(q, k, v, mesh, "sp", causal=True,
                                 striped=True)
    out_striped.block_until_ready()
    t_striped = t.elapsed()

    t.restart()
    out_uly = ulysses_attention(q, k, v, mesh, "sp", causal=True)
    out_uly.block_until_ready()
    t_uly = t.elapsed()

    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out_striped),
                               np.asarray(want), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out_uly), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    print(f"seq={seq} over {ndev} devices "
          f"(S/P = {seq // ndev} resident per chip):")
    print(f"  ring attention:    {t_ring * 1e3:8.2f} ms (first call, "
          f"incl. compile)")
    print(f"  striped ring:      {t_striped * 1e3:8.2f} ms (balanced "
          f"causal work: rank r never idles on future chunks)")
    print(f"  ulysses attention: {t_uly * 1e3:8.2f} ms")
    print("all match the full-materialization oracle")
    return 0


if __name__ == "__main__":
    sys.exit(main())
