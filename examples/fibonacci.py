"""Futurized fibonacci — the canonical HPX quickstart demo.

Reference analog: examples/quickstart/fibonacci.cpp (naive recursive
fib where each level is an hpx::async; demonstrates task spawning and
future composition, and why task granularity matters).

Usage: python examples/fibonacci.py [n] [threshold]
"""

import sys
import time

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import hpx_tpu as hpx  # noqa: E402


def fib_plain(n: int) -> int:
    return n if n < 2 else fib_plain(n - 1) + fib_plain(n - 2)


def fib_futurized(n: int, threshold: int) -> int:
    """Spawn a task per node above the threshold; below it, run serial
    (HPX's fibonacci_futures 'cutoff' — granularity control)."""
    if n < threshold:
        return fib_plain(n)
    lhs = hpx.async_(fib_futurized, n - 1, threshold)
    rhs = fib_futurized(n - 2, threshold)
    return lhs.get() + rhs


def main() -> int:
    n = int(argv[0]) if argv else 20
    threshold = int(argv[1]) if len(argv) > 1 else 12

    t = hpx.HighResolutionTimer()
    serial = fib_plain(n)
    t_serial = t.elapsed()

    t.restart()
    futurized = fib_futurized(n, threshold)
    t_fut = t.elapsed()

    assert serial == futurized
    print(f"fib({n}) = {futurized}")
    print(f"serial:    {t_serial * 1e3:8.2f} ms")
    print(f"futurized: {t_fut * 1e3:8.2f} ms "
          f"(threshold {threshold}, tasks on "
          f"{hpx.get_topology().number_of_cores()} core(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
