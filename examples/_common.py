"""Shared example plumbing: platform selection before jax import.

Examples run on the real TPU by default; pass --cpu-mesh N (or set
HPX_TPU_EXAMPLE_CPU=N) to run on an N-device virtual CPU mesh — the
same environment the test suite uses, so every example is runnable
anywhere. Must be imported BEFORE jax.
"""

import os
import sys


def setup_platform(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    n = os.environ.get("HPX_TPU_EXAMPLE_CPU")
    if "--cpu-mesh" in argv:
        i = argv.index("--cpu-mesh")
        n = argv[i + 1] if i + 1 < len(argv) else "8"
        del argv[i:i + 2]
    if n:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    return argv
