"""Load-balanced component placement + batched task fan-out.

Demonstrates (single- or multi-locality):
  * hpx.binpacked() — create components on the least-loaded locality
    (the reference's binpacking_distribution_policy);
  * hpx.colocated(client) — place next to an existing component;
  * hpx.post_many / hpx.async_many — fan out thousands of tasks with
    one batched scheduler submission;
  * the scheduler counters that make the load visible
    (--hpx:print-counter analog).

Run:  python examples/load_balancing.py [--cpu-mesh 8]
      python -m hpx_tpu.run -l 3 examples/load_balancing.py
"""

import os
import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

setup_platform()

# locality 0 grinds through many remote round trips while the workers
# sit in the closing barrier; on a loaded 1-core CI host that can
# exceed the 180 s default
os.environ.setdefault("HPX_TPU_BARRIER_TIMEOUT", "600")

import hpx_tpu as hpx  # noqa: E402


@hpx.register_component_type
class Shard(hpx.Component):
    """A stand-in for a stateful service shard."""

    def __init__(self, tag: str = "") -> None:
        self.tag = tag
        self.hits = 0

    def hit(self) -> int:
        self.hits += 1
        return self.hits

    def where_am_i(self) -> int:
        return hpx.find_here()


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    n_loc = hpx.get_num_localities()

    if here == 0:
        # binpacked placement: shards spread by per-type component load
        shards = [hpx.new_(Shard, hpx.binpacked(), f"s{i}").get()
                  for i in range(max(4, n_loc * 2))]
        homes = [s.sync("where_am_i") for s in shards]
        print(f"shards placed on localities: {sorted(set(homes))} "
              f"(distribution {[homes.count(x) for x in range(n_loc)]})")

        # colocated: an index cache wants to live WITH its shard
        cache = hpx.new_(Shard, hpx.colocated(shards[0]), "cache").get()
        assert cache.sync("where_am_i") == homes[0]
        print("cache colocated with shard 0 on locality", homes[0])

        # batched fan-out: one scheduler submission for the whole burst
        # (each task BLOCKS on a remote call — the help-depth-bounded
        # waiting path). Scaled to the runtime: multi-locality hits are
        # full parcel round trips, and on a loaded 1-core CI host each
        # can take seconds.
        n_hits = 96 if n_loc == 1 else 24
        futs = hpx.async_many(
            lambda i: shards[i % len(shards)].sync("hit"),
            [(i,) for i in range(n_hits)])
        total = sum(f.get() for f in futs)
        counts = sorted(s.sync("hit") - 1 for s in shards)
        print(f"{n_hits} batched hits -> per-shard "
              f"{counts[0]}..{counts[-1]}, running-counter sum {total}")

        # the load is observable through the counter registry
        from hpx_tpu.svc.performance_counters import query_counter
        executed = query_counter(
            "/threads{locality#0/pool#default}/count/cumulative").value
        idle = query_counter(
            "/threads{locality#0/pool#default}/idle-rate").value
        print(f"pool#default executed={executed:.0f} "
              f"idle-rate={idle:.2f}")

        for s in shards + [cache]:
            s.free().get()
        if n_loc > 1:
            hpx.get_runtime().barrier("done")
        print("OK")
    else:
        hpx.get_runtime().barrier("done")

    hpx.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
