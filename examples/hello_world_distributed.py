"""Hello world from every locality — the first HPX distributed demo.

Reference analog: examples/quickstart/hello_world_distributed.cpp
(hello from every locality, marshalled through hpx::cout so the console
prints one coherent stream).

Single process:  python examples/hello_world_distributed.py
Multi-locality:  python -m hpx_tpu.run examples/hello_world_distributed.py -l 3
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

setup_platform()

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.svc.iostreams import cout  # noqa: E402


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    n = hpx.get_num_localities()
    topo = hpx.get_topology()
    cout.println(f"hello world from locality {here} of {n} "
                 f"({topo.number_of_cores()} cores, "
                 f"platform {topo.platform()})")
    cout.flush().get()
    hpx.get_runtime().barrier("hello-done")
    hpx.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
