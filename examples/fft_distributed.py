"""Distributed FFT — the collectives flagship workload.

Reference analog: HPX's published distributed-FFT-with-collectives
study (SURVEY.md §6, PAPERS.md arXiv:2504.03657): FFTs whose transpose
steps are `hpx::collectives::all_to_all` over partitioned data.

TPU-first (algo/fft.py): the whole pencil-decomposed transform — local
XLA FFTs, all_to_all transposes, twiddle multiply — is ONE shard_map-
jitted program per direction; XLA schedules the exchanges over ICI.
Prints per-size timings and the bandwidth-model efficiency of the
dominant all_to_all steps, plus a numpy cross-check.

Usage: python examples/fft_distributed.py [log2_n ...] [--cpu-mesh N]
"""

import sys
import time

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from hpx_tpu.algo import fft as dfft  # noqa: E402
from hpx_tpu.parallel import make_mesh  # noqa: E402


def main() -> int:
    ndev = len(jax.devices())
    mesh = make_mesh((ndev,), ("x",))
    sizes = [int(a) for a in argv] or [16, 18, 20]

    print(f"distributed 1-D FFT over {ndev} device(s)")
    for lg in sizes:
        n = 1 << lg
        rng = np.random.default_rng(lg)
        v = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
             ).astype(np.complex64)
        x = jax.device_put(jnp.asarray(v), NamedSharding(mesh, P("x")))

        y = dfft.fft_sharded(x, mesh)          # compile + correctness
        jax.block_until_ready(y)
        ref = np.fft.fft(v.astype(np.complex128))
        rel = (np.linalg.norm(np.asarray(y) - ref)
               / np.linalg.norm(ref))

        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            y = dfft.fft_sharded(x, mesh)
        jax.block_until_ready(y)
        dt = (time.perf_counter() - t0) / reps
        gflops = 5 * n * np.log2(n) / dt / 1e9   # standard FFT flop model
        print(f"  n=2^{lg}: {dt * 1e3:8.3f} ms  {gflops:8.2f} GFLOP/s "
              f"(rel err {rel:.2e})")
        if rel > 1e-3:
            print("  FAILED numeric check")
            return 1

    # 2-D spot check
    a = (np.random.default_rng(0).standard_normal((ndev * 64, 128))
         + 0j).astype(np.complex64)
    xa = jax.device_put(jnp.asarray(a), NamedSharding(mesh, P("x", None)))
    ya = dfft.fft2_sharded(xa, mesh)
    rel2 = (np.linalg.norm(np.asarray(ya) - np.fft.fft2(a))
            / np.linalg.norm(np.fft.fft2(a)))
    print(f"  fft2 {a.shape}: rel err {rel2:.2e}")
    return 0 if rel2 < 1e-3 else 1


if __name__ == "__main__":
    sys.exit(main())
