"""Distributed matrix transpose — phase-based collectives demo.

Reference analog: examples/transpose/transpose_block.cpp (block
transpose where every locality exchanges tiles with every other —
the all_to_all communication pattern).

TPU-first: the matrix is row-sharded over the mesh; the transpose is
ONE sharded XLA program — `lax.all_to_all` inside shard_map exchanges
tiles over ICI, then each shard transposes its received tiles locally.
Compare with the reference's N² explicit parcels.

Usage: python examples/transpose.py [n]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from hpx_tpu.utils.jaxcompat import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.parallel import make_mesh, shard_1d  # noqa: E402


def main() -> int:
    import jax
    ndev = len(jax.devices())
    n = int(argv[0]) if argv else 1024
    n -= n % ndev                     # divisible rows/cols
    mesh = make_mesh((ndev,), ("x",))

    a = jnp.asarray(np.random.default_rng(0).random((n, n), np.float32))
    a = jax.device_put(a, jax.sharding.NamedSharding(mesh, P("x", None)))

    def body(blk):                    # blk: (n/ndev, n) local rows
        # split my rows into ndev column-tiles, trade tile j to device j
        tiles = blk.reshape(blk.shape[0], ndev, n // ndev)
        tiles = jnp.moveaxis(tiles, 1, 0)           # (ndev, rows, cols)
        recv = jax.lax.all_to_all(tiles, "x", 0, 0, tiled=False)
        # recv[j] = tile from device j: my columns of their rows
        return jnp.concatenate(
            [r.T for r in recv], axis=1)            # (n/ndev, n)

    tr = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("x", None),),
                           out_specs=P("x", None)))

    t = hpx.HighResolutionTimer()
    at = tr(a)
    at.block_until_ready()
    dt = t.elapsed()

    np.testing.assert_allclose(np.asarray(at), np.asarray(a).T, rtol=1e-6)
    gbs = 2 * n * n * 4 / dt / 1e9
    print(f"transpose {n}x{n} over {ndev} devices: "
          f"{dt * 1e3:.2f} ms ({gbs:.1f} GB/s effective)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
