"""Pipeline-parallel training two ways.

Reference analog: HPX expresses pipelines as dataflow chains with
channel handoff (SURVEY.md §2.9 PP row). This demo trains the same
tiny transformer with BOTH TPU-native forms and checks they agree:

  1. host-driven (parallel/pipeline.py): each stage is its own jitted
     program on its own device; XLA async dispatch overlaps stages —
     the futures ARE the schedule;
  2. in-jit SPMD (parallel/pipeline_spmd.py via
     models/transformer.make_pipelined_train_step): layers stacked
     over the "pp" mesh axis, one ppermute hop per scan step, backward
     is AD through the scan.

Usage: python examples/pipeline_train.py [steps] [--cpu-mesh N]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import hpx_tpu.models.transformer as tfm  # noqa: E402


def main() -> int:
    steps = int(argv[0]) if argv else 6
    devs = jax.devices()
    ndev = len(devs)
    pp = 4 if ndev % 4 == 0 else (2 if ndev % 2 == 0 else 1)
    dp = 2 if (ndev // pp) % 2 == 0 else 1
    mesh = Mesh(np.array(devs[:dp * pp]).reshape(dp, pp), ("dp", "pp"))

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2 * pp, d_ff=64,
                                lr=0.05)
    toks, tgts = tfm.sample_batch(cfg, batch=4 * dp, seq=16,
                                  key=jax.random.PRNGKey(1))

    # -- in-jit SPMD pipeline -------------------------------------------
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    stacked = tfm.shard_pipeline_params(
        tfm.stack_pipeline_params(params), mesh)
    step = tfm.make_pipelined_train_step(cfg, mesh, n_microbatches=2)
    sh = NamedSharding(mesh, P("dp", None))
    t, g = jax.device_put(toks, sh), jax.device_put(tgts, sh)
    losses = []
    for _ in range(steps):
        stacked, loss = step(stacked, t, g)
        losses.append(float(loss))
    # loss AT the final params (each step reports pre-update loss)
    _ignored, final_loss = step(stacked, t, g)
    final_loss = float(final_loss)
    print(f"in-jit pp (dp={dp}, pp={pp}, M=2): "
          f"{losses[0]:.4f} -> {final_loss:.4f}")

    # -- host-driven pipeline (inference of the trained model) ----------
    from hpx_tpu.parallel.pipeline import Pipeline

    # stage s = layers [s*2, s*2+2); embed/head folded into first/last
    host_params = jax.device_get(stacked)

    def mk_stage(lo, hi, first, last):
        def fn(sp, x):
            if first:
                x = sp["emb"][x.astype(jnp.int32)]
            for i in range(hi - lo):
                lp = jax.tree.map(lambda a, i=i: a[i], sp["layers"])
                x = tfm._pp_block(x, lp, cfg, None)
            if last:
                x = tfm._ln(x, sp["ln_f"])
                x = jnp.einsum("bsd,vd->bsv", x, sp["emb"])
            return x
        return fn

    per = cfg.n_layers // pp
    stage_defs = []
    for s in range(pp):
        sp = {"layers": jax.tree.map(
            lambda a, s=s: a[s * per:(s + 1) * per], host_params["layers"])}
        if s == 0:
            sp["emb"] = host_params["emb"]
        if s == pp - 1:
            sp["emb"] = host_params["emb"]
            sp["ln_f"] = host_params["ln_f"]
        stage_defs.append((mk_stage(s * per, (s + 1) * per, s == 0,
                                    s == pp - 1), sp))
    pipe = Pipeline(stage_defs, devices=devs[:pp])
    mbs = [toks[i:i + 2] for i in range(0, toks.shape[0], 2)]
    outs = pipe.forward(mbs)
    logits = jnp.concatenate([jnp.asarray(o) for o in outs])

    # cross-check: host pipeline logits match a direct forward
    nll = -jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    ce = float(jnp.take_along_axis(nll, tgts[..., None], -1).mean())
    print(f"host pipeline CE of trained model: {ce:.4f} "
          f"(in-jit loss at same params {final_loss:.4f})")
    ok = final_loss < losses[0] and abs(ce - final_loss) < 1e-3
    print("OK" if ok else "MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
