"""Serving walkthrough: train briefly, then serve every way the
framework can.

Exercises the whole serving surface on one tiny GQA+RoPE model:
  1. greedy decode (KV caches hold only the grouped kv heads);
  2. sampled decode (temperature/top_k; keys fold global row+position);
  3. eos-pinned decode;
  4. int8 weight-only quantized decode (models/quant.py);
  5. speculative decoding (a briefly-trained 1-layer draft; SAME
     tokens as greedy by construction — return_stats counts the
     verification rounds, which shrink as the draft gets better at
     agreeing with the target);
  6. sharded decode over a Mesh(dp, tp) — bit-matched against (1);
  7. continuous batching: mixed-length requests through decode slots,
     each result identical to its solo greedy run.

Usage: python examples/serving_demo.py [--cpu-mesh N]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import hpx_tpu.models.transformer as tfm  # noqa: E402
from hpx_tpu.models import quant  # noqa: E402


def main() -> int:
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=64,
                                n_kv_heads=2, rope=True, lr=0.05)
    mesh1 = tfm.make_mesh_3d(1)
    params = tfm.shard_params(tfm.init_params(cfg, jax.random.PRNGKey(0)),
                              cfg, mesh1)
    step = tfm.make_train_step(cfg, mesh1)
    toks, tgts = tfm.sample_batch(cfg, batch=8, seq=24,
                                  key=jax.random.PRNGKey(1))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh1)
    for i in range(20):
        params, loss = step(params, toks, tgts)
    print(f"trained 20 steps, loss {float(loss):.3f}")
    host = jax.device_get(params)

    prompt = jnp.array([[3, 1, 4, 1], [2, 7, 1, 8]], jnp.int32)
    greedy = tfm.generate(host, cfg, prompt, max_new=10)
    print("greedy    :", np.asarray(greedy).tolist())

    sampled = tfm.generate(host, cfg, prompt, max_new=10,
                           temperature=0.8, top_k=8,
                           key=jax.random.PRNGKey(2))
    print("sampled   :", np.asarray(sampled).tolist())

    eos = int(np.asarray(greedy)[0, 3])
    pinned = tfm.generate(host, cfg, prompt, max_new=10, eos_id=eos)
    print(f"eos={eos}  :", np.asarray(pinned).tolist())

    qp = quant.quantize_params(host)
    qout = tfm.generate(qp, cfg, prompt, max_new=10)
    shrink = (quant.quantized_bytes(host["layers"])
              / quant.quantized_bytes(qp["layers"]))
    agree = float((np.asarray(qout) == np.asarray(greedy)).mean())
    print(f"int8      : {np.asarray(qout).tolist()} "
          f"(weights {shrink:.1f}x smaller, {agree:.0%} token agreement)")

    draft_cfg = tfm.TransformerConfig(vocab=64, d_model=16, n_heads=2,
                                      head_dim=8, n_layers=1, d_ff=32,
                                      rope=True)
    dparams = tfm.shard_params(tfm.init_params(draft_cfg,
                                               jax.random.PRNGKey(3)),
                               draft_cfg, mesh1)
    dstep = tfm.make_train_step(draft_cfg, mesh1)
    for _ in range(20):      # same data: the draft learns to agree
        dparams, _ = dstep(dparams, toks, tgts)
    draft = jax.device_get(dparams)
    spec, rounds = tfm.speculative_generate(
        host, cfg, draft, draft_cfg, prompt, max_new=10, k=3,
        return_stats=True)
    # compare by agreement rate, not hard equality: a float argmax tie
    # (window vs sequential forwards reassociate sums) may flip a token
    # legitimately — the unit tests pin exactness on tie-free seeds
    sagree = float((np.asarray(spec) == np.asarray(greedy)).mean())
    print(f"speculative: {np.asarray(spec).tolist()} "
          f"({int(rounds)} verification rounds for 10 tokens, "
          f"{sagree:.0%} token agreement)")

    ok = sagree >= 0.8
    ndev = len(jax.devices())
    if ndev >= 4:
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "tp"))
        sharded = tfm.generate(tfm.shard_params(host, cfg, mesh), cfg,
                               prompt, max_new=10, mesh=mesh)
        match = np.array_equal(np.asarray(sharded), np.asarray(greedy))
        print(f"sharded dp2/tp2: bit-match={match}")
        ok = ok and match
        # int8 + tp: scales shard with their channels (quant.
        # shard_quantized); output bit-matches single-device int8
        qsharded = tfm.generate(quant.shard_quantized(qp, cfg, mesh),
                                cfg, prompt, max_new=10, mesh=mesh)
        qmatch = np.array_equal(np.asarray(qsharded), np.asarray(qout))
        print(f"int8 sharded dp2/tp2: bit-match={qmatch}")
        ok = ok and qmatch

    from hpx_tpu.models.serving import ContinuousServer
    srv = ContinuousServer(host, cfg, slots=2, smax=32)
    reqs = {srv.submit([3, 1, 4, 1], max_new=6): [3, 1, 4, 1],
            srv.submit([2, 7], max_new=9): [2, 7],
            srv.submit([5, 5, 5], max_new=4): [5, 5, 5]}
    served = srv.run()
    cb_ok = all(
        served[rid] == np.asarray(tfm.generate(
            host, cfg, jnp.asarray([p], jnp.int32),
            max_new=len(served[rid])))[0].tolist()
        for rid, p in reqs.items())
    print(f"continuous batching: 3 requests / 2 slots, "
          f"all == solo greedy: {cb_ok}")
    ok = ok and cb_ok

    hits = np.where(np.asarray(pinned)[0] == eos)[0]
    ok = ok and hits.size > 0 and \
        (np.asarray(pinned)[0, hits[0]:] == eos).all()
    print("OK" if ok else "MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
