"""Accumulator component — the classic HPX components tutorial.

Reference analog: examples/accumulators/ (a server component with
add/query actions, a client_base wrapper, creation on a chosen
locality, access from anywhere by symbolic name).

Single process:  python examples/accumulator.py
Multi-locality:  python -m hpx_tpu.run examples/accumulator.py -l 2
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

setup_platform()

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.svc.iostreams import cout  # noqa: E402


@hpx.register_component_type
class Accumulator(hpx.Component):
    def __init__(self) -> None:
        self.value = 0

    def reset(self) -> None:
        self.value = 0

    def add(self, n: int) -> None:
        self.value += n

    def query(self) -> int:
        return self.value


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    n = hpx.get_num_localities()

    if here == 0:
        # create on the LAST locality (remote when n > 1)
        acc = hpx.new_(Accumulator, n - 1).get()
        hpx.register_with_basename("example/accumulator", acc).get()
        for i in range(1, 11):
            acc.add(i).get()
        cout.println(f"accumulator lives on locality "
                     f"{acc.where().get()}; sum(1..10) = "
                     f"{acc.sync('query')}")
    if n > 1:
        hpx.get_runtime().barrier("acc-created")
        if here != 0:
            acc = hpx.find_from_basename("example/accumulator").get()
            acc.add(1000 * here).get()
        hpx.get_runtime().barrier("acc-added")
        if here == 0:
            total = acc.sync("query")
            expect = 55 + sum(1000 * i for i in range(1, n))
            cout.println(f"after remote adds: {total} (expect {expect})")
            assert total == expect
        hpx.get_runtime().barrier("acc-done")
    cout.flush().get()
    hpx.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
