"""Checkpointed 1d_stencil: save a RUNNING dataflow mid-flight, kill
the state, restore, and finish — bit-identical to an uninterrupted run.

Reference analog: the checkpoint examples of libs/full/checkpoint
(save_checkpoint over a pack of futures — the 1d_stencil_4 DAG's
partition futures are exactly such a pack; SURVEY.md §2.6/§5.4).

Flow:
  1. run the dataflow DAG for nt/2 timesteps
  2. save_checkpoint(*partition_futures) -> file  (futures are awaited,
     their VALUES serialized — the in-flight DAG drains into the save)
  3. throw everything away ("failure")
  4. restore_checkpoint_from_file -> partition values, re-wrap as ready
     futures, run the REMAINING nt/2 steps
  5. compare against an uninterrupted nt-step run

Usage: python examples/checkpointed_stencil.py [nx_per_part] [np] [nt]
"""

import os
import sys
import tempfile

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import numpy as np  # noqa: E402

from hpx_tpu.futures.future import make_ready_future  # noqa: E402
from hpx_tpu.models.stencil1d import (  # noqa: E402
    StencilParams, gather_dataflow_result, init_domain, stencil_dataflow)
from hpx_tpu.svc.checkpoint import (  # noqa: E402
    restore_checkpoint_from_file, save_checkpoint_to_file)


def main() -> int:
    nx = int(argv[0]) if argv else 256
    np_ = int(argv[1]) if len(argv) > 1 else 4
    nt = int(argv[2]) if len(argv) > 2 else 32
    assert nt % 2 == 0
    u0 = init_domain(StencilParams(nx=nx, np_=np_, nt=nt))

    # uninterrupted oracle
    oracle = gather_dataflow_result(stencil_dataflow(
        StencilParams(nx=nx, np_=np_, nt=nt), u0=u0))

    # ---- first half, then checkpoint the LIVE future pack -------------
    half = StencilParams(nx=nx, np_=np_, nt=nt // 2)
    futs = stencil_dataflow(half, u0=u0)
    path = os.path.join(tempfile.mkdtemp(), "stencil.ckpt")
    save_checkpoint_to_file(path, *futs).get()
    print(f"checkpointed {np_} partitions mid-run -> {path} "
          f"({os.path.getsize(path)} bytes)")

    # ---- simulated failure: drop every future ------------------------
    del futs

    # ---- restore and finish ------------------------------------------
    parts = restore_checkpoint_from_file(path)
    resumed = [make_ready_future(x) for x in parts]
    final = stencil_dataflow(half, u0=gather_dataflow_result(resumed))
    got = gather_dataflow_result(final)

    np.testing.assert_allclose(np.asarray(got), np.asarray(oracle),
                               rtol=1e-6, atol=1e-6)
    print(f"restored + finished: {nt // 2}+{nt // 2} steps == "
          f"{nt} uninterrupted steps (nx={nx * np_}) ✓")
    return 0


if __name__ == "__main__":
    sys.exit(main())
