"""Distributed 1d_stencil — the 1d_stencil_8 analog.

Reference analog: examples/1d_stencil/1d_stencil_8.cpp — each locality
owns a contiguous slab of the domain; per-step halo cells cross
locality boundaries through channels (hpx::distributed::channel /
receive_buffer pattern, SURVEY.md §3.5, §5.7).

Control-plane channels carry the one-cell halos between processes;
each locality's slab update is a jitted kernel. (On a real pod the
halo would ride ICI via ppermute — parallel/halo.py — this example
exercises the cross-PROCESS path the reference ships.)

Run: python -m hpx_tpu.run -l 3 examples/1d_stencil_distributed.py
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import numpy as np  # noqa: E402

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.svc.iostreams import cout  # noqa: E402

NX = 64          # cells per locality
NT = 20          # time steps
COEF = 0.25


def main() -> int:
    import jax
    import jax.numpy as jnp

    hpx.init()
    here = hpx.find_here()
    nloc = hpx.get_num_localities()
    comm = hpx.create_channel_communicator("stencil8", nloc)

    @jax.jit
    def update(left_ghost, slab, right_ghost):
        ext = jnp.concatenate([left_ghost, slab, right_ghost])
        return ext[1:-1] + COEF * (ext[:-2] - 2.0 * ext[1:-1] + ext[2:])

    # global domain u[i] = i (periodic); my slab:
    base = here * NX
    u = jnp.arange(base, base + NX, dtype=jnp.float32)

    left = (here - 1) % nloc
    right = (here + 1) % nloc
    for t in range(NT):
        # send boundary cells (tag = timestep — the receive_buffer
        # indexed-step pattern); then wait for the neighbors'
        comm.set(left, np.asarray(u[:1]), tag=2 * t)       # to left's right
        comm.set(right, np.asarray(u[-1:]), tag=2 * t + 1)  # to right's left
        lg = jnp.asarray(comm.get(left, tag=2 * t + 1).get())
        rg = jnp.asarray(comm.get(right, tag=2 * t).get())
        u = update(lg, u, rg)

    # verify against the serial whole-domain run on locality 0
    total = np.asarray(u)
    gathered = hpx.collectives.gather(
        hpx.create_communicator("stencil8-done", nloc), total).get()
    if here == 0:
        full = np.concatenate(gathered)
        ref = np.arange(nloc * NX, dtype=np.float32)
        for _ in range(NT):
            ref = ref + COEF * (np.roll(ref, 1) - 2 * ref
                                + np.roll(ref, -1))
        np.testing.assert_allclose(full, ref, rtol=1e-5, atol=1e-5)
        cout.println(f"1d_stencil_distributed: {nloc} localities x {NX} "
                     f"cells, {NT} steps — matches serial")
        cout.flush().get()
    hpx.get_runtime().barrier("stencil8-exit")
    hpx.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
