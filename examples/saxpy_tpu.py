"""SAXPY + dot via transform_reduce on the TPU executor — config #1.

Reference analog: hpx::transform_reduce with execution::par
(libs/core/algorithms), the north-star spelling:
`par.on(tpu_executor())` reroutes the whole algorithm to one fused XLA
program (SURVEY.md §3.3 TPU note).

Usage: python examples/saxpy_tpu.py [log2_n]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import hpx_tpu as hpx  # noqa: E402


def main() -> int:
    log2n = int(argv[0]) if argv else 22
    n = 1 << log2n
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random(n, np.float32))
    y = jnp.asarray(rng.random(n, np.float32))
    a = jnp.float32(2.5)

    policy = hpx.par.on(hpx.tpu_executor())

    # z = a*x + y (transform), then dot(z, x) (transform_reduce) — the
    # composed saxpy+dot of BASELINE config #1
    z = hpx.transform(policy, x, lambda xi: a * xi)     # scale
    z = hpx.transform(policy, z, jnp.add, rng2=y)       # + y
    dot = hpx.transform_reduce(policy, z, jnp.float32(0.0), jnp.add,
                               jnp.multiply, rng2=x)

    t = hpx.HighResolutionTimer()
    reps = 10
    for _ in range(reps):
        z = hpx.transform(policy, z, jnp.add, rng2=y)
    _ = float(z[0])
    per = t.elapsed() / reps
    gbs = 3 * n * 4 / per / 1e9

    want = float(np.dot(np.asarray(z) - reps * np.asarray(y),
                        np.asarray(x)))
    print(f"n = {n}: dot(saxpy) = {float(dot):.2f} "
          f"(check offset vs final z: {want:.2f})")
    print(f"streaming add: {gbs:.1f} GB/s effective")
    return 0


if __name__ == "__main__":
    sys.exit(main())
