"""Tree-shaped host collectives — hpx::collectives::communication_set.

Reference analog: libs/full/collectives' communication_set arranges
communicators in an arity-A tree so large-site-count collectives don't
funnel through one root (SURVEY.md §2.4 collectives row; the flat
Communicator in collectives/communicator.py is a documented O(P) star
fan-in — correct at 8 sites, the wrong shape at 64+).

Composition, not reimplementation: a CommunicationSet is a tree of
ordinary Communicators. Sites 0..N-1 split into ceil(N/A) groups of at
most A; each group gets a leaf communicator whose root-side exchange
state lives on the GROUP ROOT's locality (so fan-in load spreads across
localities), and group roots recurse into a smaller CommunicationSet
(or a single Communicator at the top). Results flow back down with a
per-group broadcast. Like HPX's communication_set, the tree supports
the fold-able subset of verbs — all_reduce, reduce, broadcast,
barrier — the full verb set stays on the flat Communicator.

Stages chain through Future.then (future<future> unwraps), so nothing
blocks a thread between levels.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Optional

from ..futures.future import Future
from . import communicator as _flat

__all__ = ["CommunicationSet", "create_communication_set"]


class CommunicationSet:
    """Arity-A collective tree over num_sites sites.

    site_locality maps a site index to the locality hosting it
    (identity by default — the common one-site-per-locality layout);
    leaf exchange state is placed on each group root's locality.
    """

    def __init__(self, basename: str, num_sites: int, this_site: int,
                 arity: int = 8,
                 site_locality: Optional[Callable[[int], int]] = None ) -> None:
        if num_sites < 1 or not (0 <= this_site < num_sites):
            raise ValueError(f"bad site {this_site}/{num_sites}")
        if arity < 2:
            raise ValueError("arity must be >= 2")
        self.basename = basename
        self.num_sites = num_sites
        self.this_site = this_site
        self.arity = arity
        loc = site_locality or (lambda s: s)
        self._site_locality = loc

        group = this_site // arity
        base = group * arity
        group_size = min(arity, num_sites - base)
        self._group_root_site = base
        self._is_group_root = this_site == base
        self._leaf = _flat.Communicator(
            f"{basename}/leaf/{group}", num_sites=group_size,
            this_site=this_site - base,
            root_locality=loc(base))

        n_groups = -(-num_sites // arity)
        # _has_upper: the TREE has more levels (true for every member of
        # a multi-group set); _upper: only group roots hold the handle
        self._has_upper = n_groups > 1
        self._upper: Any = None
        if n_groups > 1 and self._is_group_root:
            if n_groups <= arity:
                self._upper = _flat.Communicator(
                    f"{basename}/top", num_sites=n_groups,
                    this_site=group, root_locality=loc(0))
            else:
                self._upper = CommunicationSet(
                    f"{basename}/up", n_groups, group, arity,
                    site_locality=lambda g: loc(g * arity))

    # -- verbs ---------------------------------------------------------------
    def all_reduce(self, value: Any,
                   op: Callable = operator.add) -> Future:
        """Every site gets the op-fold of all sites' contributions."""
        local = _flat.all_reduce(self._leaf, value, op=op)
        if not self._has_upper:
            return local
        if self._is_group_root:
            up = local.then(lambda f: _all_reduce_any(
                self._upper, f.get(), op))
            return up.then(
                lambda f: _flat.broadcast(self._leaf, f.get(), root=0))
        # non-root member: contribute, then receive the group broadcast
        return local.then(
            lambda _f: _flat.broadcast(self._leaf, None, root=0))

    def reduce(self, value: Any, op: Callable = operator.add) -> Future:
        """Site 0 gets the fold; every other site gets None."""
        def pick(f):
            return f.get() if self.this_site == 0 else None
        return self.all_reduce(value, op=op).then(pick)

    def broadcast(self, value: Any = None) -> Future:
        """Every site gets site 0's value."""
        return self.all_reduce(_Tagged(self.this_site, value),
                               op=_keep_lowest).then(
            lambda f: f.get().value)

    def barrier(self) -> Future:
        # module-level op, NOT a lambda: contributions travel in parcels
        # when the leaf root is remote, and lambdas don't pickle
        return self.all_reduce(None, op=_none_op)


class _Tagged:
    __slots__ = ("site", "value")

    def __init__(self, site: int, value: Any) -> None:
        self.site = site
        self.value = value


def _keep_lowest(a: "_Tagged", b: "_Tagged") -> "_Tagged":
    return a if a.site <= b.site else b


def _none_op(a: Any, b: Any) -> None:
    return None


def _all_reduce_any(comm: Any, value: Any, op: Callable) -> Future:
    if isinstance(comm, CommunicationSet):
        return comm.all_reduce(value, op=op)
    return _flat.all_reduce(comm, value, op=op)


def create_communication_set(basename: str, num_sites: Optional[int] = None,
                             this_site: Optional[int] = None,
                             arity: int = 8,
                             site_locality: Optional[Callable[[int], int]]
                             = None) -> CommunicationSet:
    """hpx::collectives::create_communication_set analog."""
    from ..dist.runtime import find_here, get_num_localities
    return CommunicationSet(
        basename,
        num_sites if num_sites is not None else get_num_localities(),
        this_site if this_site is not None else find_here(),
        arity=arity, site_locality=site_locality)
