"""Collectives (libs/full/collectives analog), two planes:

  * host/control plane (communicator.py, channels.py): futures-based, any
    payload, HPX's exact API and semantics;
  * device/data plane (device.py): the same verbs compiled to XLA
    collectives over ICI inside shard_map.
"""

from .comm_set import (  # noqa: F401
    CommunicationSet,
    create_communication_set,
)
from .communicator import (  # noqa: F401
    Communicator,
    create_communicator,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    exclusive_scan,
    gather,
    inclusive_scan,
    reduce,
    scatter,
)
from .channels import (  # noqa: F401
    ChannelCommunicator,
    DistributedChannel,
    DistributedLatch,
    create_channel_communicator,
)
from . import device  # noqa: F401
