"""Point-to-point channels over the distributed runtime.

Reference analogs:
  * channel_communicator (libs/full/collectives/.../channel_communicator.hpp):
    p2p set/get between sites of a communicator, FIFO per (from, to) pair;
  * hpx::distributed::channel (libs/full/lcos_distributed): a named
    channel COMPONENT hosted on one locality, accessed from anywhere;
  * hpx::distributed::latch (libs/full/collectives/latch.hpp).

TPU-first shape: channel state lives on a hosting locality (root for the
channel_communicator, the creating locality for distributed::channel) as
plain lcos Channel objects; set/get travel as actions and return futures.
This is control-plane messaging — bulk arrays should ride device.py
collectives instead (SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..dist.actions import async_action, plain_action
from ..dist.runtime import find_here, get_num_localities
from ..futures.future import Future, SharedState

# ---------------------------------------------------------------------------
# Mailbox state (hosted on the root/hosting locality): one local lcos
# Channel per key — the same FIFO value/getter pairing, one implementation.
# ---------------------------------------------------------------------------

from ..lcos.local import Channel as _LocalChannel
from ..synchronization import Mutex

_lock = Mutex()
_mailboxes: Dict[Tuple, _LocalChannel] = {}


def _mailbox(key: Tuple) -> _LocalChannel:
    with _lock:
        return _mailboxes.setdefault(key, _LocalChannel())


# Per-sender sequence reordering: two un-awaited set() calls race through
# the work-stealing pool (or the parcel decode path), so arrival order is
# not send order. Each sender stamps a monotonic seq; the host applies a
# sender's stream to the mailbox strictly in seq order, buffering gaps.
_ord_lock = Mutex()
_ordered: Dict[Tuple, list] = {}  # (key, sender) -> [next_seq, {seq: value}]


@plain_action(name="channels.put_ordered")
def _put_ordered_action(key: Tuple, sender: Tuple, seq: int,
                        value: Any) -> bool:
    with _ord_lock:
        st = _ordered.setdefault((key, sender), [0, {}])
        st[1][seq] = value
        # delivery stays under the lock: releasing between pops would let
        # two callers interleave their mailbox.set calls out of order
        while st[0] in st[1]:
            _mailbox(key).set(st[1].pop(st[0]))
            st[0] += 1
    return True


# Receive-side ordering: the same pool-reordering hazard exists for two
# un-awaited get() futures, so get requests are seq-stamped per receiver
# and the host pairs them with the mailbox strictly in seq order.
_get_ord: Dict[Tuple, list] = {}  # (key, getter) -> [next_seq, {seq: state}]


@plain_action(name="channels.get_ordered")
def _get_ordered_action(key: Tuple, getter: Tuple, seq: int) -> Future:
    st: SharedState = SharedState()
    issued = []
    with _ord_lock:
        state = _get_ord.setdefault((key, getter), [0, {}])
        state[1][seq] = st
        while state[0] in state[1]:
            # hpxlint: disable-next=HPX001 — Channel.get() is
            # non-blocking: it returns a Future immediately (pairing it
            # with the waiter happens after unlock via set_value below)
            issued.append((_mailbox(key).get(), state[1].pop(state[0])))
            state[0] += 1
    for src, dst in issued:
        dst.set_value(src)   # SharedState adopts the future's outcome
    return Future(st)


@plain_action(name="channels.drop")
def _drop_action(key: Tuple) -> bool:
    from ..core.errors import Error, HpxError
    with _lock:
        mb = _mailboxes.pop(key, None)
    orphans = []
    with _ord_lock:
        for k in [k for k in _ordered if k[0] == key]:
            del _ordered[k]
        for k in [k for k in _get_ord if k[0] == key]:
            orphans.extend(_get_ord.pop(k)[1].values())
    if mb is not None:
        mb.close()  # fails pending getters with 'channel is closed'
    for st in orphans:  # gap-buffered get requests never paired
        st.set_exception(HpxError(Error.invalid_status, "channel is closed"))
    return True


@plain_action(name="channels.drop_peer")
def _drop_peer_action(token: Tuple) -> bool:
    """Drop the per-sender/per-getter reorder state of a closed peer;
    gap-buffered get requests fail rather than hang."""
    from ..core.errors import Error, HpxError
    orphans = []
    with _ord_lock:
        for k in [k for k in _ordered if k[1] == token]:
            del _ordered[k]
        for k in [k for k in _get_ord if k[1] == token]:
            orphans.extend(_get_ord.pop(k)[1].values())
    for st in orphans:
        st.set_exception(HpxError(Error.invalid_status, "peer closed"))
    return True


# Peer tokens must be unique for the life of the HOST's reorder state:
# id(self) can be reused after GC, which would resume a dead sender's seq
# numbering and stall delivery forever. A process-unique counter cannot.
import itertools as _itertools

_peer_counter = _itertools.count()


def _peer_token() -> Tuple:
    return (find_here(), next(_peer_counter))


# ---------------------------------------------------------------------------
# channel_communicator
# ---------------------------------------------------------------------------

class ChannelCommunicator:
    """hpx::collectives::channel_communicator analog.

    set(to, value) / get(from) between sites; FIFO per directed pair.
    All mailboxes live on the root locality (the component host in HPX).
    """

    def __init__(self, basename: str, num_sites: Optional[int] = None,
                 this_site: Optional[int] = None,
                 root_locality: int = 0) -> None:
        self.basename = basename
        self.num_sites = (num_sites if num_sites is not None
                          else get_num_localities())
        self.this_site = (this_site if this_site is not None
                          else find_here())
        self.root_locality = root_locality
        # peer token unique to this communicator instance; seq counters
        # per (to, tag) give FIFO per directed pair from this instance
        self._sender = _peer_token()
        self._seq: Dict[Tuple, int] = {}
        self._seq_lock = Mutex()

    def _key(self, frm: int, to: int, tag: Optional[int]) -> Tuple:
        return ("chan_comm", self.basename, frm, to, tag)

    def set(self, to: int, value: Any, tag: Optional[int] = None) -> Future:
        self._check_open()
        if not 0 <= to < self.num_sites:
            raise IndexError(to)
        key = self._key(self.this_site, to, tag)
        with self._seq_lock:
            seq = self._seq.get(key, 0)
            self._seq[key] = seq + 1
        return async_action(_put_ordered_action, self.root_locality,
                            key, self._sender, seq, value)

    def get(self, frm: int, tag: Optional[int] = None) -> Future:
        self._check_open()
        if not 0 <= frm < self.num_sites:
            raise IndexError(frm)
        key = self._key(frm, self.this_site, tag)
        with self._seq_lock:
            seq = self._seq.get(("get", key), 0)
            self._seq[("get", key)] = seq + 1
        return async_action(_get_ordered_action, self.root_locality,
                            key, self._sender, seq)

    def close(self) -> None:
        """Release this instance's reorder state on the host and
        invalidate the instance (further set/get raise): reusing the seq
        counters after the host state is gone would stall delivery."""
        self._closed = True
        async_action(_drop_peer_action, self.root_locality,
                     self._sender).get()

    def _check_open(self) -> None:
        if getattr(self, "_closed", False):
            from ..core.errors import Error, HpxError
            raise HpxError(Error.invalid_status,
                           "channel_communicator is closed")

    def __enter__(self) -> "ChannelCommunicator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def create_channel_communicator(basename: str,
                                num_sites: Optional[int] = None,
                                this_site: Optional[int] = None,
                                root_locality: int = 0
                                ) -> ChannelCommunicator:
    return ChannelCommunicator(basename, num_sites, this_site, root_locality)


# ---------------------------------------------------------------------------
# hpx::distributed::channel — a named channel hosted where it was created
# ---------------------------------------------------------------------------

class DistributedChannel:
    """Named cross-locality channel (lcos_distributed analog).

    The creator hosts the state and registers `(name -> (host locality,
    incarnation))` in AGAS; `connect` resolves both and routes set/get
    there. The incarnation number makes each create() a fresh mailbox
    key, so handles of an unregistered previous incarnation can never
    poison (or read from) a recreated channel of the same name.
    """

    def __init__(self, name: str, host_locality: int,
                 incarnation: int) -> None:
        self.name = name
        self.host_locality = host_locality
        self.incarnation = incarnation
        self._sender = _peer_token()
        self._next_seq = 0
        self._next_get_seq = 0
        self._seq_lock = Mutex()

    @classmethod
    def create(cls, name: str) -> "DistributedChannel":
        from ..dist import agas
        here = find_here()
        inc = next(_peer_counter)
        ok = agas.register_name(f"dchannel/{name}", (here, inc)).get()
        if not ok:
            raise ValueError(f"channel name already registered: {name}")
        return cls(name, here, inc)

    @classmethod
    def connect(cls, name: str) -> "DistributedChannel":
        from ..dist import agas
        host, inc = agas.resolve_name(f"dchannel/{name}", wait=True).get()
        return cls(name, host, inc)

    def _key(self) -> Tuple:
        return ("dchannel", self.name, self.incarnation)

    def set(self, value: Any) -> Future:
        with self._seq_lock:
            seq = self._next_seq
            self._next_seq = seq + 1
        return async_action(_put_ordered_action, self.host_locality,
                            self._key(), self._sender, seq, value)

    def get(self) -> Future:
        with self._seq_lock:
            seq = self._next_get_seq
            self._next_get_seq = seq + 1
        return async_action(_get_ordered_action, self.host_locality,
                            self._key(), self._sender, seq)

    def unregister(self) -> None:
        """Remove the AGAS name AND the hosted mailbox — a channel
        re-created under the same name starts empty."""
        from ..dist import agas
        agas.unregister_name(f"dchannel/{self.name}").get()
        async_action(_drop_action, self.host_locality, self._key()).get()


# ---------------------------------------------------------------------------
# hpx::distributed::latch
# ---------------------------------------------------------------------------

_latch_lock = Mutex()
_latches: Dict[str, list] = {}  # name -> [arrived, released, [SharedStates]]


@plain_action(name="channels.latch_arrive")
def _latch_arrive(name: str, count: int, n: int, wait: bool):
    """Hosted on root: accumulate arrivals; with wait, future released
    once arrivals reach the threshold.

    Arrival-count semantics (not remaining-count) make the exchange
    order-independent: actions from concurrent localities — or from one
    caller, reordered by the task pool — commute, and a wait landing
    after release completes immediately. One-shot per name, matching
    std::latch / hpx::distributed::latch."""
    st = SharedState() if wait else None
    released = None
    with _latch_lock:
        state = _latches.setdefault(name, [0, False, []])
        state[0] += count
        already_released = state[1]
        if st is not None and not already_released:
            state[2].append(st)
        if not state[1] and state[0] >= n:
            state[1] = True
            released = state[2]
            state[2] = []
    if released is not None:
        for w in released:
            w.set_value(True)
    if st is not None and already_released:
        st.set_value(True)
    if st is None:
        return True
    return Future(st)


class DistributedLatch:
    """hpx::distributed::latch: created with a threshold, counted down
    from any locality; wait() completes when the count reaches zero.
    One-shot per name (as std::latch is per instance)."""

    def __init__(self, name: str, count: int,
                 root_locality: int = 0) -> None:
        self.name = name
        self.count = count
        self.root_locality = root_locality

    def count_down(self, n: int = 1) -> Future:
        return async_action(_latch_arrive, self.root_locality,
                            self.name, n, self.count, False)

    def arrive_and_wait(self, n: int = 1) -> Future:
        return async_action(_latch_arrive, self.root_locality,
                            self.name, n, self.count, True)

    def wait(self) -> Future:
        return async_action(_latch_arrive, self.root_locality,
                            self.name, 0, self.count, True)
