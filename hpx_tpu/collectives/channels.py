"""Point-to-point channels over the distributed runtime.

Reference analogs:
  * channel_communicator (libs/full/collectives/.../channel_communicator.hpp):
    p2p set/get between sites of a communicator, FIFO per (from, to) pair;
  * hpx::distributed::channel (libs/full/lcos_distributed): a named
    channel COMPONENT hosted on one locality, accessed from anywhere;
  * hpx::distributed::latch (libs/full/collectives/latch.hpp).

TPU-first shape: channel state lives on a hosting locality (root for the
channel_communicator, the creating locality for distributed::channel) as
plain lcos Channel objects; set/get travel as actions and return futures.
This is control-plane messaging — bulk arrays should ride device.py
collectives instead (SURVEY.md §5.8).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ..dist.actions import async_action, plain_action
from ..dist.runtime import find_here, get_num_localities
from ..futures.future import Future, SharedState

# ---------------------------------------------------------------------------
# Mailbox state (hosted on the root/hosting locality): one local lcos
# Channel per key — the same FIFO value/getter pairing, one implementation.
# ---------------------------------------------------------------------------

from ..lcos.local import Channel as _LocalChannel

_lock = threading.Lock()
_mailboxes: Dict[Tuple, _LocalChannel] = {}


def _mailbox(key: Tuple) -> _LocalChannel:
    with _lock:
        return _mailboxes.setdefault(key, _LocalChannel())


@plain_action(name="channels.put")
def _put_action(key: Tuple, value: Any) -> bool:
    _mailbox(key).set(value)
    return True


@plain_action(name="channels.get")
def _get_action(key: Tuple) -> Future:
    return _mailbox(key).get()   # parcel layer chains the continuation


# ---------------------------------------------------------------------------
# channel_communicator
# ---------------------------------------------------------------------------

class ChannelCommunicator:
    """hpx::collectives::channel_communicator analog.

    set(to, value) / get(from) between sites; FIFO per directed pair.
    All mailboxes live on the root locality (the component host in HPX).
    """

    def __init__(self, basename: str, num_sites: Optional[int] = None,
                 this_site: Optional[int] = None,
                 root_locality: int = 0) -> None:
        self.basename = basename
        self.num_sites = (num_sites if num_sites is not None
                          else get_num_localities())
        self.this_site = (this_site if this_site is not None
                          else find_here())
        self.root_locality = root_locality

    def _key(self, frm: int, to: int, tag: Optional[int]) -> Tuple:
        return ("chan_comm", self.basename, frm, to, tag)

    def set(self, to: int, value: Any, tag: Optional[int] = None) -> Future:
        if not 0 <= to < self.num_sites:
            raise IndexError(to)
        return async_action(_put_action, self.root_locality,
                            self._key(self.this_site, to, tag), value)

    def get(self, frm: int, tag: Optional[int] = None) -> Future:
        if not 0 <= frm < self.num_sites:
            raise IndexError(frm)
        return async_action(_get_action, self.root_locality,
                            self._key(frm, self.this_site, tag))


def create_channel_communicator(basename: str,
                                num_sites: Optional[int] = None,
                                this_site: Optional[int] = None,
                                root_locality: int = 0
                                ) -> ChannelCommunicator:
    return ChannelCommunicator(basename, num_sites, this_site, root_locality)


# ---------------------------------------------------------------------------
# hpx::distributed::channel — a named channel hosted where it was created
# ---------------------------------------------------------------------------

class DistributedChannel:
    """Named cross-locality channel (lcos_distributed analog).

    The creator hosts the state and registers `(name -> host locality)`
    in AGAS; `connect` resolves the host and routes set/get there.
    """

    def __init__(self, name: str, host_locality: int) -> None:
        self.name = name
        self.host_locality = host_locality

    @classmethod
    def create(cls, name: str) -> "DistributedChannel":
        from ..dist import agas
        here = find_here()
        ok = agas.register_name(f"dchannel/{name}", here).get()
        if not ok:
            raise ValueError(f"channel name already registered: {name}")
        return cls(name, here)

    @classmethod
    def connect(cls, name: str) -> "DistributedChannel":
        from ..dist import agas
        host = agas.resolve_name(f"dchannel/{name}", wait=True).get()
        return cls(name, host)

    def _key(self) -> Tuple:
        return ("dchannel", self.name)

    def set(self, value: Any) -> Future:
        return async_action(_put_action, self.host_locality,
                            self._key(), value)

    def get(self) -> Future:
        return async_action(_get_action, self.host_locality, self._key())

    def unregister(self) -> None:
        from ..dist import agas
        agas.unregister_name(f"dchannel/{self.name}").get()


# ---------------------------------------------------------------------------
# hpx::distributed::latch
# ---------------------------------------------------------------------------

_latch_lock = threading.Lock()
_latches: Dict[str, list] = {}  # name -> [arrived, released, [SharedStates]]


@plain_action(name="channels.latch_arrive")
def _latch_arrive(name: str, count: int, n: int, wait: bool):
    """Hosted on root: accumulate arrivals; with wait, future released
    once arrivals reach the threshold.

    Arrival-count semantics (not remaining-count) make the exchange
    order-independent: actions from concurrent localities — or from one
    caller, reordered by the task pool — commute, and a wait landing
    after release completes immediately. One-shot per name, matching
    std::latch / hpx::distributed::latch."""
    st = SharedState() if wait else None
    released = None
    with _latch_lock:
        state = _latches.setdefault(name, [0, False, []])
        state[0] += count
        already_released = state[1]
        if st is not None and not already_released:
            state[2].append(st)
        if not state[1] and state[0] >= n:
            state[1] = True
            released = state[2]
            state[2] = []
    if released is not None:
        for w in released:
            w.set_value(True)
    if st is not None and already_released:
        st.set_value(True)
    if st is None:
        return True
    return Future(st)


class DistributedLatch:
    """hpx::distributed::latch: created with a threshold, counted down
    from any locality; wait() completes when the count reaches zero.
    One-shot per name (as std::latch is per instance)."""

    def __init__(self, name: str, count: int,
                 root_locality: int = 0) -> None:
        self.name = name
        self.count = count
        self.root_locality = root_locality

    def count_down(self, n: int = 1) -> Future:
        return async_action(_latch_arrive, self.root_locality,
                            self.name, n, self.count, False)

    def arrive_and_wait(self, n: int = 1) -> Future:
        return async_action(_latch_arrive, self.root_locality,
                            self.name, n, self.count, True)

    def wait(self) -> Future:
        return async_action(_latch_arrive, self.root_locality,
                            self.name, 0, self.count, True)
