"""Device-plane collectives: the same verbs compiled to XLA collectives.

Reference analog: none directly — HPX's collectives are host-value star
fan-ins through a root component (communicator.py replicates that
correctness model). THIS module is the performance model that replaces it
on TPU (SURVEY.md §3.6, §5.8): bulk-array collectives lower to
`lax.psum / all_gather / all_to_all / ppermute` inside `shard_map`, so
XLA schedules ring/tree exchanges over ICI — compiled, not tag-matched,
and never staged through a root.

Two surfaces:
  * whole-array helpers: take a jax.Array sharded over a mesh axis, run
    ONE jitted shard_map program, return the collective's result
    (replicated or resharded as the verb implies);
  * in-body re-exports (psum, pmax, ppermute, ...) for user shard_map
    SPMD code — the `hpx::collectives` verbs usable inside a fork_join-
    style team body.

Programs are cached per (mesh, axis, verb, op) — the first call compiles,
the rest dispatch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

# In-body verbs (psum, pmax, pmin, pmean, ppermute, axis_index) are
# re-exported lazily via __getattr__ so importing hpx_tpu does not pull
# in jax before the caller has configured platform env vars.
_LAZY_LAX = ("psum", "pmax", "pmin", "pmean", "ppermute", "axis_index")


def __getattr__(name: str):
    if name in _LAZY_LAX:
        from jax import lax
        return getattr(lax, name)
    raise AttributeError(name)


_REDUCERS: Dict[str, Callable] = {}


def _reducers() -> Dict[str, Callable]:
    if not _REDUCERS:
        from jax import lax
        _REDUCERS.update({
            "add": lax.psum, "sum": lax.psum,
            "max": lax.pmax, "min": lax.pmin, "mean": lax.pmean,
        })
    return _REDUCERS


_programs: Dict[Tuple, Any] = {}


def _program(mesh, axis: str, key: Tuple, build: Callable) -> Any:
    # keyed by mesh VALUE (Mesh is hashable): equal-but-distinct Mesh
    # objects (e.g. per-container default layouts) share one compilation
    cache_key = (mesh, axis) + key
    prog = _programs.get(cache_key)
    if prog is None:
        prog = build()
        _programs[cache_key] = prog
    return prog


def _shard_map(body, mesh, in_spec, out_spec):
    import jax
    from ..utils.jaxcompat import shard_map
    # check_vma stays ON (the default): with it off, jax falls back to
    # the legacy psum transpose and silently produces WRONG gradients
    # for differentiated collectives. Each verb below is written so its
    # output's varying-mesh-axes type matches its out_spec (e.g.
    # all_gather is expressed as scatter-place + psum, whose vma rule
    # proves the replication the all_gather rule cannot).
    return jax.jit(shard_map(body, mesh=mesh, in_specs=in_spec,
                             out_specs=out_spec))


def _specs(axis: str):
    from jax.sharding import PartitionSpec as P
    return P(axis), P()


def all_reduce(x: Any, mesh, axis: str = "x", op: str = "add") -> Any:
    """Reduce the per-device shards of x with op; replicated result of
    one shard's shape. `op`: add | max | min | mean."""
    sharded, rep = _specs(axis)

    def build():
        reducer = _reducers()[op]
        return _shard_map(lambda s: reducer(s, axis), mesh, sharded, rep)

    return _program(mesh, axis, ("all_reduce", op), build)(x)


def all_gather(x: Any, mesh, axis: str = "x") -> Any:
    """Gather shards along the axis: every device ends with the full
    (concatenated) array, replicated over the WHOLE mesh (`axis` is
    retained for cache keying and API symmetry; the resharding below
    replicates across every mesh axis)."""

    def build():
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        # Whole-array gather IS a resharding: axis-sharded -> fully
        # replicated. GSPMD lowers it to a native all-gather over ICI
        # (no shard_map, so no varying-axes proof is needed), and jax
        # differentiates the resharding exactly.
        return jax.jit(lambda s: s,
                       out_shardings=NamedSharding(mesh, P()))

    return _program(mesh, axis, ("all_gather",), build)(x)


def broadcast(x: Any, mesh, axis: str = "x", root: int = 0) -> Any:
    """Every device gets root's shard (replicated)."""
    import jax.numpy as jnp
    sharded, rep = _specs(axis)

    def build():
        from jax import lax

        def body(s):
            # keep only root's contribution, then sum-reduce: a compiled
            # one-to-all without host staging
            mine = jnp.where(lax.axis_index(axis) == root, s,
                             jnp.zeros_like(s))
            return lax.psum(mine, axis)
        return _shard_map(body, mesh, sharded, rep)

    return _program(mesh, axis, ("broadcast", root), build)(x)


def all_to_all(x: Any, mesh, axis: str = "x") -> Any:
    """Transpose shard ownership: with N devices, shard i's j-th block
    moves to device j's i-th block — the Ulysses/sequence-parallel
    primitive (SURVEY.md §5.7). x stays sharded over the axis."""
    sharded, _ = _specs(axis)
    n_ = mesh.shape[axis]
    shard_len = x.shape[0] // n_
    if x.shape[0] % n_ or shard_len % n_:
        raise ValueError(
            f"all_to_all needs leading dim divisible by n*n (n={n_} devices,"
            f" so a multiple of {n_ * n_}); got shape {tuple(x.shape)}")

    def build():
        from jax import lax
        n = mesh.shape[axis]

        def body(s):
            blocks = s.reshape((n, -1) + s.shape[1:])
            out = lax.all_to_all(blocks, axis, 0, 0, tiled=False)
            return out.reshape((-1,) + s.shape[1:])
        return _shard_map(body, mesh, sharded, sharded)

    return _program(mesh, axis, ("all_to_all",), build)(x)


def reduce_scatter(x: Any, mesh, axis: str = "x", op: str = "add") -> Any:
    """psum_scatter: reduce across devices, leave each device with its
    1/N slice — the bandwidth-optimal half of all_reduce. XLA exposes
    only the additive form (psum_scatter); other ops are rejected rather
    than silently summed."""
    if op not in ("add", "sum"):
        raise ValueError(f"reduce_scatter supports only add, got {op!r}")
    sharded, _ = _specs(axis)

    def build():
        from jax import lax

        def body(s):
            return lax.psum_scatter(s, axis, tiled=True)
        return _shard_map(body, mesh, sharded, sharded)

    return _program(mesh, axis, ("reduce_scatter", op), build)(x)


def ring_shift(x: Any, mesh, axis: str = "x", shift: int = 1) -> Any:
    """Neighbor exchange over the ICI ring (ppermute) — the halo/ring-
    attention substrate. Shard i receives shard (i - shift) mod N."""
    sharded, _ = _specs(axis)

    def build():
        from jax import lax
        n = mesh.shape[axis]
        perm = [(i, (i + shift) % n) for i in range(n)]
        return _shard_map(lambda s: lax.ppermute(s, axis, perm),
                          mesh, sharded, sharded)

    return _program(mesh, axis, ("ring_shift", shift), build)(x)


def barrier(mesh, axis: str = "x") -> None:
    """Device-plane fence: a trivial psum over the axis, blocked on."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build():
        from jax import lax
        sharded, rep = _specs(axis)
        return _shard_map(lambda s: lax.psum(s, axis), mesh, sharded, rep)

    n = mesh.shape[axis]
    token = jax.device_put(
        jnp.zeros((n,), jnp.int32),
        NamedSharding(mesh, P(axis)))
    jax.block_until_ready(_program(mesh, axis, ("barrier",), build)(token))
