"""Futures-based collectives over a communicator.

Reference analog: libs/full/collectives — `create_communicator(basename,
num_sites, this_site)` rendezvous, then `all_reduce / all_gather /
all_to_all / broadcast / gather / scatter / reduce / inclusive_scan /
exclusive_scan / barrier`, each returning a future. HPX implements these
as a communicator COMPONENT on a root locality holding per-operation
and_gate state; each participant contributes via action and receives a
future of its per-site result (SURVEY.md §3.6 — O(P) star fan-in).

TPU-first split (SURVEY.md §5.8): THIS module is the control-plane
implementation — host values, small payloads, exact HPX semantics, any
num_sites (sites may be threads within one locality or distinct
localities; contributions travel as actions to the root). The DATA plane
— bulk arrays over ICI — is collectives/device.py, where the same verbs
compile to XLA collectives inside shard_map and never touch the host.

Exceptions: an error raised while combining (e.g. a reducing op failing)
propagates to every participating site's future.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, Optional, Tuple

from ..dist.actions import async_action, plain_action
from ..dist.runtime import find_here, get_num_localities
from ..futures.future import Future, SharedState
from ..svc import tracing
from ..synchronization import Mutex

# ---------------------------------------------------------------------------
# Root-side exchange state. One generic primitive: every site contributes a
# value under (name, kind, generation); when the last arrives, a per-kind
# combine computes each site's result and releases all futures.
# ---------------------------------------------------------------------------

_lock = Mutex()
_exchanges: Dict[Tuple[str, str, int], dict] = {}
_hosted_total = 0     # exchanges whose root state lived HERE (cumulative)


@plain_action(name="collectives.hosted_count")
def hosted_exchange_count() -> int:
    """How many collective exchanges this locality has hosted root
    state for (cumulative). Lets tests/operators verify load placement
    — e.g. that a communication_set really spreads fan-in across group
    roots instead of funneling through locality 0."""
    with _lock:
        return _hosted_total


def _combine(kind: str, contribs: Dict[int, Any], num_sites: int,
             op: Optional[Callable], root: int) -> Dict[int, Any]:
    values = [contribs[i] for i in range(num_sites)]
    if kind == "all_reduce" or kind == "reduce":
        acc = values[0]
        for v in values[1:]:
            acc = op(acc, v)
        if kind == "reduce":
            return {i: (acc if i == root else None)
                    for i in range(num_sites)}
        return {i: acc for i in range(num_sites)}
    if kind == "all_gather":
        return {i: list(values) for i in range(num_sites)}
    if kind == "gather":
        return {i: (list(values) if i == root else None)
                for i in range(num_sites)}
    if kind == "broadcast":
        return {i: values[root] for i in range(num_sites)}
    if kind == "scatter":
        parts = values[root]
        if len(parts) != num_sites:
            raise ValueError(
                f"scatter: root provided {len(parts)} parts for "
                f"{num_sites} sites")
        return {i: parts[i] for i in range(num_sites)}
    if kind == "all_to_all":
        for i, v in enumerate(values):
            if len(v) != num_sites:
                raise ValueError(
                    f"all_to_all: site {i} provided {len(v)} parts for "
                    f"{num_sites} sites")
        return {i: [values[j][i] for j in range(num_sites)]
                for i in range(num_sites)}
    if kind == "inclusive_scan":
        out, acc = {}, None
        for i, v in enumerate(values):
            acc = v if acc is None else op(acc, v)
            out[i] = acc
        return out
    if kind == "exclusive_scan":
        # site i gets the fold of sites [0, i); site 0 has no prefix
        out, acc = {0: None}, None
        for i in range(1, num_sites):
            acc = values[i - 1] if acc is None else op(acc, values[i - 1])
            out[i] = acc
        return out
    if kind == "barrier":
        return {i: True for i in range(num_sites)}
    raise ValueError(f"unknown collective kind: {kind}")


@plain_action(name="collectives.contribute")
def _contribute(name: str, kind: str, gen: int, site: int, num_sites: int,
                value: Any, op: Optional[Callable], root: int):
    """Root action: register a contribution; future completes when all
    sites have arrived (and_gate) with this site's combined result."""
    key = (name, kind, gen)
    st = SharedState()
    global _hosted_total
    with _lock:
        ex = _exchanges.get(key)
        if ex is None:
            ex = _exchanges[key] = {"contribs": {}, "waiters": {}}
            _hosted_total += 1
        if site in ex["contribs"]:
            raise ValueError(
                f"duplicate contribution from site {site} to {key}")
        ex["contribs"][site] = value
        ex["waiters"][site] = st
        complete = len(ex["contribs"]) == num_sites
        if complete:
            del _exchanges[key]
    if complete:
        try:
            results = _combine(kind, ex["contribs"], num_sites, op, root)
        except BaseException as e:  # noqa: BLE001 — fan the error out
            for w in ex["waiters"].values():
                w.set_exception(e)
            return Future(st)
        for s, w in ex["waiters"].items():
            w.set_value(results[s])
    return Future(st)


# ---------------------------------------------------------------------------
# Client surface
# ---------------------------------------------------------------------------

class Communicator:
    """hpx::collectives::communicator analog.

    The HPX component + AGAS-symbol rendezvous collapses: the communicator
    is fully described by (basename, num_sites, this_site, root locality),
    so creation is immediate and the rendezvous happens implicitly at the
    first exchange (the and_gate on the root). Generations are tracked
    per operation kind — every site must issue the same sequence of calls
    on a given communicator, the same contract HPX has.
    """

    def __init__(self, basename: str, num_sites: Optional[int] = None,
                 this_site: Optional[int] = None,
                 root_locality: int = 0) -> None:
        self.basename = basename
        self.num_sites = (num_sites if num_sites is not None
                          else get_num_localities())
        self.this_site = (this_site if this_site is not None
                          else find_here())
        self.root_locality = root_locality
        self._gen: Dict[str, int] = {}
        self._gen_lock = Mutex()

    def _next_gen(self, kind: str, generation: Optional[int]) -> int:
        with self._gen_lock:
            if generation is not None:
                # fast-forward so later implicit calls don't collide
                # with explicitly-numbered rounds
                self._gen[kind] = max(self._gen.get(kind, 0),
                                      generation + 1)
                return generation
            g = self._gen.get(kind, 0)
            self._gen[kind] = g + 1
            return g

    def _exchange(self, kind: str, value: Any,
                  op: Optional[Callable] = None, root: int = 0,
                  generation: Optional[int] = None) -> Future:
        gen = self._next_gen(kind, generation)
        # span covers the LAUNCH (contribution dispatch); completion is
        # visible as the continuation/flow the returned future carries
        with tracing.span(f"collectives.{kind}", "collectives",
                          basename=self.basename, gen=gen,
                          site=self.this_site):
            return async_action(
                _contribute, self.root_locality, self.basename, kind,
                gen, self.this_site, self.num_sites, value, op, root)

    def __repr__(self) -> str:
        return (f"<communicator '{self.basename}' site {self.this_site}/"
                f"{self.num_sites}>")


def create_communicator(basename: str, num_sites: Optional[int] = None,
                        this_site: Optional[int] = None,
                        root_locality: int = 0) -> Communicator:
    """hpx::collectives::create_communicator analog."""
    return Communicator(basename, num_sites, this_site, root_locality)


def all_reduce(comm: Communicator, value: Any,
               op: Callable = operator.add,
               generation: Optional[int] = None) -> Future:
    """Every site gets op-fold of all contributions (future)."""
    return comm._exchange("all_reduce", value, op=op, generation=generation)


def reduce(comm: Communicator, value: Any, op: Callable = operator.add,
           root: int = 0, generation: Optional[int] = None) -> Future:
    """Root site gets the fold; other sites get None."""
    return comm._exchange("reduce", value, op=op, root=root,
                          generation=generation)


def all_gather(comm: Communicator, value: Any,
               generation: Optional[int] = None) -> Future:
    """Every site gets [site 0's value, ..., site N-1's value]."""
    return comm._exchange("all_gather", value, generation=generation)


def gather(comm: Communicator, value: Any, root: int = 0,
           generation: Optional[int] = None) -> Future:
    """Root gets the list of values; other sites get None (gather_there/
    gather_here collapse into the root parameter)."""
    return comm._exchange("gather", value, root=root, generation=generation)


def broadcast(comm: Communicator, value: Any = None, root: int = 0,
              generation: Optional[int] = None) -> Future:
    """Every site gets root's value (broadcast_to on root, broadcast_from
    elsewhere — non-root sites may pass value=None)."""
    return comm._exchange("broadcast", value, root=root,
                          generation=generation)


def scatter(comm: Communicator, parts: Any = None, root: int = 0,
            generation: Optional[int] = None) -> Future:
    """Root provides a list of num_sites parts; site i's future yields
    parts[i] (scatter_to/scatter_from collapse)."""
    return comm._exchange("scatter", parts, root=root, generation=generation)


def all_to_all(comm: Communicator, parts: Any,
               generation: Optional[int] = None) -> Future:
    """Site i provides [to site 0, ..., to site N-1]; gets
    [from site 0, ..., from site N-1]."""
    return comm._exchange("all_to_all", parts, generation=generation)


def inclusive_scan(comm: Communicator, value: Any,
                   op: Callable = operator.add,
                   generation: Optional[int] = None) -> Future:
    """Site i gets op-fold of sites [0, i]."""
    return comm._exchange("inclusive_scan", value, op=op,
                          generation=generation)


def exclusive_scan(comm: Communicator, value: Any,
                   op: Callable = operator.add,
                   generation: Optional[int] = None) -> Future:
    """Site i gets op-fold of sites [0, i); site 0 gets None."""
    return comm._exchange("exclusive_scan", value, op=op,
                          generation=generation)


def barrier(comm: Communicator,
            generation: Optional[int] = None) -> Future:
    """Future completes when every site has arrived."""
    return comm._exchange("barrier", None, generation=generation)
