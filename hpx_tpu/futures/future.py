"""Futures with continuations — the core LCO.

Reference analog: libs/core/futures (hpx::future / hpx::shared_future /
hpx::promise; future_data shared state with continuation list; automatic
future<future<T>> unwrapping).

TPU-first notes:
- A future's value may be a dispatched (still-executing) jax.Array. JAX's
  dispatch is already asynchronous, so a future holding such an array is
  READY in the HPX sense for dependency purposes: consumers can be
  scheduled immediately and XLA enforces the data dependency on device.
  This is what lets fine-grained dataflow graphs run at device speed —
  the host races ahead building/dispatching while the TPU streams through
  the queued programs (SURVEY.md §7 "task granularity chasm" mitigation).
- Continuations run inline on the completing thread by default (HPX's
  launch::sync continuation behavior) or on an executor when given.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, Iterable, List, Optional, TypeVar

from ..core.errors import Error, FutureError

T = TypeVar("T")

_NOT_SET = object()

# Causal-trace continuation hook (svc/tracing): when a tracer is
# active, _trace_continuation(run, user_fn) wraps a then-continuation
# so its execution records a span parented to the ATTACHING context
# (plus a flow arrow). None when tracing is off — then() pays one
# global load + is-None test.
_trace_continuation: Optional[Callable[..., Any]] = None


def set_trace_continuation_hook(hook: Optional[Callable[..., Any]]
                                ) -> None:
    global _trace_continuation
    _trace_continuation = hook


def _run_callback(cb: Callable[["SharedState"], None],
                  st: "SharedState") -> None:
    """Continuations are isolated: one raising callback must not poison the
    producer's set_value nor starve the remaining continuations. Framework
    continuations (then/dataflow/when_*) capture exceptions into their own
    futures, so anything escaping here is a user callback bug — report it
    loudly and keep going."""
    try:
        cb(st)
    except BaseException:  # noqa: BLE001
        import traceback
        traceback.print_exc()


class SharedState(Generic[T]):
    """future_data analog: value/exception slot + continuation list.

    Lock is only held for state transitions; continuations are invoked
    outside the lock. A waiter Condition is created lazily — the hot path
    (async_ + dataflow chains, future_overhead benchmark) never allocates
    one.
    """

    __slots__ = ("_lock", "_value", "_exception", "_callbacks", "_cond")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value: Any = _NOT_SET
        self._exception: Optional[BaseException] = None
        self._callbacks: Optional[List[Callable[["SharedState"], None]]] = None
        self._cond: Optional[threading.Condition] = None

    # -- producer side ------------------------------------------------------
    def set_value(self, value: T) -> None:
        if isinstance(value, Future):
            # future<future<T>> unwrapping: adopt the inner future's result.
            value._state.add_callback(lambda st: self._adopt(st))
            return
        self._finish(value, None)

    def set_exception(self, exc: BaseException) -> None:
        self._finish(_NOT_SET, exc)

    def _adopt(self, inner: "SharedState") -> None:
        if inner._exception is not None:
            self._finish(_NOT_SET, inner._exception)
        else:
            self.set_value(inner._value)  # may unwrap again

    def _finish(self, value: Any, exc: Optional[BaseException]) -> None:
        with self._lock:
            if self._value is not _NOT_SET or self._exception is not None:
                raise FutureError(Error.promise_already_satisfied,
                                  "shared state already set")
            self._value = value
            self._exception = exc
            callbacks = self._callbacks
            self._callbacks = None
            cond = self._cond
        if cond is not None:
            with cond:
                cond.notify_all()
        if callbacks:
            for cb in callbacks:
                _run_callback(cb, self)

    # -- consumer side ------------------------------------------------------
    def is_ready(self) -> bool:
        return self._value is not _NOT_SET or self._exception is not None

    def has_exception(self) -> bool:
        return self._exception is not None

    def add_callback(self, cb: Callable[["SharedState"], None]) -> None:
        """Run cb(state) when ready; inline immediately if already ready."""
        with self._lock:
            if not self.is_ready():
                if self._callbacks is None:
                    self._callbacks = []
                self._callbacks.append(cb)
                return
        _run_callback(cb, self)

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self.is_ready():
            return True

        # Work-helping (HPX suspension analog): a pool worker waiting on a
        # future keeps executing queued tasks so nested async+get patterns
        # can't starve the pool — essential on few-core hosts where the
        # whole pool may be a single worker. help_one itself is
        # depth-bounded (threadpool.HELP_DEPTH_CAP): a mass fan-out of
        # blocking tasks parks at the cap instead of recursing one
        # Python/C call chain per nested help into a stack overflow.
        from ..runtime.threadpool import current_worker_pool
        pool = current_worker_pool()
        if pool is not None:
            import time as _time
            deadline = None if timeout is None else _time.monotonic() + timeout
            while not self.is_ready():
                if deadline is not None and _time.monotonic() >= deadline:
                    return False
                if not pool.help_one():
                    # nothing runnable (or at the help-depth cap): the
                    # dependency completes on another thread (or a
                    # device); park briefly and re-check
                    with self._lock:
                        if self.is_ready():
                            return True
                        if self._cond is None:
                            self._cond = threading.Condition(self._lock)
                        self._cond.wait_for(self.is_ready, 0.0005)
            return True

        with self._lock:
            if self.is_ready():
                return True
            if self._cond is None:
                self._cond = threading.Condition(self._lock)
            cond = self._cond
            return cond.wait_for(self.is_ready, timeout)

    def result(self, timeout: Optional[float] = None) -> T:
        if not self.wait(timeout):
            raise FutureError(Error.invalid_status, "future wait timed out")
        if self._exception is not None:
            raise self._exception
        return self._value


class Future(Generic[T]):
    """hpx::future / hpx::shared_future analog.

    Python note: there is no move semantics, so this type behaves like
    hpx::shared_future — get() may be called repeatedly and by multiple
    consumers. `share()` exists for API parity and returns self.
    """

    __slots__ = ("_state",)

    def __init__(self, state: Optional[SharedState] = None) -> None:
        self._state = state if state is not None else SharedState()

    # -- observers ----------------------------------------------------------
    def is_ready(self) -> bool:
        return self._state.is_ready()

    def has_value(self) -> bool:
        return self._state.is_ready() and not self._state.has_exception()

    def has_exception(self) -> bool:
        return self._state.has_exception()

    def valid(self) -> bool:
        return self._state is not None

    # -- retrieval ----------------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> T:
        return self._state.result(timeout)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._state.wait(timeout)

    def share(self) -> "Future[T]":
        return self

    # -- composition --------------------------------------------------------
    def then(self, fn: Callable[["Future[T]"], Any],
             executor: Optional[Any] = None) -> "Future":
        """Attach continuation fn(self); returns future of its result.

        If fn returns a Future it is unwrapped (hpx::future::then +
        unwrapping semantics). With `executor`, the continuation is
        scheduled through executor.post (async_execute fire-and-forget).
        """
        next_state: SharedState = SharedState()

        def run(_st: SharedState) -> None:
            try:
                next_state.set_value(fn(self))
            except BaseException as e:  # noqa: BLE001 — propagate into future
                next_state.set_exception(e)

        wrap = _trace_continuation
        if wrap is not None:
            run = wrap(run, fn)

        if executor is None:
            self._state.add_callback(run)
        else:
            self._state.add_callback(
                lambda st: executor.post(run, st))
        return Future(next_state)

    def unwrap(self) -> "Future":
        """future<future<T>> -> future<T> explicitly."""
        out: SharedState = SharedState()

        def feed(st: SharedState) -> None:
            if st._exception is not None:
                out.set_exception(st._exception)
            else:
                out.set_value(st._value)  # SharedState unwraps Futures

        self._state.add_callback(feed)
        return Future(out)

    def __repr__(self) -> str:
        s = ("ready" if self.has_value() else
             "exceptional" if self.has_exception() else "pending")
        return f"<Future {s}>"


class Promise(Generic[T]):
    """hpx::promise analog."""

    __slots__ = ("_state", "_future_retrieved")

    def __init__(self) -> None:
        self._state: SharedState[T] = SharedState()
        self._future_retrieved = False

    def get_future(self) -> Future[T]:
        if self._future_retrieved:
            raise FutureError(Error.future_already_retrieved,
                              "future already retrieved from promise")
        self._future_retrieved = True
        return Future(self._state)

    def set_value(self, value: T) -> None:
        self._state.set_value(value)

    def set_exception(self, exc: BaseException) -> None:
        self._state.set_exception(exc)


class PackagedTask(Generic[T]):
    """hpx::packaged_task analog: callable + promise."""

    __slots__ = ("_fn", "_promise")

    def __init__(self, fn: Callable[..., T]) -> None:
        self._fn = fn
        self._promise: Promise[T] = Promise()

    def get_future(self) -> Future[T]:
        return self._promise.get_future()

    def __call__(self, *args: Any, **kwargs: Any) -> None:
        try:
            self._promise.set_value(self._fn(*args, **kwargs))
        except BaseException as e:  # noqa: BLE001
            self._promise.set_exception(e)


def make_ready_future(value: T = None) -> Future[T]:
    st: SharedState[T] = SharedState()
    st.set_value(value)
    return Future(st)


def make_exceptional_future(exc: BaseException) -> Future:
    st: SharedState = SharedState()
    st.set_exception(exc)
    return Future(st)


def is_future(x: Any) -> bool:
    return isinstance(x, Future)
