"""hpx::async / hpx::post / hpx::sync / launch policies.

Reference analog: libs/core/async_base + libs/core/async_local
(async_dispatch over launch policies; parallel_executor::async_execute as
the default scheduling path — SURVEY.md §3.2).
"""

from __future__ import annotations

import enum
from typing import Any, Callable

from ..runtime.threadpool import default_pool
from .future import Future, SharedState, make_ready_future


class Launch(enum.Enum):
    """hpx::launch policies."""

    async_ = "async"      # schedule on a worker now
    sync = "sync"         # run inline in the caller
    deferred = "deferred" # run lazily on first wait/get
    fork = "fork"         # HPX: run child first on this worker; host analog
                          # is inline execution (caller continues after)


def _run_into(state: SharedState, fn: Callable[..., Any],
              args: tuple, kwargs: dict) -> None:
    try:
        state.set_value(fn(*args, **kwargs))
    except BaseException as e:  # noqa: BLE001
        state.set_exception(e)


def async_(fn: Callable[..., Any], *args: Any,
           policy: Launch = Launch.async_, executor: Any = None,
           **kwargs: Any) -> Future:
    """hpx::async analog: returns a Future of fn(*args).

    If fn returns a Future, the result is unwrapped (HPX semantics).
    `executor` overrides the default pool (two-argument hpx::async form
    `async(exec, f, ...)`).
    """
    if policy in (Launch.sync, Launch.fork):
        state: SharedState = SharedState()
        _run_into(state, fn, args, kwargs)
        return Future(state)

    if policy is Launch.deferred:
        return _deferred(fn, args, kwargs)

    state = SharedState()
    if executor is not None:
        executor.post(_run_into, state, fn, args, kwargs)
    else:
        default_pool().submit(_run_into, state, fn, args, kwargs)
    return Future(state)


class _DeferredState(SharedState):
    """Shared state that runs its thunk on first demand.

    Demand = wait()/result() (HPX semantics) or a continuation being
    attached (then/dataflow/when_all): a deferred future consumed through
    the callback interface would otherwise never start and hang every
    downstream future.
    """

    __slots__ = ("_thunk", "_started")

    def __init__(self, fn: Callable[..., Any], args: tuple, kwargs: dict):
        super().__init__()
        self._thunk = (fn, args, kwargs)
        self._started = False

    def _maybe_run(self) -> None:
        run = False
        with self._lock:
            if not self._started:
                self._started = True
                run = True
        if run:
            fn, args, kwargs = self._thunk
            _run_into(self, fn, args, kwargs)

    def wait(self, timeout=None):  # type: ignore[override]
        self._maybe_run()
        return super().wait(timeout)

    def result(self, timeout=None):  # type: ignore[override]
        self._maybe_run()
        return super().result(timeout)

    def add_callback(self, cb):  # type: ignore[override]
        self._maybe_run()
        super().add_callback(cb)


def _deferred(fn: Callable[..., Any], args: tuple, kwargs: dict) -> Future:
    return Future(_DeferredState(fn, args, kwargs))


def post(fn: Callable[..., Any], *args: Any, executor: Any = None,
         **kwargs: Any) -> None:
    """hpx::post (fire-and-forget; no future is produced)."""
    if executor is not None:
        executor.post(fn, *args, **kwargs)
    else:
        default_pool().submit(fn, *args, **kwargs)


def post_many(fn: Callable[..., Any], argss, executor: Any = None) -> None:
    """Fire-and-forget fan-out: schedule fn(*args) for every args in
    `argss` through ONE batched pool submission (one GIL/C-ABI crossing
    on the native scheduler — the high-throughput spawn path the
    reference reaches with its C++ scheduler; see
    benchmarks/future_overhead.py)."""
    argss = [tuple(a) for a in argss]     # accept any iterable once
    if executor is not None:
        for a in argss:
            executor.post(fn, *a)
        return
    default_pool().submit_many([(fn, a, {}) for a in argss])


def async_many(fn: Callable[..., Any], argss) -> list:
    """hpx::async fan-out: one Future per args tuple, all submitted in
    one batch (see post_many)."""
    argss = [tuple(a) for a in argss]     # a generator must not be
    states = [SharedState() for _ in argss]   # exhausted building states
    default_pool().submit_many(
        [(_run_into, (st, fn, a, {}), {})
         for st, a in zip(states, argss)])
    return [Future(st) for st in states]


def sync(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
    """hpx::sync: run now, return the value (exceptions propagate raw)."""
    result = fn(*args, **kwargs)
    if isinstance(result, Future):
        return result.get()
    return result
