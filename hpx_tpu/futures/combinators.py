"""Future combinators: when_all / when_any / when_some / when_each, wait_*.

Reference analog: libs/core/async_combinators. Signatures follow HPX:
when_all over an iterable (or varargs) of futures returns a future of the
list of (ready) futures; when_any returns a future of a WhenAnyResult with
the index of the first ready future; when_some waits for n.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from .future import Future, SharedState, is_future, make_ready_future


def _normalize(args: Sequence[Any]) -> List[Future]:
    """Accept when_all(f1, f2) and when_all([f1, f2]); coerce values."""
    if len(args) == 1 and not is_future(args[0]) and hasattr(args[0], "__iter__"):
        items = list(args[0])
    else:
        items = list(args)
    return [x if is_future(x) else make_ready_future(x) for x in items]


def when_all(*args: Any) -> Future:
    """future<list<future>>: ready when every input is ready.

    Never rethrows input exceptions itself — exceptional inputs appear as
    exceptional futures in the result list (HPX semantics; callers see the
    exception at inner .get())."""
    futures = _normalize(args)
    if not futures:
        return make_ready_future([])
    out: SharedState = SharedState()
    remaining = [len(futures)]
    lock = threading.Lock()

    def on_ready(_st: SharedState) -> None:
        with lock:
            remaining[0] -= 1
            done = remaining[0] == 0
        if done:
            out.set_value(futures)

    for f in futures:
        f._state.add_callback(on_ready)
    return Future(out)


@dataclass
class WhenAnyResult:
    index: int
    futures: List[Future] = field(default_factory=list)


def when_any(*args: Any) -> Future:
    """future<WhenAnyResult>: ready when the first input is ready."""
    futures = _normalize(args)
    if not futures:
        return make_ready_future(WhenAnyResult(-1, []))
    out: SharedState = SharedState()
    fired = threading.Event()

    def make_cb(i: int) -> Callable[[SharedState], None]:
        def cb(_st: SharedState) -> None:
            if not fired.is_set():
                # benign race: Event.set is idempotent; first setter wins
                # via SharedState's already-set guard below.
                fired.set()
                try:
                    out.set_value(WhenAnyResult(i, futures))
                except Exception:
                    pass  # lost the race
        return cb

    for i, f in enumerate(futures):
        f._state.add_callback(make_cb(i))
    return Future(out)


@dataclass
class WhenSomeResult:
    indices: List[int]
    futures: List[Future] = field(default_factory=list)


def when_some(n: int, *args: Any) -> Future:
    """future<WhenSomeResult>: ready when n inputs are ready."""
    futures = _normalize(args)
    if n <= 0 or not futures:
        return make_ready_future(WhenSomeResult([], futures))
    n = min(n, len(futures))
    out: SharedState = SharedState()
    lock = threading.Lock()
    ready_idx: List[int] = []

    def make_cb(i: int) -> Callable[[SharedState], None]:
        def cb(_st: SharedState) -> None:
            fire = False
            with lock:
                ready_idx.append(i)
                if len(ready_idx) == n:
                    fire = True
            if fire:
                out.set_value(WhenSomeResult(sorted(ready_idx[:n]), futures))
        return cb

    for i, f in enumerate(futures):
        f._state.add_callback(make_cb(i))
    return Future(out)


def when_each(fn: Callable[[Future], Any], *args: Any) -> Future:
    """Invoke fn(future) as each becomes ready; future<None> when all did."""
    futures = _normalize(args)
    if not futures:
        return make_ready_future(None)
    out: SharedState = SharedState()
    remaining = [len(futures)]
    lock = threading.Lock()

    def make_cb(f: Future) -> Callable[[SharedState], None]:
        def cb(_st: SharedState) -> None:
            try:
                fn(f)
            finally:
                with lock:
                    remaining[0] -= 1
                    done = remaining[0] == 0
                if done:
                    out.set_value(None)
        return cb

    for f in futures:
        f._state.add_callback(make_cb(f))
    return Future(out)


# -- blocking variants ------------------------------------------------------

def wait_all(*args: Any, timeout: Optional[float] = None) -> bool:
    """Wait for all inputs; one shared timeout, returns readiness."""
    return when_all(*args).wait(timeout)


def wait_any(*args: Any, timeout: Optional[float] = None) -> int:
    return when_any(*args).get(timeout).index


def wait_some(n: int, *args: Any, timeout: Optional[float] = None) -> List[int]:
    return when_some(n, *args).get(timeout).indices


def wait_each(fn: Callable[[Future], Any], *args: Any) -> None:
    when_each(fn, *args).get()


def split_future(f: Future, n: int) -> List[Future]:
    """hpx::split_future analog: future<tuple> -> list of n futures."""
    outs = [SharedState() for _ in range(n)]

    def fan_out(st: SharedState) -> None:
        if st._exception is not None:
            for o in outs:
                o.set_exception(st._exception)
            return
        vals = st._value
        for i, o in enumerate(outs):
            try:
                o.set_value(vals[i])
            except BaseException as e:  # noqa: BLE001
                o.set_exception(e)

    f._state.add_callback(fan_out)
    return [Future(o) for o in outs]
