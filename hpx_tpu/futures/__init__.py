from .future import (  # noqa: F401
    Future,
    PackagedTask,
    Promise,
    SharedState,
    is_future,
    make_exceptional_future,
    make_ready_future,
)
from .async_ import (  # noqa: F401
    Launch,
    async_,
    async_many,
    post,
    post_many,
    sync,
)
from .combinators import (  # noqa: F401
    WhenAnyResult,
    WhenSomeResult,
    split_future,
    wait_all,
    wait_any,
    wait_each,
    wait_some,
    when_all,
    when_any,
    when_each,
    when_some,
)
from .dataflow import dataflow, unwrapping  # noqa: F401
from .task_group import TaskGroup, task_group  # noqa: F401
