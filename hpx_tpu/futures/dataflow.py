"""hpx::dataflow + hpx::unwrapping — DAG construction without blocking.

Reference analog: libs/core/pack_traversal (traverse_pack, unwrapping) and
the dataflow frame in async_combinators (SURVEY.md §3.5): dataflow(f, a, b)
traverses its argument pack for futures (including futures nested inside
lists/tuples/dicts), attaches a callback to each non-ready one, and
schedules f once the last dependency fires — no thread ever blocks waiting.

TPU-first: this is the host-side DAG engine that keeps the device busy.
With tpu_executor's eager device futures, a time-stepped dataflow graph
(1d_stencil_4 style) degenerates into a straight-line dispatch loop — the
host enqueues XLA programs as fast as it can while the device chews through
them; dependencies between dispatched jax.Arrays are enforced by XLA, not
by host synchronization.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Tuple

from .async_ import Launch
from .future import Future, SharedState, is_future
from ..runtime.threadpool import default_pool


def _collect_futures(obj: Any, acc: List[Future]) -> None:
    """Deep traversal of the argument pack (tuple/list/dict nesting)."""
    if is_future(obj):
        acc.append(obj)
    elif isinstance(obj, (list, tuple)):
        for x in obj:
            _collect_futures(x, acc)
    elif isinstance(obj, dict):
        for x in obj.values():
            _collect_futures(x, acc)


def _substitute(obj: Any, unwrap: bool) -> Any:
    """Replace ready futures by their value (unwrapping) or leave them."""
    if is_future(obj):
        return obj.get() if unwrap else obj
    if isinstance(obj, list):
        return [_substitute(x, unwrap) for x in obj]
    if isinstance(obj, tuple):
        return tuple(_substitute(x, unwrap) for x in obj)
    if isinstance(obj, dict):
        return {k: _substitute(v, unwrap) for k, v in obj.items()}
    return obj


def dataflow(fn: Callable[..., Any], *args: Any,
             policy: Launch = Launch.async_, executor: Any = None,
             unwrap: bool = False, **kwargs: Any) -> Future:
    """Run fn(*args) once all futures in args are ready; returns Future.

    By default fn receives the *futures themselves* (now ready) — HPX
    semantics. Use unwrap=True (or wrap fn in `unwrapping`) to receive
    their values instead. If fn returns a Future it is unwrapped into the
    result (dataflow returns future<T>, not future<future<T>>).
    """
    deps: List[Future] = []
    _collect_futures(args, deps)
    _collect_futures(kwargs, deps)

    out: SharedState = SharedState()

    def fire() -> None:
        try:
            a = _substitute(args, unwrap)
            kw = _substitute(kwargs, unwrap)
            out.set_value(fn(*a, **kw))
        except BaseException as e:  # noqa: BLE001
            out.set_exception(e)

    def schedule() -> None:
        if policy is Launch.sync or policy is Launch.fork:
            fire()
        elif executor is not None:
            executor.post(fire)
        else:
            default_pool().submit(fire)

    if not deps:
        schedule()
        return Future(out)

    remaining = [len(deps)]
    lock = threading.Lock()

    def on_dep(_st: SharedState) -> None:
        with lock:
            remaining[0] -= 1
            done = remaining[0] == 0
        if done:
            schedule()

    for d in deps:
        d._state.add_callback(on_dep)
    return Future(out)


class unwrapping:
    """hpx::unwrapping(f): adapter mapping future arguments to values.

    dataflow(unwrapping(f), futs...) == dataflow(f, futs..., unwrap=True).
    Also usable standalone: unwrapping(f)(future, 3) == f(future.get(), 3).
    """

    __slots__ = ("_fn",)

    def __init__(self, fn: Callable[..., Any]) -> None:
        self._fn = fn

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        a = _substitute(args, unwrap=True)
        kw = _substitute(kwargs, unwrap=True)
        return self._fn(*a, **kw)
