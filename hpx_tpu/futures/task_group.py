"""hpx::experimental::task_group analog.

Reference analog: libs/core/task_group (run children, wait collects; a
child throwing makes wait() rethrow; the group is reusable after wait;
children may spawn further children into the group).

    with task_group() as tg:          # wait() implied at scope exit
        tg.run(f, x)
        tg.run(g)
    # or explicitly:
    tg = TaskGroup(); tg.run(f); tg.wait()
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from .async_ import async_
from .future import Future


class TaskGroup:
    """Structured concurrency: spawn tasks, wait for all of them.

    Exceptions: like the reference, the FIRST child exception is
    rethrown by wait(); the rest are swallowed (all children always run
    to completion before wait returns). Children may call run() to add
    more children; wait() drains until the group is empty.
    """

    def __init__(self, executor: Any = None) -> None:
        self._executor = executor
        self._lock = threading.Lock()
        self._futures: List[Future] = []

    def run(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        """Schedule a child task."""
        if self._executor is not None:
            f = self._executor.async_execute(fn, *args, **kwargs)
        else:
            f = async_(fn, *args, **kwargs)
        with self._lock:
            self._futures.append(f)

    def wait(self) -> None:
        """Wait for all children (including ones they spawn); rethrows
        the first child exception once everything has finished."""
        first_exc: Optional[BaseException] = None
        while True:
            with self._lock:
                batch = self._futures[:]
                self._futures.clear()
            if not batch:
                break
            for f in batch:
                try:
                    f.get()
                except BaseException as e:  # noqa: BLE001
                    if first_exc is None:
                        first_exc = e
        if first_exc is not None:
            raise first_exc

    # -- context manager (scope-exit wait, like the reference's dtor) -------
    def __enter__(self) -> "TaskGroup":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.wait()
        else:
            # an exception is already in flight: still drain children,
            # but don't mask the original error
            try:
                self.wait()
            except BaseException:  # noqa: BLE001
                pass


def task_group(executor: Any = None) -> TaskGroup:
    """Factory spelling: `with task_group() as tg: ...`."""
    return TaskGroup(executor)
