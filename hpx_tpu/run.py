"""Multi-locality launcher — the hpxrun.py analog.

Reference analog: cmake/templates/hpxrun.py.in (launch N OS processes on
localhost wired via the TCP parcelport — SURVEY.md §4).

    python -m hpx_tpu.run -l 4 [-t 2] script.py [script args...]

Spawns N copies of script.py with HPX_TPU_LOCALITY/LOCALITIES/PARCEL__*
env vars set; locality 0 shares the console port with everyone. Exit
status is the max of the children's (HPX convention: nonzero = failures).
Children default to the CPU jax platform (multi-process dev harness —
the real-TPU path is single-process per host, as on actual pods).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
from typing import List


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch(script: str, script_args: List[str], localities: int,
           threads: int = 0, jax_platform: str = "cpu",
           timeout: float = 300.0) -> int:
    import secrets as _secrets
    port = _free_port()
    # per-launch shared secret: every locality authenticates its parcel
    # connections (dist/auth.py HMAC handshake) even on loopback, so the
    # pickle deserializer is never reachable unauthenticated and the
    # handshake path is exercised by every multi-process run
    secret = os.environ.get("HPX_TPU_PARCEL__SECRET",
                            _secrets.token_hex(16))
    procs = []
    for loc in range(localities):
        env = dict(os.environ)
        env["HPX_TPU_LOCALITY"] = str(loc)
        env["HPX_TPU_LOCALITIES"] = str(localities)
        env["HPX_TPU_PARCEL__PORT"] = str(port)
        env["HPX_TPU_PARCEL__SECRET"] = secret
        if threads:
            env["HPX_TPU_OS_THREADS"] = str(threads)
        if jax_platform:
            env["JAX_PLATFORMS"] = jax_platform
            # the env var alone is not enough on sandboxes whose
            # sitecustomize force-registers an accelerator plugin and
            # calls jax.config.update("jax_platforms", ...) at interpreter
            # start; hpx_tpu honors this at import and re-updates the
            # config (tests/conftest.py does the same for pytest)
            env["HPX_TPU_FORCE_PLATFORM"] = jax_platform
        procs.append(subprocess.Popen(
            [sys.executable, script, *script_args], env=env))
    rc = 0
    try:
        for p in procs:
            try:
                p.wait(timeout=timeout)
                code = p.returncode or 0
                # signal deaths are negative — report as failure, not 0
                rc = max(rc, code if code > 0 else (1 if code else 0))
            except subprocess.TimeoutExpired:
                rc = max(rc, 1)   # hung locality counts as failure
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                rc = max(rc, 1)
    return rc


def bench_mesh(n_devices: int, timeout: float = 1800.0) -> int:
    """`python -m hpx_tpu.run --bench-mesh N`: BASELINE configs #3/#4/#5
    (partitioned_vector triad, 1M all_reduce, sharded Jacobi) at
    1/2/4/../N devices — real chips when jax exposes enough, otherwise a
    virtual N-device CPU mesh in a child process (the same harness runs
    unchanged on multi-chip hardware)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "benchmarks", "mesh_scaling.py")
    env = dict(os.environ)
    # probe the device count in a THROWAWAY subprocess: importing jax
    # here would grab exclusive accelerator locks (libtpu) / preallocate
    # (GPU) in a process that never releases them, starving the child
    enough = False
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, sys; sys.stdout.write(str(len(jax.devices())))"],
            capture_output=True, text=True, timeout=120)
        enough = (probe.returncode == 0
                  and probe.stdout.strip().isdigit()
                  and int(probe.stdout.strip()) >= n_devices)
    except Exception:  # noqa: BLE001
        pass
    if not enough:
        env["JAX_PLATFORMS"] = "cpu"
        env["HPX_TPU_FORCE_PLATFORM"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split() if not
                 f.startswith("--xla_force_host_platform_device_count")]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n_devices}"])
    proc = subprocess.run(
        [sys.executable, script, "--devices", str(n_devices)],
        cwd=repo, env=env, timeout=timeout)
    return proc.returncode


def _split_argv(argv: List[str]):
    """Launcher flags BEFORE the script path; everything from the
    script on is the script's own (so a script's --timeout is never
    swallowed — hpxrun convention)."""
    takes_value = {"-l", "--localities", "-t", "--threads", "--timeout",
                   "--platform", "--bench-mesh"}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            return argv[: i + 1], None, []
        if a in takes_value:
            i += 2
        elif a.startswith("-") and "=" in a and \
                a.split("=", 1)[0] in takes_value:
            i += 1
        elif a.startswith("-"):
            # an unknown flag is a launcher usage error, not a script:
            # silently Popen-ing "--localites" would hang N children
            raise SystemExit(
                f"hpx_tpu.run: unknown launcher flag {a!r} "
                "(launcher flags go before the script path; "
                "see --help)")
        else:
            return argv[:i], argv[i], argv[i + 1:]
    # no script: legal only for script-less launcher modes (--bench-mesh)
    return argv, None, []


def main() -> None:
    ap = argparse.ArgumentParser(prog="hpx_tpu.run", allow_abbrev=False)
    ap.add_argument("-l", "--localities", type=int, default=2)
    ap.add_argument("-t", "--threads", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--bench-mesh", type=int, default=0)
    # only PRE-SCRIPT flags are the launcher's: `run.py script.py
    # --bench-mesh 4` passes --bench-mesh through to the script
    launcher_args, script, script_args = _split_argv(sys.argv[1:])
    ns = ap.parse_args(launcher_args)
    if script is None:
        if ns.bench_mesh:           # script-less mode: harness IS the job
            sys.exit(bench_mesh(ns.bench_mesh, max(ns.timeout, 1800.0)))
        raise SystemExit("hpx_tpu.run: no script given")
    if ns.bench_mesh:
        raise SystemExit("hpx_tpu.run: --bench-mesh takes no script")
    sys.exit(launch(script, script_args, ns.localities, ns.threads,
                    ns.platform, ns.timeout))


if __name__ == "__main__":
    main()
