"""hpx_tpu — a TPU-native asynchronous many-task framework.

Capability target: biddisco/hpx (see SURVEY.md). Architecture: TPU-first —
futures/dataflow orchestrate XLA program dispatches; parallel algorithms
lower to jit/Pallas kernels; partitioned data is sharded jax.Arrays;
collectives ride XLA collectives (psum/ppermute/all_gather/all_to_all) over
ICI inside shard_map; localities map onto processes/devices with an
AGAS-style name registry.

Public API façade mirroring HPX's umbrella headers (hpx/hpx.hpp):

    import hpx_tpu as hpx
    f = hpx.async_(fn, *args)            # hpx::async
    hpx.dataflow(fn, f1, f2)             # hpx::dataflow
    hpx.when_all(fs); hpx.wait_all(fs)   # combinators
    hpx.transform_reduce(hpx.par.on(hpx.tpu_executor()), ...)
"""

# Platform override hook (set by hpx_tpu.run for child localities):
# sandboxes can force an accelerator platform via sitecustomize
# (jax.config.update at interpreter start), which wins over the
# JAX_PLATFORMS env var — counter it before any device query.
import os as _os  # noqa: E402

if _os.environ.get("HPX_TPU_FORCE_PLATFORM"):
    import jax as _jax

    _jax.config.update("jax_platforms",
                       _os.environ["HPX_TPU_FORCE_PLATFORM"])

from .core.version import HPX_TPU_VERSION, full_version_as_string  # noqa: F401
from .core.errors import Error, ErrorCode, HpxError  # noqa: F401
from .core.config import Configuration  # noqa: F401
from .core.timing import (  # noqa: F401
    HighResolutionTimer, TimedExecutor, async_after, async_at,
    high_resolution_clock_now, sleep_for, sleep_until,
)
from .core.topology import Topology, get_topology  # noqa: F401
from .runtime.resource import (  # noqa: F401
    Pool, ResourcePartitioner, get_partitioner,
)
from .runtime import batch_environments  # noqa: F401
from .runtime.dataloader import DeviceLoader, device_loader  # noqa: F401

__version__ = full_version_as_string()

# -- futures / async / dataflow (M1) ----------------------------------------
from .futures import (  # noqa: F401
    Future, Promise, PackagedTask, Launch,
    async_, async_many, post, post_many, sync, dataflow, unwrapping,
    make_ready_future, make_exceptional_future, is_future,
    when_all, when_any, when_each, when_some,
    wait_all, wait_any, wait_each, wait_some, split_future,
)
from .futures.task_group import TaskGroup, task_group  # noqa: F401
from . import lcos  # noqa: F401
from .synchronization import (  # noqa: F401
    Barrier, ConditionVariable, CountingSemaphore, Event, Latch, Mutex,
    SharedMutex, SlidingSemaphore, Spinlock, StopSource, StopToken,
    enable_lock_verification,
)

# -- executors & execution policies (M2) ------------------------------------
from .exec import (  # noqa: F401
    BaseExecutor, SequencedExecutor, ParallelExecutor, ThreadPoolExecutor,
    ForkJoinExecutor, TpuExecutor, Target, get_targets, default_target,
    get_future,
    ExecutionPolicy, seq, par, par_unseq, unseq, simd, par_simd,
    static_chunk_size, auto_chunk_size, dynamic_chunk_size,
    guided_chunk_size, num_cores,
)

# tpu_executor: the north-star spelling (BASELINE.json:
# `hpx::execution::par.on(tpu_executor{})`)
tpu_executor = TpuExecutor

# P2300 senders/receivers (hpx::execution::experimental)
from .exec import p2300  # noqa: F401
# the reference exposes this under hpx::execution::experimental
execution_experimental = p2300

# SPMD blocks (host plane + device/shard_map plane)
from .parallel.spmd import (  # noqa: F401
    SpmdBlock, define_spmd_block, device_spmd_block,
)

# pipeline parallelism (GPipe-style microbatched stages)
from .parallel.pipeline import Pipeline, PipelineStage  # noqa: F401

# plugin system (binary filters, coalescing, open registry)
from .dist import plugins  # noqa: F401

# -- parallel algorithms (M3) ------------------------------------------------
from .algo import (  # noqa: F401
    for_each, for_each_n, for_loop, transform, copy, copy_n, copy_if,
    fill, fill_n, generate, generate_n,
    reduce, transform_reduce, count, count_if,
    all_of, any_of, none_of, min_element, max_element, minmax_element,
    equal, mismatch, find, find_if,
    inclusive_scan, exclusive_scan, transform_inclusive_scan,
    transform_exclusive_scan, adjacent_difference, adjacent_find,
    sort, stable_sort, is_sorted, merge, reverse, rotate, unique, partition,
    induction, reduction,
)

# -- distributed runtime: localities, actions, AGAS (M5) ---------------------
from .dist import (  # noqa: F401
    plain_action, direct_action, async_action, post_action,
    resilient_action,
    init, finalize, get_runtime,
    find_here, find_all_localities, find_remote_localities,
    find_root_locality, get_num_localities,
)
from .dist import agas  # noqa: F401

# -- components: distributed objects (hpx::components) -----------------------
from .dist.components import (  # noqa: F401
    Client, Component, IdType,
    new_, new_sync, migrate, async_colocated,
    register_component_type, register_with_basename, find_from_basename,
)

# -- partitioned data + segmented algorithms (M6) ----------------------------
from .containers import (  # noqa: F401
    PartitionedVector, PartitionedVectorView, Segment, UnorderedMap,
)
from .dist.distribution_policies import (  # noqa: F401
    Binpacked, Colocated, ContainerLayout, PlacementPolicy, binpacked,
    colocated, container_layout, default_layout, target_layout,
)

# the HPX spelling
partitioned_vector = PartitionedVector

# -- collectives + channels (M7) ---------------------------------------------
from . import collectives  # noqa: F401
from .collectives import (  # noqa: F401
    Communicator, create_communicator, create_channel_communicator,
    ChannelCommunicator, DistributedChannel, DistributedLatch,
)

# -- block executor + 2-D halo substrate (M8) --------------------------------
from .exec.block import BlockExecutor, place_blocks  # noqa: F401

# -- services (M9) ------------------------------------------------------------
from .svc import performance_counters  # noqa: F401
from .svc.performance_counters import (  # noqa: F401
    CounterValue, GaugeCounter, CallbackCounter, ElapsedTimeCounter,
    AverageCounter, counter_name, parse_counter_name, register_counter,
    unregister_counter, discover_counters, query_counter, query_counters,
    print_counters, start_counter_printing,
)
from .svc.checkpoint import (  # noqa: F401
    Checkpoint, save_checkpoint, save_checkpoint_sync, restore_checkpoint,
    save_checkpoint_to_file, restore_checkpoint_from_file,
    save_sharded_state, save_sharded_state_to_file,
    restore_sharded_state, restore_sharded_state_from_file,
)
from .svc.resiliency import (  # noqa: F401
    AbortReplayException, AbortReplicateException, ReplayValidationError,
    ReplicateVotingError, async_replay, async_replay_validate,
    async_replicate, async_replicate_validate, async_replicate_vote,
    async_replay_distributed, majority_vote, ReplayExecutor,
    ReplicateExecutor,
)
from .svc.logging import get_logger, set_log_level  # noqa: F401
from .svc.iostreams import cout, cerr  # noqa: F401
from .svc import profiling  # noqa: F401
from .svc import tracing  # noqa: F401
from .svc.tracing import (  # noqa: F401
    Tracer, active_tracer, start_tracing, stop_tracing,
)
