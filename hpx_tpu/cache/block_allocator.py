"""Fixed-size KV-block allocator: the AGAS move applied to decode memory.

Reference analog: `containers/partitioned_vector.py` stores data at
rest as fixed-size segments behind an address map; this module is the
same discipline for data in flight — decode-time K/V lives in ONE
preallocated pool of `[num_blocks, block_size, n_kv, head_dim]` rows
per layer, and requests hold *block ids*, never rows. The allocator is
pure host-side bookkeeping (free list + ref counts) so it is testable
without jax; the device pools it indexes live with their owner
(`models/serving.ContinuousServer(paged=True)`).

Ref counting is what makes prefix sharing safe: a block chain published
into the radix tree (`cache/radix.py`) and matched by three live
requests has refcount 4 (tree + 3 readers); it returns to the free
list only when the last holder drops it. Copy-on-write (`fork`) covers
the writer case: a holder that must mutate a block it shares gets a
fresh exclusive block (and the caller copies the device rows).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core.errors import CacheOOM
from ..svc import faultinject
from ..synchronization import Mutex

__all__ = ["BlockAllocator", "CacheOOM", "block_bytes",
           "blocks_for_budget"]

# storage bytes per KV element, by `hpx.cache.kv_dtype`. The scale
# sidecar rides separately: quantized pools (int8 AND fp8 — both
# 1 byte/elem) carry one f32 scale per (block, kv-head) per pool
# (K and V each), accounted by block_bytes.
_KV_ITEMSIZE = {"bf16": 2, "f32": 4, "int8": 1, "fp8": 1}
_SCALE_BYTES = 4          # f32 per (block, kv-head) sidecar entry
_QUANTIZED_KV = ("int8", "fp8")   # kv_dtypes that ride a scale sidecar


def block_bytes(block_size: int, n_kv: int, head_dim: int,
                kv_dtype: str = "bf16", layers: int = 1) -> int:
    """HBM bytes ONE pool block costs across `layers` layers, K and V
    pools both, INCLUDING the quantized-dtype scale sidecar — the unit
    for dtype-aware pool sizing and for the bytes/token roofline
    counters (cache/counters.py). int8 and fp8 (e4m3) both store
    1 byte/elem — half of bf16, a quarter of an f32 compute dtype; the
    sidecar adds 4 bytes per (block, kv-head) per pool, amortized to
    noise for any real block_size * head_dim."""
    if kv_dtype not in _KV_ITEMSIZE:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}; expected one "
                         f"of {sorted(_KV_ITEMSIZE)}")
    rows = block_size * n_kv * head_dim * _KV_ITEMSIZE[kv_dtype]
    sidecar = n_kv * _SCALE_BYTES if kv_dtype in _QUANTIZED_KV else 0
    return 2 * layers * (rows + sidecar)          # K pool + V pool


def blocks_for_budget(budget_bytes: int, block_size: int, n_kv: int,
                      head_dim: int, kv_dtype: str = "bf16",
                      layers: int = 1) -> int:
    """How many pool blocks fit an HBM budget at this geometry/dtype —
    the dtype-aware inverse of block_bytes (int8 fits ~2x the blocks
    of bf16). Always at least 1 (the reserved trash block)."""
    per = block_bytes(block_size, n_kv, head_dim, kv_dtype, layers)
    return max(1, budget_bytes // per)


class BlockAllocator:
    """Free-list + ref-count accounting for `num_blocks` fixed-size
    blocks of `block_size` token rows each.

    Allocation order is deterministic (LIFO free list seeded
    0..num_blocks-1 reversed, so fresh pools hand out 0, 1, 2, ...):
    paged-vs-dense token equality tests rely on runs being repeatable,
    and debugging a block-map is far easier when ids are stable.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 kv_dtype: str = "bf16") -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if kv_dtype not in _KV_ITEMSIZE:
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}; expected "
                             f"one of {sorted(_KV_ITEMSIZE)}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # storage dtype of the pools this allocator's ids index —
        # quantized pools (int8/fp8) carry a [num_blocks, n_kv] f32
        # scale sidecar per pool, sized/accounted via
        # block_bytes/pool_bytes
        self.kv_dtype = kv_dtype
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref: Dict[int, int] = {}
        self._lock = Mutex()
        # cumulative counters (cache/counters.py reads these)
        self.total_allocs = 0
        self.total_frees = 0
        self.total_cow_copies = 0

    # -- queries ----------------------------------------------------------

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.num_blocks - len(self._free)

    def refcount(self, bid: int) -> int:
        with self._lock:
            return self._ref.get(bid, 0)

    # -- lifecycle --------------------------------------------------------

    def alloc(self) -> int:
        """One fresh block at refcount 1, or CacheOOM when the pool is
        exhausted (callers evict-and-retry; see serving._alloc_block).
        An installed fault injector can raise InjectedOOM here — a
        CacheOOM subclass, so it walks the same evict→retry→shed
        ladder a genuinely exhausted pool does."""
        faultinject.check("alloc")
        with self._lock:
            if not self._free:
                raise CacheOOM(
                    f"KV pool exhausted: all {self.num_blocks} blocks "
                    "in use", "BlockAllocator.alloc")
            bid = self._free.pop()
            self._ref[bid] = 1
            self.total_allocs += 1
            return bid

    def incref(self, bid: int) -> int:
        with self._lock:
            n = self._ref.get(bid, 0)
            if n < 1:
                raise ValueError(f"incref on unallocated block {bid}")
            self._ref[bid] = n + 1
            return n + 1

    def decref(self, bid: int) -> bool:
        """Drop one reference; returns True when this freed the block
        (refcount hit zero and it went back on the free list)."""
        with self._lock:
            n = self._ref.get(bid, 0)
            if n < 1:
                raise ValueError(f"decref on unallocated block {bid}")
            if n > 1:
                self._ref[bid] = n - 1
                return False
            del self._ref[bid]
            self._free.append(bid)
            self.total_frees += 1
            return True

    def fork(self, bid: int) -> tuple:
        """Copy-on-write: make `bid` safely writable by THIS holder.

        Exclusive already (refcount 1): returns ``(bid, False)`` — write
        in place. Shared: drops this holder's ref, allocates a fresh
        block, and returns ``(new_bid, True)`` — the caller must copy
        the device rows old→new before writing (the allocator never
        touches device memory). Raises CacheOOM like alloc()."""
        with self._lock:
            n = self._ref.get(bid, 0)
            if n < 1:
                raise ValueError(f"fork of unallocated block {bid}")
            if n == 1:
                return bid, False
            if not self._free:
                raise CacheOOM(
                    f"KV pool exhausted: cannot copy-on-write shared "
                    f"block {bid} ({self.num_blocks} blocks in use)",
                    "BlockAllocator.fork")
            self._ref[bid] = n - 1
            new = self._free.pop()
            self._ref[new] = 1
            self.total_allocs += 1
            self.total_cow_copies += 1
            return new, True

    def pool_pspec(self, tp_axis: Optional[str] = None) -> tuple:
        """PartitionSpec entries (as a plain tuple — this module stays
        jax-free) for the `[num_blocks, block_size, n_kv, head_dim]`
        pools this allocator's ids index on a (dp, tp) mesh: kv-heads
        shard over `tp_axis`, the BLOCK AXIS never shards. Replicating
        blocks over dp is the sharded-serving invariant that keeps
        every block id resolvable on every data-parallel shard, so a
        per-shard table gather never crosses shards (the HPX010
        fence); tp slices only the head dim, which block ids never
        address."""
        return (None, None, tp_axis, None)

    def scale_pspec(self, tp_axis: Optional[str] = None) -> tuple:
        """PartitionSpec entries for the `[num_blocks, n_kv]` int8/fp8
        scale sidecars — same placement rule as `pool_pspec` (blocks
        replicated, kv-heads over tp)."""
        return (None, tp_axis)

    def pool_bytes(self, n_kv: int, head_dim: int,
                   layers: int = 1) -> int:
        """Total HBM footprint of the pools this allocator sizes
        (scale sidecars included for int8/fp8) — what the HBM-budget
        counters and `blocks_for_budget` callers reason about."""
        return self.num_blocks * block_bytes(
            self.block_size, n_kv, head_dim, self.kv_dtype, layers)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "kv_dtype": self.kv_dtype,
                "free": len(self._free),
                "in_use": self.num_blocks - len(self._free),
                "total_allocs": self.total_allocs,
                "total_frees": self.total_frees,
                "total_cow_copies": self.total_cow_copies,
            }
