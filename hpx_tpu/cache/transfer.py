"""Block-granular KV transfer between localities.

Reference analog: none in HPX proper — this is the disaggregated-serving
KV shipping protocol (prefill worker → decode worker) the MPMD split in
`models/disagg.py` rides on, in the spirit of the parcel layer: framed,
checksummed, idempotent.

Wire unit is the :class:`KVSegment`: a contiguous run of finished
prefill rows for one request, framed with (rid, seq, start, ntok,
total) and a sha256 over header+payload. For disaggregated prefill the
payload is the RAW compute-dtype scratch rows the prefill worker's
chunk programs produced — the receiver splices them into its own pool
through the server's `_paged_splice_prog`, which quantizes identically
to the colocated path, so pool bytes on the decode worker equal what a
colocated prefill would have written. That identity is what lets
decode failover replay from shipped blocks byte-exactly.

The host-tier promotion path (`cache/tier.py`) rides the same framing
with a different payload contract: RAW POOL-DTYPE block rows (int8 /
fp8 quantized bytes, axis 2 in tokens) plus a second segment stream of
f32 scale sidecars (axis 2 in blocks), spliced back dequantize-free at
the promoted block ids. The checksum covers dtype+shape+bytes either
way, so both contracts get the same corruption/idempotency guarantees.

Delivery discipline (the robustness core):

* **framing** — `start`/`ntok` position each segment absolutely in the
  sequence; `total` is the full prefill length, so completeness is a
  local check (covered == total), independent of arrival order.
* **checksums** — sha256 over header+payload; a corrupt segment raises
  :class:`TransferCorruptError` (a ``NetworkError``, so the sender's
  bounded-retry resend loop treats it as transient and re-ships).
* **idempotent re-delivery** — the receiver dedups on (rid, seq):
  duplicates (sender retry after a lost ack, injected ``parcel.dup``)
  are ACKED AND DROPPED, never double-ingested; the ack carries
  ``dup=True`` so chaos tests can count them.

The receiver holds HOST rows only — no KV blocks are allocated until
the decode server admits the sequence (`admit_prefilled`), so an
aborted/abandoned transfer can never leak pool blocks.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.errors import NetworkError
from ..synchronization import Mutex

__all__ = [
    "KVSegment",
    "TransferCorruptError",
    "TransferReceiver",
    "make_segment",
]


class TransferCorruptError(NetworkError):
    """Segment checksum mismatch: the payload was damaged in flight.
    A ``NetworkError`` so resend loops classify it as transient."""

    def __init__(self, rid: str, seq: int, message: str = ""):
        super().__init__(
            message or f"KV segment {rid}:{seq} failed checksum",
            "TransferReceiver.ingest")
        self.rid = rid
        self.seq = seq


def _digest(rid: str, seq: int, start: int, ntok: int, total: int,
            payload: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(f"{rid}|{seq}|{start}|{ntok}|{total}|"
             f"{payload.dtype.str}|{payload.shape}".encode())
    h.update(np.ascontiguousarray(payload).tobytes())
    return h.hexdigest()


@dataclass(frozen=True)
class KVSegment:
    """One framed, checksummed run of prefill KV rows.

    payload shape: [n_layers, 2, ntok, n_kv, head_dim] in the model's
    COMPUTE dtype (pre-quantization — see module docstring).
    """

    rid: str          # request id (router-global)
    seq: int          # segment index within the request, 0-based
    start: int        # absolute first token row this segment covers
    ntok: int         # rows in this segment
    total: int        # full prefill length of the request
    payload: np.ndarray = field(repr=False)
    checksum: str = ""

    @property
    def key(self) -> str:
        return f"{self.rid}:{self.seq}"

    def verify(self) -> bool:
        return self.checksum == _digest(self.rid, self.seq, self.start,
                                        self.ntok, self.total,
                                        self.payload)


def make_segment(rid: str, seq: int, start: int, total: int,
                 payload: np.ndarray) -> KVSegment:
    """Frame + checksum one run of rows (payload axis 2 is tokens)."""
    payload = np.ascontiguousarray(payload)
    ntok = int(payload.shape[2])
    return KVSegment(rid=rid, seq=seq, start=start, ntok=ntok,
                     total=total, payload=payload,
                     checksum=_digest(rid, seq, start, ntok, total,
                                      payload))


class TransferReceiver:
    """Decode-worker side: reassemble segments into contiguous prefill
    rows, exactly once. Thread-safe (ingest arrives on parcel-handler
    pool threads; assemble runs on the serving loop)."""

    def __init__(self) -> None:
        self._lock = Mutex()
        # rid -> {seq: KVSegment}; dropped at assemble/abort
        self._segs: Dict[str, Dict[int, KVSegment]] = {}
        self._aborted: set = set()
        self.dups = 0          # duplicate deliveries acked+dropped
        self.corrupt = 0       # checksum failures rejected

    def ingest(self, seg: KVSegment) -> Dict[str, object]:
        """Accept one segment; returns the ack ``{"rid", "seq", "dup"}``.

        Duplicates (same rid+seq already held) are acked with
        ``dup=True`` and dropped. Corrupt payloads raise
        :class:`TransferCorruptError` — the sender re-ships."""
        if not seg.verify():
            with self._lock:
                self.corrupt += 1
            raise TransferCorruptError(seg.rid, seg.seq)
        with self._lock:
            if seg.rid in self._aborted:
                # late segment for an aborted transfer: ack so the
                # sender stops resending, keep nothing
                return {"rid": seg.rid, "seq": seg.seq, "dup": True}
            per = self._segs.setdefault(seg.rid, {})
            if seg.seq in per:
                self.dups += 1
                return {"rid": seg.rid, "seq": seg.seq, "dup": True}
            per[seg.seq] = seg
        return {"rid": seg.rid, "seq": seg.seq, "dup": False}

    def covered(self, rid: str) -> int:
        """Distinct token rows held for `rid`."""
        with self._lock:
            per = self._segs.get(rid, {})
            return sum(s.ntok for s in per.values())

    def complete(self, rid: str) -> bool:
        """True when held segments cover the full prefill length."""
        with self._lock:
            per = self._segs.get(rid)
            if not per:
                return False
            total = next(iter(per.values())).total
            got = sorted((s.start, s.ntok) for s in per.values())
        pos = 0
        for start, ntok in got:
            if start != pos:
                return False
            pos = start + ntok
        return pos == total

    def assemble(self, rid: str) -> np.ndarray:
        """Concatenate a complete transfer into one
        [n_layers, 2, total, n_kv, head_dim] array and release the
        held segments."""
        if not self.complete(rid):
            with self._lock:
                per = self._segs.get(rid, {})
                held = sorted(s.seq for s in per.values())
            raise NetworkError(
                f"KV transfer {rid} incomplete: segments {held}",
                "TransferReceiver.assemble")
        with self._lock:
            per = self._segs.pop(rid)
        segs = sorted(per.values(), key=lambda s: s.start)
        return np.concatenate([s.payload for s in segs], axis=2)

    def abort(self, rid: str) -> None:
        """Drop everything held for `rid`; later segments for it are
        acked (dup=True) but not kept."""
        with self._lock:
            self._segs.pop(rid, None)
            self._aborted.add(rid)

    def pending(self) -> List[str]:
        with self._lock:
            return sorted(self._segs)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pending": len(self._segs), "dups": self.dups,
                    "corrupt": self.corrupt}
