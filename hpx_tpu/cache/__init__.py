"""Paged KV-cache subsystem: block pools, page tables, radix prefix
reuse, and the performance counters that observe them.

Host-side bookkeeping lives here (`BlockAllocator`, `PageTable`,
`RadixCache`); the jit-side gather/scatter numerics live in
`hpx_tpu/ops/paged_attention.py`; `models/serving.ContinuousServer`
wires both together behind its `paged=True` flag. Tunables come from
the `hpx.cache.*` config keys (`core/config.py`).
"""

from .block_allocator import BlockAllocator, CacheOOM
from .counters import register_fleet, register_server
from .page_table import PageTable, materialize
from .radix import RadixCache, prefix_hashes

__all__ = [
    "BlockAllocator",
    "CacheOOM",
    "PageTable",
    "RadixCache",
    "materialize",
    "prefix_hashes",
    "register_fleet",
    "register_server",
]
