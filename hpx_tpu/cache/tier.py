"""Host-RAM KV tier: where radix evictions go instead of oblivion.

At scale the shared-prefix working set (system prompts, few-shot
templates, multi-turn sessions) dwarfs HBM but fits comfortably in
host RAM. `RadixCache` eviction used to be leaf-LRU to oblivion —
every budget-pressure evict turned a future prefix hit back into a
full re-prefill. This module adds the tier below: on eviction the
radix tree's demote hook hands the victim block here, and the tier
copies its RAW pool rows (quantized bytes for int8/fp8 pools, plus
the f32 scale sidecars — dequantize-free in both directions) into
pooled host buffers keyed by the chain's `prefix_digest` hash.

Budget and eviction mirror the hot tier one level down: a byte budget
(`hpx.cache.tier.host_budget_mb`), LRU-to-oblivion as the FINAL tier.
Buffers are pooled (free-listed by shape/dtype and recycled across
demotions) so steady-state demotion traffic allocates nothing — the
stand-in for pinned host memory while the device tunnel is down.

Restoration is gated, not automatic: `RestoreGate` estimates restore
time (bytes over a measured host→device copy bandwidth, plus a fixed
splice overhead) against re-prefill time (tokens times the live
per-token prefill cost from `svc/progprof`'s cb_chunk records, config
fallback before any samples exist) and only promotes when copy-in
beats recompute by `hpx.cache.tier.min_speedup` — the cost-model-
arbitrated execution choice applied to cache restoration. The server
re-ships promoted rows through the `cache/transfer.py` KVSegment
framing (checksums, idempotent seq numbers) and splices the raw bytes
back at the promoted block ids, so a restored block dequantizes
bit-identically to the block that was demoted.

Consistency argument (why snapshots cannot go stale): published radix
blocks are immutable — decode writes COW-fork shared blocks and the
admit splice redirects matched-prefix entries to the trash block — so
the bytes demoted at eviction are the block's FINAL bytes. A tier hit
can therefore be spliced back without any validation beyond the chain
hash + token-chunk equality check.

Checkout discipline (hpxlint HPX015 covers this file): `checkout()`
removes an entry and marks its buffers in flight; every checkout must
reach exactly one of `checkin()` (promotion landed — recycle buffers)
or `putback()` (promotion aborted — reinsert the entry). In-flight
buffers at drain are LEAKS: `leaked_buffers()` is the host-side twin
of `BlockAllocator.leaked_blocks()`.
"""

from __future__ import annotations

import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..synchronization import Mutex

__all__ = ["HostTier", "RestoreGate", "flight_snapshot"]

# live tiers, for svc/flight shed bundles (weak: a server dropping its
# tier must not be kept alive by observability)
_TIERS: "weakref.WeakSet[HostTier]" = weakref.WeakSet()


class _TierEntry:
    """One demoted block: raw pool rows + scale sidecars, host-side."""

    __slots__ = ("chain", "parent", "key", "rows", "scales", "nbytes",
                 "last_used")

    def __init__(self, chain: int, parent: int, key: Tuple[int, ...],
                 rows: np.ndarray, scales: Optional[np.ndarray],
                 nbytes: int) -> None:
        self.chain = chain          # 64-bit chain hash of the prefix
        self.parent = parent        # chain hash of the parent prefix
        self.key = key              # the block's token chunk
        self.rows = rows            # [n_layers, 2, bs, n_kv, head_dim]
        self.scales = scales        # [n_layers, 2, n_kv] f32 or None
        self.nbytes = nbytes
        self.last_used = 0


class HostTier:
    """Byte-budgeted host store of demoted KV blocks, LRU to oblivion.

    Thread-safe; the radix demote hook runs under the radix lock and
    the serving loop promotes concurrently with fleet digest pulls."""

    _POOL_SPARES = 8    # recycled buffers kept per (shape, dtype)

    def __init__(self, budget_bytes: int, block_size: int) -> None:
        self.budget_bytes = max(0, int(budget_bytes))
        self.block_size = int(block_size)
        self._lock = Mutex()
        self._entries: Dict[int, _TierEntry] = {}
        self._clock = 0
        self._bytes_held = 0
        self._inflight = 0          # checked-out entries not yet back
        self._pool: Dict[Tuple[Tuple[int, ...], str],
                         List[np.ndarray]] = {}
        # cumulative stats (cache/counters.py exports these)
        self.total_demoted = 0      # blocks accepted from eviction
        self.total_promoted = 0     # blocks restored to the device
        self.total_dropped = 0      # blocks LRU'd out / rejected
        self.total_declined = 0     # gate said re-prefill instead
        self.hit_depth_blocks = 0   # cumulative promoted chain depth
        _TIERS.add(self)

    # -- pooled host buffers ---------------------------------------------

    def _buf(self, like: np.ndarray) -> np.ndarray:
        key = (tuple(like.shape), like.dtype.str)
        free = self._pool.get(key)
        buf = free.pop() if free else np.empty(like.shape, like.dtype)
        np.copyto(buf, like, casting="no")
        return buf

    def _recycle(self, arr: Optional[np.ndarray]) -> None:
        if arr is None:
            return
        key = (tuple(arr.shape), arr.dtype.str)
        free = self._pool.setdefault(key, [])
        if len(free) < self._POOL_SPARES:
            free.append(arr)

    # -- demote / probe / checkout ---------------------------------------

    def demote(self, chain: int, parent: int, key: Sequence[int],
               rows: np.ndarray, scales: Optional[np.ndarray]) -> bool:
        """Accept one evicted block's raw rows. Returns True when the
        tier retained it (the radix eviction counts it as demoted,
        not dropped); False when the budget cannot hold it."""
        nbytes = rows.nbytes + (scales.nbytes if scales is not None
                                else 0)
        if nbytes > self.budget_bytes:
            with self._lock:
                self.total_dropped += 1
            return False
        with self._lock:
            old = self._entries.pop(chain, None)
            if old is not None:
                self._bytes_held -= old.nbytes
                self._recycle(old.rows)
                self._recycle(old.scales)
            e = _TierEntry(int(chain), int(parent),
                           tuple(int(t) for t in key),
                           self._buf(rows),
                           None if scales is None else self._buf(scales),
                           nbytes)
            self._clock += 1
            e.last_used = self._clock
            self._entries[chain] = e
            self._bytes_held += nbytes
            self.total_demoted += 1
            self._evict_locked()
        return True

    def _evict_locked(self) -> None:
        while self._bytes_held > self.budget_bytes and self._entries:
            victim = min(self._entries.values(),
                         key=lambda e: e.last_used)
            del self._entries[victim.chain]
            self._bytes_held -= victim.nbytes
            self._recycle(victim.rows)
            self._recycle(victim.scales)
            self.total_dropped += 1

    def probe(self, chain: int, key: Sequence[int]) -> Optional[int]:
        """Membership test for the two-tier match: the entry's nbytes
        when the tier holds `chain` AND its token chunk equals `key`
        (the collision guard), else None. Touches recency — a probed
        chain is about to matter."""
        want = tuple(int(t) for t in key)
        with self._lock:
            e = self._entries.get(int(chain))
            if e is None or e.key != want:
                return None
            self._clock += 1
            e.last_used = self._clock
            return e.nbytes

    def checkout(self, chain: int) -> Optional[_TierEntry]:
        """Remove and return the entry for `chain` (None when a
        concurrent demotion LRU'd it out). The entry's buffers are in
        flight until `checkin` (promoted) or `putback` (aborted)."""
        with self._lock:
            e = self._entries.pop(int(chain), None)
            if e is None:
                return None
            self._bytes_held -= e.nbytes
            self._inflight += 1
            return e

    def checkin(self, entry: _TierEntry) -> None:
        """Promotion landed: the radix tree holds the chain hot again
        (it will re-demote on the next eviction), so the tier's copy
        retires and its buffers recycle."""
        with self._lock:
            self._inflight -= 1
            self._recycle(entry.rows)
            self._recycle(entry.scales)
            self.total_promoted += 1
            self.hit_depth_blocks += 1

    def putback(self, entry: _TierEntry) -> None:
        """Promotion aborted (allocation failed mid-chain, corrupt
        frame): reinsert the entry so the data survives for the next
        hit."""
        with self._lock:
            self._inflight -= 1
            self._clock += 1
            entry.last_used = self._clock
            self._entries[entry.chain] = entry
            self._bytes_held += entry.nbytes
            self._evict_locked()

    def declined(self, nblocks: int) -> None:
        """The crossover gate chose re-prefill over restore."""
        with self._lock:
            self.total_declined += int(nblocks)

    # -- observability ----------------------------------------------------

    def digest(self, max_entries: int = 64) -> List[int]:
        """MRU-first chain hashes, the cold mirror of
        `RadixCache.prefix_digest` — what a fleet router scores with
        the discounted `w_tier` weight."""
        with self._lock:
            ranked = sorted(self._entries.values(),
                            key=lambda e: -e.last_used)
            return [e.chain for e in ranked[:max(0, int(max_entries))]]

    def leaked_buffers(self) -> int:
        """Checked-out entries that never came back — host buffers a
        drained server would strand. Must be 0 at drain."""
        with self._lock:
            return self._inflight

    def clear(self) -> None:
        with self._lock:
            for e in self._entries.values():
                self._bytes_held -= e.nbytes
                self._recycle(e.rows)
                self._recycle(e.scales)
            self._entries.clear()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "tier_entries": len(self._entries),
                "tier_bytes_held": self._bytes_held,
                "tier_budget_bytes": self.budget_bytes,
                "tier_demoted": self.total_demoted,
                "tier_promoted": self.total_promoted,
                "tier_dropped": self.total_dropped,
                "tier_declined": self.total_declined,
                "tier_hit_depth_blocks": self.hit_depth_blocks,
                "tier_inflight": self._inflight,
            }


class RestoreGate:
    """Restore-vs-recompute crossover estimator.

    Promote a tier hit only when the estimated restore time (bytes
    over measured host→device bandwidth plus a fixed splice overhead)
    beats the estimated re-prefill time (tokens times the live
    per-token cost from progprof's cb_chunk records) by at least
    `min_speedup`. The bandwidth probe is injectable so tests can pin
    both gate outcomes; the default probe times one real host→device
    transfer of `hpx.cache.tier.probe_mb` and is measured lazily
    once — construction must not touch the device."""

    def __init__(self, min_speedup: Optional[float] = None,
                 probe_mb: Optional[int] = None,
                 prefill_cost_us: Optional[float] = None,
                 overhead_us: Optional[float] = None,
                 probe_fn=None) -> None:
        from ..core.config import runtime_config
        rc = runtime_config()
        self.min_speedup = (rc.get_float("hpx.cache.tier.min_speedup",
                                         1.0)
                            if min_speedup is None else
                            float(min_speedup))
        self.probe_mb = (rc.get_int("hpx.cache.tier.probe_mb", 4)
                         if probe_mb is None else int(probe_mb))
        self.prefill_cost_us = (
            rc.get_float("hpx.cache.tier.prefill_cost_us", 50.0)
            if prefill_cost_us is None else float(prefill_cost_us))
        self.overhead_us = (
            rc.get_float("hpx.cache.tier.restore_overhead_us", 200.0)
            if overhead_us is None else float(overhead_us))
        self._probe_fn = probe_fn
        self._bandwidth: Optional[float] = None

    # -- inputs -----------------------------------------------------------

    def bandwidth(self) -> float:
        """Host→device copy bandwidth in bytes/s, measured once."""
        if self._bandwidth is None:
            nbytes = max(1, self.probe_mb) << 20
            if self._probe_fn is not None:
                self._bandwidth = max(1.0, float(self._probe_fn(nbytes)))
            else:
                self._bandwidth = max(1.0, _copy_probe(nbytes))
        return self._bandwidth

    def prefill_s_per_token(self) -> float:
        """Live per-token prefill cost from the profiler's cb_chunk
        records (exec seconds over chunk-width tokens, all buckets
        pooled), config fallback before any chunk has run or when
        profiling is off."""
        from ..svc import progprof
        prof = progprof.active_profiler()
        if prof is not None:
            sec = tok = 0.0
            for rec in prof.records():
                if rec.label != "cb_chunk":
                    continue
                key = rec.key
                width = (key[2] if isinstance(key, tuple)
                         and len(key) > 2
                         and isinstance(key[2], int) else 0)
                if width and rec.exec_hist.count:
                    sec += rec.exec_hist.sum
                    tok += rec.exec_hist.count * width
            if tok:
                return sec / tok
        return self.prefill_cost_us * 1e-6

    # -- the decision -----------------------------------------------------

    def should_promote(self, ntok: int,
                       nbytes: int) -> Tuple[bool, Dict[str, float]]:
        """(promote?, estimate) for restoring `nbytes` of tier rows
        that would otherwise re-prefill `ntok` tokens."""
        restore_s = (nbytes / self.bandwidth()
                     + self.overhead_us * 1e-6)
        prefill_s = ntok * self.prefill_s_per_token()
        est = {"restore_s": restore_s, "prefill_s": prefill_s,
               "bandwidth_bytes_s": self.bandwidth(),
               "min_speedup": self.min_speedup}
        return prefill_s >= restore_s * self.min_speedup, est


def _copy_probe(nbytes: int) -> float:
    """Default bandwidth probe: time one host→device put of `nbytes`
    and return bytes/s. jax imports lazily — the tier itself is
    numpy-only."""
    import jax
    import jax.numpy as jnp
    buf = np.empty(nbytes, np.uint8)
    t0 = time.perf_counter()
    jax.block_until_ready(jnp.asarray(buf))
    dt = max(1e-9, time.perf_counter() - t0)
    return nbytes / dt


def flight_snapshot() -> Dict[str, float]:
    """Aggregate tier state for svc/flight shed/failover bundles —
    the same shape whether one server or a fleet is live; {} when no
    tier exists (the flight doc key stays optional)."""
    tiers = list(_TIERS)
    if not tiers:
        return {}
    agg: Dict[str, float] = {"tiers": len(tiers)}
    for t in tiers:
        for k, v in t.stats().items():
            if k == "tier_budget_bytes":
                continue
            agg[k] = agg.get(k, 0) + v
    return agg
