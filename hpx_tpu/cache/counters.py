"""Serving + cache performance counters, per ContinuousServer.

Registers into `svc/performance_counters.py`'s registry, following its
built-in discipline: counters OBSERVE through weakrefs and read 0 once
the server is gone — observability must never keep a retired server
(and its device pools) alive. A refresh hook (run before every
discovery/query, via `register_refresh_hook`) garbage-collects the
names of dead servers so `discover_counters` stays truthful.

Every server gets the serving counters::

    /serving{locality#L/server#i}/queue/depth       queued requests
    /serving{locality#L/server#i}/slots/occupancy   live slots / slots
    /serving{locality#L/server#i}/tokens/rate       decode tokens/sec
                                                    (windowed RateCounter)
    /serving{locality#L/server#i}/prefill/chunks    prefill chunk dispatches
    /serving{locality#L/server#i}/prefill/pending   in-flight chunked prefills
    /serving{locality#L/server#i}/programs/cache-hits    program-cache hits
    /serving{locality#L/server#i}/programs/cache-misses  program builds (compiles)

Speculative servers (``hpx.serving.spec.enable``) add::

    /serving{locality#L/server#i}/spec/drafted          draft tokens proposed
    /serving{locality#L/server#i}/spec/accepted         draft tokens accepted
    /serving{locality#L/server#i}/spec/acceptance-rate  accepted / drafted
    /serving{locality#L/server#i}/spec/tokens-per-step  emitted / spec steps

(the default ``hpx.trace.counters`` pattern ``/serving*`` matches
these, so the Chrome-trace counter sampler picks up an
acceptance-rate track with no extra config).

MoE servers (``cfg.n_experts > 0``) add the expert-routing feed::

    /serving{locality#L/server#i}/moe/tokens-routed   routing claims honored
    /serving{locality#L/server#i}/moe/tokens-dropped  claims over capacity
    /serving{locality#L/server#i}/moe/expert#e/occupancy  latest capacity
                                                          fraction, per expert

Tuned servers (``hpx.tune.enable``) add the closed-loop controller's
accounting — ``/serving{...}/tune/ticks``, ``tune/evals``,
``tune/probes``, ``tune/accepts``, ``tune/reverts``, ``tune/holds``.

Paged servers additionally export the cache counters::

    /cache{locality#L/server#i}/hit-rate                radix prefix hit rate
    /cache{locality#L/server#i}/blocks/in-use           pool blocks allocated
    /cache{locality#L/server#i}/blocks/free             pool blocks free
    /cache{locality#L/server#i}/blocks/radix-held       blocks retained by the tree
    /cache{locality#L/server#i}/count/evictions         LRU chains dropped
    /cache{locality#L/server#i}/prefill-tokens/saved    prompt tokens NOT recomputed
    /cache{locality#L/server#i}/prefill-tokens/computed prompt tokens prefilled
    /cache{locality#L/server#i}/count/hbm-read-per-token  mapped blocks streamed
                                                          per decode token
    /cache{locality#L/server#i}/bytes/hbm-read-per-token  dtype-aware bytes of the
                                                          above (int8/fp8 scale
                                                          sidecars incl. — fp8 pools
                                                          report the ~0.25x ratio vs
                                                          an f32 compute dtype)

Tiered servers (``hpx.cache.tier.enable``) add the host-tier feed::

    /cache{locality#L/server#i}/tier/bytes-held         host bytes retained
    /cache{locality#L/server#i}/tier/entries            demoted blocks held
    /cache{locality#L/server#i}/tier/count/demoted      evictions the tier kept
    /cache{locality#L/server#i}/tier/count/promoted     blocks restored to device
    /cache{locality#L/server#i}/tier/count/dropped      LRU'd out of the tier
    /cache{locality#L/server#i}/tier/count/declined     gate chose re-prefill
    /cache{locality#L/server#i}/tier/hit-depth-blocks   cumulative promoted depth
    /cache{locality#L/server#i}/tier/promote-latency-s  promotion histogram
                                                        (+ derived pNN counters)
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Tuple

from ..svc import performance_counters as pc
from ..synchronization import Mutex

__all__ = ["register_fleet", "register_server"]

_lock = Mutex()
_servers: Dict[int, Tuple["weakref.ref", List[str]]] = {}
_next_idx = 0
_fleets: Dict[int, Tuple["weakref.ref", List[str]]] = {}
_next_fleet_idx = 0


def _read(ref, fn):
    """Weakref-observing callback: a collected server reads 0.0."""
    def value() -> float:
        srv = ref()
        if srv is None:
            return 0.0
        return float(fn(srv))
    return value


def register_server(srv) -> str:
    """Register one server's counters; returns its instance name
    (``server#<i>``). Called from ContinuousServer.__init__."""
    global _next_idx
    with _lock:
        idx = _next_idx
        _next_idx += 1
    inst = f"server#{idx}"
    ref = weakref.ref(srv)
    names: List[str] = []

    def put(object_: str, counter: str, c: pc.Counter) -> None:
        name = pc.counter_name(object_, counter, inst)
        pc.register_counter(name, c)
        names.append(name)

    put("serving", "queue/depth",
        pc.CallbackCounter(_read(ref, lambda s: len(s._queue))))
    put("serving", "slots/occupancy",
        pc.CallbackCounter(_read(ref, lambda s: sum(
            r is not None for r in s._slot_req) / max(1, s.slots))))
    # the server's own windowed tokens/sec counter, registered as-is
    # (RateCounter IS a Counter); it holds no reference back
    put("serving", "tokens/rate", srv._rate)
    put("serving", "prefill/chunks",
        pc.CallbackCounter(_read(ref, lambda s: s._chunks)))
    put("serving", "prefill/pending",
        pc.CallbackCounter(_read(ref, lambda s: len(s._pending))))
    put("serving", "programs/cache-hits",
        pc.CallbackCounter(_read(ref, lambda s: s._prog_hits)))
    put("serving", "programs/cache-misses",
        pc.CallbackCounter(_read(ref, lambda s: s._prog_misses)))

    # fault/recovery ladder observability (svc/faultinject +
    # ContinuousServer.fault_stats): injected faults seen, step
    # retries, checkpoint restores, typed sheds, degradations
    put("serving", "faults/injected",
        pc.CallbackCounter(_read(ref, lambda s: s._flt_injected)))
    put("serving", "faults/retried",
        pc.CallbackCounter(_read(ref, lambda s: s._flt_retried)))
    put("serving", "faults/restored",
        pc.CallbackCounter(_read(ref, lambda s: s._flt_restored)))
    put("serving", "faults/shed",
        pc.CallbackCounter(_read(ref, lambda s: s._flt_shed)))
    put("serving", "faults/degraded",
        pc.CallbackCounter(_read(ref, lambda s: s._flt_degraded)))
    put("serving", "faults/restore-p99-s",
        pc.CallbackCounter(_read(ref, lambda s: s.fault_stats()
                           ["restore_p99_s"])))

    # SLO latency distributions: the server's live HistogramCounters
    # registered as-is (a histogram IS a Counter, value = mean, and
    # holds no reference back) plus derived pNN quantile counters —
    # /serving{...}/latency/ttft-s, .../ttft-s/p99, ...
    from ..svc.metrics import register_histogram
    _HIST_KEYS = (("ttft", "latency/ttft-s"),
                  ("queue_wait", "latency/queue-wait-s"),
                  ("transfer", "latency/transfer-s"),
                  ("decode_stall", "latency/decode-stall-s"),
                  ("e2e", "latency/e2e-s"))
    for attr, cname in _HIST_KEYS:
        names.extend(register_histogram("serving", cname,
                                        srv.hist[attr], inst))

    if getattr(srv, "_spec", False):
        put("serving", "spec/drafted",
            pc.CallbackCounter(_read(ref, lambda s: s._spec_drafted)))
        put("serving", "spec/accepted",
            pc.CallbackCounter(_read(ref, lambda s: s._spec_accepted)))
        put("serving", "spec/acceptance-rate",
            pc.CallbackCounter(_read(ref, lambda s: (
                s._spec_accepted / s._spec_drafted
                if s._spec_drafted else 0.0))))
        put("serving", "spec/tokens-per-step",
            pc.CallbackCounter(_read(ref, lambda s: (
                s._spec_emitted / s._spec_steps
                if s._spec_steps else 0.0))))

    if getattr(srv.cfg, "n_experts", 0) > 0:
        # expert-parallel MoE decode routing (models/moe): routing
        # claims routed vs dropped-over-capacity (capacity-factor
        # knob), plus each expert's latest occupancy fraction —
        # /serving{...}/moe/*. Fed from the per-step stats vector the
        # decode/verify programs return, drained at flush boundaries.
        put("serving", "moe/tokens-routed",
            pc.CallbackCounter(_read(ref, lambda s: s._moe_routed)))
        put("serving", "moe/tokens-dropped",
            pc.CallbackCounter(_read(ref, lambda s: s._moe_dropped)))
        for e in range(srv.cfg.n_experts):
            put("serving", f"moe/expert#{e}/occupancy",
                pc.CallbackCounter(_read(
                    ref, lambda s, e=e: s._moe_occ[e])))

    if getattr(srv, "_tuner", None) is not None:
        # closed-loop tuner observability (svc/autotune): tick/probe/
        # accept/revert totals — /serving{...}/tune/*. The default
        # hpx.trace.counters pattern /serving* samples these too, so
        # a trace shows tuner activity alongside the decode track.
        put("serving", "tune/ticks",
            pc.CallbackCounter(_read(ref, lambda s: s._tuner.ticks)))
        put("serving", "tune/evals",
            pc.CallbackCounter(_read(ref, lambda s: s._tuner.evals)))
        put("serving", "tune/probes",
            pc.CallbackCounter(_read(ref, lambda s: s._tuner.probes)))
        put("serving", "tune/accepts",
            pc.CallbackCounter(_read(ref, lambda s: s._tuner.accepts)))
        put("serving", "tune/reverts",
            pc.CallbackCounter(_read(ref, lambda s: s._tuner.reverts)))
        put("serving", "tune/holds",
            pc.CallbackCounter(_read(ref, lambda s: s._tuner.holds)))

    if getattr(srv, "_alerts", None) is not None:
        # SLO burn-rate alerting (svc/slo_alerts): evaluation and
        # transition totals — /serving{...}/alerts/*. `active` is the
        # number of rules currently in the alerting state, so a trace
        # or /varz scrape shows incident windows as a step function.
        put("serving", "alerts/evals",
            pc.CallbackCounter(_read(ref, lambda s: s._alerts.evals)))
        put("serving", "alerts/fired",
            pc.CallbackCounter(_read(ref, lambda s: s._alerts.fired)))
        put("serving", "alerts/cleared",
            pc.CallbackCounter(_read(ref, lambda s: s._alerts.cleared)))
        put("serving", "alerts/active",
            pc.CallbackCounter(_read(ref, lambda s: s._alerts.active())))

    if getattr(srv, "paged", False):
        put("cache", "hit-rate",
            pc.CallbackCounter(_read(ref, lambda s: s._radix.hit_rate())))
        put("cache", "blocks/in-use",
            pc.CallbackCounter(_read(ref, lambda s: s._alloc.in_use)))
        put("cache", "blocks/free",
            pc.CallbackCounter(_read(ref, lambda s: s._alloc.free_count)))
        put("cache", "blocks/radix-held",
            pc.CallbackCounter(_read(ref, lambda s: s._radix.blocks_held)))
        put("cache", "count/evictions",
            pc.CallbackCounter(
                _read(ref, lambda s: s._radix.total_evictions)))
        put("cache", "prefill-tokens/saved",
            pc.CallbackCounter(_read(ref, lambda s: s._prefill_saved)))
        put("cache", "prefill-tokens/computed",
            pc.CallbackCounter(_read(ref, lambda s: s._prefill_computed)))
        # decode-attention HBM roofline feed: mapped blocks (and their
        # dtype-aware bytes, int8/fp8 scale sidecars included) streamed
        # per generated token — see ContinuousServer.hbm_read_stats
        put("cache", "count/hbm-read-per-token",
            pc.CallbackCounter(_read(ref, lambda s: s.hbm_read_stats()
                               ["hbm_read_blocks_per_token"])))
        put("cache", "bytes/hbm-read-per-token",
            pc.CallbackCounter(_read(ref, lambda s: s.hbm_read_stats()
                               ["hbm_read_bytes_per_token"])))
        if getattr(srv, "_tier", None) is not None:
            # host-RAM demotion tier (cache/tier.py): occupancy,
            # demote/promote/drop/decline totals, cumulative hit
            # depth, and the promotion-latency histogram (with its
            # derived pNN quantile counters) — /cache{...}/tier/*
            put("cache", "tier/bytes-held",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.stats()["tier_bytes_held"])))
            put("cache", "tier/entries",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.stats()["tier_entries"])))
            put("cache", "tier/count/demoted",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.total_demoted)))
            put("cache", "tier/count/promoted",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.total_promoted)))
            put("cache", "tier/count/dropped",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.total_dropped)))
            put("cache", "tier/count/declined",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.total_declined)))
            put("cache", "tier/hit-depth-blocks",
                pc.CallbackCounter(_read(
                    ref, lambda s: s._tier.hit_depth_blocks)))
            names.extend(register_histogram(
                "cache", "tier/promote-latency-s", srv._tier_hist,
                inst))

    with _lock:
        _servers[idx] = (ref, names)
    return inst


def register_fleet(rt) -> str:
    """Register one FleetRouter's ``/serving{...}/fleet/*`` counters;
    returns its instance name (``fleet#<i>``). Called from
    svc/fleet.FleetRouter.__init__, same weakref discipline as
    :func:`register_server` — a collected router reads 0 and its
    names GC out of discovery.

    Per-worker queue-depth counters register up to the AUTOSCALE
    CEILING (``fleet/worker#k/queue-depth``): an index past the
    current pool reads 0, so scale-up/-down changes values, never the
    counter namespace (discovery stays stable across a wave)."""
    global _next_fleet_idx
    with _lock:
        idx = _next_fleet_idx
        _next_fleet_idx += 1
    inst = f"fleet#{idx}"
    ref = weakref.ref(rt)
    names: List[str] = []

    def put(counter: str, c: pc.Counter) -> None:
        name = pc.counter_name("serving", counter, inst)
        pc.register_counter(name, c)
        names.append(name)

    put("fleet/placed/prefix",
        pc.CallbackCounter(_read(ref, lambda r: r._placed_prefix)))
    put("fleet/placed/load",
        pc.CallbackCounter(_read(ref, lambda r: r._placed_load)))
    put("fleet/digest/staleness-s",
        pc.CallbackCounter(_read(ref,
                                 lambda r: r.digest_staleness_s())))
    put("fleet/autoscale/up",
        pc.CallbackCounter(_read(ref, lambda r: r._autoscale_up)))
    put("fleet/autoscale/down",
        pc.CallbackCounter(_read(ref, lambda r: r._autoscale_down)))
    put("fleet/prefill-tokens/saved",
        pc.CallbackCounter(_read(ref,
                                 lambda r: r.prefill_tokens_saved)))
    put("fleet/workers/decode",
        pc.CallbackCounter(_read(ref,
                                 lambda r: len(r._alive(r._decode)))))
    put("fleet/queue/depth",
        pc.CallbackCounter(_read(ref, lambda r: (len(r._qi)
                                                 + len(r._qb)))))
    for k in range(int(rt._pool_max)):
        put(f"fleet/worker#{k}/queue-depth",
            pc.CallbackCounter(_read(
                ref, lambda r, k=k: r.worker_queue_depth(k))))

    # fleet-wide SLO quantiles: merge() of every per-worker histogram,
    # computed at query time (so the value is BY CONSTRUCTION equal to
    # the merge of the per-worker distributions, the acceptance
    # contract serving_bench asserts) — /serving{locality#L/fleet#i}/
    # latency/ttft-s/p99 etc.
    from ..svc.metrics import (LATENCY_KEYS, configured_quantiles,
                               quantile_label)
    _CNAMES = {"ttft": "latency/ttft-s",
               "queue_wait": "latency/queue-wait-s",
               "transfer": "latency/transfer-s",
               "decode_stall": "latency/decode-stall-s",
               "e2e": "latency/e2e-s"}
    for key in LATENCY_KEYS:
        for q in configured_quantiles():
            put(f"{_CNAMES[key]}/{quantile_label(q)}",
                pc.CallbackCounter(_read(
                    ref, lambda r, k=key, q=q:
                    r.merged_hist()[k].quantile(q))))

    with _lock:
        _fleets[idx] = (ref, names)
    return inst


def _refresh() -> None:
    """Refresh hook: unregister the counters of collected servers (the
    reverse of the builtins' lazily-appearing pools — servers lazily
    DISAPPEAR)."""
    with _lock:
        dead = [(i, names) for i, (ref, names) in _servers.items()
                if ref() is None]
        for i, _ in dead:
            del _servers[i]
        dead_fleets = [(i, names) for i, (ref, names)
                       in _fleets.items() if ref() is None]
        for i, _ in dead_fleets:
            del _fleets[i]
    for _, names in dead + dead_fleets:
        for n in names:
            pc.unregister_counter(n)


pc.register_refresh_hook(_refresh)
