"""Zero-model prompt-lookup drafting: n-gram continuation mining.

The draft source that needs no second checkpoint (the "prompt lookup
decoding" trick): if the last n tokens of a slot's history (prompt +
everything generated so far) occurred earlier in that same history,
propose the tokens that followed the earlier occurrence as the draft.
Summarization, code editing, and any workload with self-repetition
accept these drafts at high rates; on non-repetitive text the proposal
is simply rejected by the verify pass — correctness never depends on
draft quality, only throughput does.

Pure host-side integer matching — O(n * len(history)) per call with
tiny constants, negligible next to a decode step — and deterministic:
longest n first, most recent earlier occurrence first, so replays
draft identically.
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["propose"]


def propose(history: Sequence[int], k: int, max_n: int = 3) -> List[int]:
    """Up to `k` draft tokens continuing `history`, or [] if no suffix
    n-gram (n = max_n down to 1) recurs earlier in the history. The
    continuation may be shorter than `k` when the match sits near the
    end; matches that overlap the suffix itself are allowed — that is
    exactly what makes periodic output (the high-acceptance case)
    match."""
    length = len(history)
    if k <= 0 or length < 2:
        return []
    for n in range(min(max_n, length - 1), 0, -1):
        suffix = tuple(int(t) for t in history[length - n:])
        for i in range(length - n - 1, -1, -1):
            if tuple(int(t) for t in history[i:i + n]) != suffix:
                continue
            cont = history[i + n:i + n + k]
            if cont:
                return [int(t) for t in cont]
    return []
