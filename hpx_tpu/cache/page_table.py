"""Per-request logical→physical block maps, materializable for jit.

A `PageTable` is the request-side view of the paged KV cache: an
ordered list of physical block ids covering the request's logical token
positions `[0, tokens)`. Logical block ``i`` holds token rows
``[i*block_size, (i+1)*block_size)``; position ``p`` lives at physical
row ``(table[p // block_size], p % block_size)``.

`as_row` / `materialize` turn host tables into padded int32 arrays the
jitted step/prefill programs index with — the analog of
partitioned_vector's segment map, materialized per step instead of per
container. Padding uses a caller-supplied block id (the server's
reserved trash block) so dead slots and unmapped tail positions always
resolve to a writable-but-never-read physical block: masked lanes can
scatter harmlessly instead of corrupting live data.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["PageTable", "device_table", "materialize", "occupancy"]

_UIDS = itertools.count()


class PageTable:
    """Block map for one request: `blocks[i]` backs logical block i.

    `version` counts mutations through the mutator methods
    (`append_block` / `replace_block` / `extend_blocks`); the serving
    step loop keys its materialized-table device cache on it, so a
    steady-state decode step re-uploads nothing. Callers that poke
    `blocks` directly must bump `version` themselves.
    """

    def __init__(self, block_size: int) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.blocks: List[int] = []
        self.tokens = 0            # logical length in token rows
        self.version = 0           # bumped by every mutator
        self.uid = next(_UIDS)     # process-unique (id() can recycle)

    def append_block(self, bid: int) -> None:
        self.blocks.append(bid)
        self.version += 1

    def extend_blocks(self, bids: Sequence[int]) -> None:
        self.blocks.extend(bids)
        self.version += 1

    def replace_block(self, idx: int, bid: int) -> None:
        """Swap the physical block backing logical block `idx`
        (copy-on-write fork installs the private copy here)."""
        self.blocks[idx] = bid
        self.version += 1

    def rollback(self, tokens: int) -> List[int]:
        """Rewind the logical frontier to `tokens` rows and return the
        block ids no longer needed to cover it (caller owns the
        decrefs). This is how speculative rejection stays cheap: draft
        rows past the accepted frontier are simply abandoned — the
        physical rows still hold stale K/V, but the decode mask only
        exposes positions < `tokens`, and any block kept here has its
        stale tail rewritten by the next write at that position before
        it can ever be attended."""
        if tokens < 0:
            raise ValueError(f"cannot rollback to {tokens} tokens")
        keep = self.blocks_for(tokens)
        dropped = self.blocks[keep:]
        if dropped:
            del self.blocks[keep:]
            self.version += 1
        self.tokens = tokens
        return dropped

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self.block_size

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to cover `tokens` rows."""
        return -(-tokens // self.block_size)

    def block_of(self, pos: int) -> int:
        """Physical block id backing logical position `pos`."""
        return self.blocks[pos // self.block_size]

    def as_row(self, max_blocks: int, pad: int) -> np.ndarray:
        """Padded int32 row `[max_blocks]` for the jitted programs."""
        if len(self.blocks) > max_blocks:
            raise ValueError(
                f"page table has {len(self.blocks)} blocks, row width "
                f"is {max_blocks}")
        row = np.full((max_blocks,), pad, np.int32)
        row[:len(self.blocks)] = self.blocks
        return row


def occupancy(tables: Sequence[Optional[PageTable]]) -> int:
    """Total MAPPED blocks across live slots (dead/None slots count 0)
    — the table-occupancy input to the decode-attention
    hbm-read-per-token counters: blocks a decode step actually streams
    per slot, as opposed to the padded `max_blocks` row width."""
    return sum(len(pt.blocks) for pt in tables if pt is not None)


def materialize(tables: Sequence[Optional[PageTable]], max_blocks: int,
                pad: int) -> np.ndarray:
    """Stack per-slot tables into the `[slots, max_blocks]` int32 array
    one decode step consumes; None slots (dead) pad entirely."""
    out = np.full((len(tables), max_blocks), pad, np.int32)
    for i, pt in enumerate(tables):
        if pt is not None:
            out[i, :len(pt.blocks)] = pt.blocks
    return out


def device_table(tables: Sequence[Optional[PageTable]],
                 max_blocks: int, pad: int, mesh=None,
                 dp_axis: str = "dp", residency: str = "sharded"):
    """Materialize and PLACE the `[slots, max_blocks]` table for the
    jitted programs. Single-device (``mesh=None``): a plain device
    array. On a mesh the block ids stay GLOBAL (pools replicate their
    block axis over dp, so any id resolves on any shard) and only the
    slot axis placement is a choice, `hpx.serving.mesh.
    table_residency`:

    * ``"sharded"`` — rows shard over `dp_axis`: each dp shard holds
      exactly its slots' rows, matching the shard_map block spec with
      zero resharding on entry (the default).
    * ``"replicated"`` — every device holds the full table; shard_map
      entry slices it. Costs slots/dp × more table bytes per device
      (noise at real sizes) but makes the host upload a single
      broadcast — an escape hatch for debugging placement issues.

    jax is imported lazily: this module stays importable (and its host
    bookkeeping testable) without jax installed."""
    arr = materialize(tables, max_blocks, pad)
    import jax
    import jax.numpy as jnp
    if mesh is None:
        return jnp.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec
    if residency not in ("sharded", "replicated"):
        raise ValueError(
            "hpx.serving.mesh.table_residency must be 'sharded' or "
            f"'replicated', got {residency!r}")
    spec = (PartitionSpec(dp_axis, None) if residency == "sharded"
            else PartitionSpec())
    return jax.device_put(arr, NamedSharding(mesh, spec))
