"""Token-prefix radix tree: prompt prefixes → ref-counted block chains.

The serving-side reuse structure (the move SGLang's RadixAttention and
vLLM's prefix caching share): when a request retires, its FULL prompt
blocks are published here keyed by their token content; a later request
whose prompt starts with the same tokens matches the chain and skips
prefilling those positions entirely — admit prefills only the suffix.

Nodes are block-granular (each edge covers exactly `block_size`
tokens), which keeps the tree aligned with the unit of allocation:
matching, sharing, and eviction all move whole blocks, so a matched
chain can be handed to a `PageTable` verbatim and an evicted leaf frees
exactly one pool block. The tree holds ONE allocator reference per
retained block; matched requests take their own (dropped at retire), so
`refcount == 1` is precisely "retained but idle" — the evictable state.

Eviction is leaf-LRU under a configurable block budget (the HBM-budget
knob `hpx.cache.radix_budget_blocks`), plus on-demand via `evict(n)`
when the allocator reports OOM (serving's OOM→evict→retry path). A
logical clock orders recency — deterministic replay matters more here
than wall time.

Eviction is no longer unconditionally to oblivion: when a `demote_hook`
is installed (the host tier in `cache/tier.py`), each victim block's
raw rows are offered to the tier BEFORE the tree reference drops, and
`evict` reports the `(demoted, dropped)` split. `match_tiered` is the
two-tier read path: the hot walk of `match`, extended by consecutive
host-tier probes keyed by the continuation chain hashes — the server
decides per hit (crossover gate) whether to restore or re-prefill.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..svc import tracing
from ..synchronization import Mutex
from .block_allocator import BlockAllocator

__all__ = ["RadixCache", "prefix_hashes"]


def _chunk_bytes(chunk: Sequence[int]) -> bytes:
    return b"".join(int(t).to_bytes(8, "little", signed=True)
                    for t in chunk)


def _chain(parent: bytes, chunk: Sequence[int]) -> bytes:
    return hashlib.blake2b(parent + _chunk_bytes(chunk),
                           digest_size=8).digest()


def prefix_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """The router-side mirror of :meth:`RadixCache.prefix_digest`: one
    64-bit chain hash per whole-block prefix of `tokens` — entry ``i``
    fingerprints ``tokens[:(i+1)*block_size]``. A worker whose digest
    contains entry ``i`` retains that ENTIRE prefix (chain hashing
    makes a match positional, not positional-chunk-coincidental), so
    the longest matching entry is the worker's cached-prefix depth for
    this prompt."""
    out: List[int] = []
    parent = b""
    for s in range(0, len(tokens) - block_size + 1, block_size):
        parent = _chain(parent, tokens[s:s + block_size])
        out.append(int.from_bytes(parent, "little"))
    return out


class _Node:
    __slots__ = ("key", "bid", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], bid: int,
                 parent: Optional["_Node"]) -> None:
        self.key = key
        self.bid = bid
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0


class RadixCache:
    """Block-granular prefix tree over an allocator's block ids."""

    def __init__(self, allocator: BlockAllocator,
                 budget_blocks: Optional[int] = None) -> None:
        self.allocator = allocator
        self.block_size = allocator.block_size
        self.budget_blocks = budget_blocks
        self._root = _Node((), -1, None)
        self._clock = 0
        self._blocks_held = 0
        self._lock = Mutex()
        # demotion tier hand-off: called as hook(chain_hash,
        # parent_hash, token_chunk, block_id) BEFORE the tree
        # reference drops; a True return counts the eviction as
        # demoted rather than dropped. Hook failures never block
        # eviction — the block is dropped as before.
        self.demote_hook = None
        # cumulative stats (cache/counters.py reads these)
        self.tokens_requested = 0
        self.tokens_matched = 0
        self.total_evictions = 0
        self.total_demoted = 0
        self.total_dropped = 0
        self.total_inserts = 0

    # -- helpers ----------------------------------------------------------

    def _chunks(self, tokens: Sequence[int]):
        bs = self.block_size
        for s in range(0, len(tokens) - bs + 1, bs):
            yield tuple(int(t) for t in tokens[s:s + bs])

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    # -- queries ----------------------------------------------------------

    @property
    def blocks_held(self) -> int:
        with self._lock:
            return self._blocks_held

    def hit_rate(self) -> float:
        """Lifetime prefix hit rate: matched / requested prefill
        tokens (0.0 before any request)."""
        with self._lock:
            if not self.tokens_requested:
                return 0.0
            return self.tokens_matched / self.tokens_requested

    # -- match / insert ---------------------------------------------------

    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix of `tokens`, in whole blocks.

        Returns ``(matched_tokens, block_ids)``; the caller receives
        ONE allocator reference per returned block (its read lease —
        dropped when the request retires). Callers that must leave a
        suffix to prefill (serving always needs the last prompt
        token's logits) pass ``tokens[:-1]``."""
        with self._lock:
            self.tokens_requested += len(tokens)
            node = self._root
            bids: List[int] = []
            for chunk in self._chunks(tokens):
                child = node.children.get(chunk)
                if child is None:
                    break
                self.allocator.incref(child.bid)
                bids.append(child.bid)
                self._touch(child)
                node = child
            matched = len(bids) * self.block_size
            self.tokens_matched += matched
        if tracing.active_tracer() is not None:
            tracing.instant("cache.match", "cache", matched=matched,
                            requested=len(tokens), blocks=len(bids))
        return matched, bids

    def match_tiered(self, tokens: Sequence[int], tier
                     ) -> Tuple[int, List[int],
                                List[Tuple[int, Tuple[int, ...], int]]]:
        """Two-tier match: the hot walk of :meth:`match`, then — where
        the tree ran out — consecutive host-tier probes keyed by the
        continuation chain hashes. Returns ``(matched_tokens,
        block_ids, tier_ext)`` where ``tier_ext`` lists
        ``(chain_hash, token_chunk, nbytes)`` for the whole-block
        chunks the tier holds immediately past the hot match (stops at
        the first cold miss — tier chains are only restorable as a
        consecutive run). The caller holds NO tier references — it
        checks entries out explicitly once the crossover gate decides
        to promote."""
        chunks = []
        with self._lock:
            self.tokens_requested += len(tokens)
            node = self._root
            bids: List[int] = []
            parent = b""
            chunks = list(self._chunks(tokens))
            depth = 0
            for chunk in chunks:
                child = node.children.get(chunk)
                if child is None:
                    break
                parent = _chain(parent, chunk)
                self.allocator.incref(child.bid)
                bids.append(child.bid)
                self._touch(child)
                node = child
                depth += 1
            matched = len(bids) * self.block_size
            self.tokens_matched += matched
        # tier probes OUTSIDE the tree lock: the tier has its own lock
        # and a racing demotion only changes what probes hit, never
        # tree consistency
        ext: List[Tuple[int, Tuple[int, ...], int]] = []
        for chunk in chunks[depth:]:
            parent = _chain(parent, chunk)
            h = int.from_bytes(parent, "little")
            nb = tier.probe(h, chunk)
            if nb is None:
                break
            ext.append((h, chunk, int(nb)))
        if tracing.active_tracer() is not None:
            tracing.instant("cache.match", "cache", matched=matched,
                            requested=len(tokens), blocks=len(bids),
                            tier_blocks=len(ext))
        return matched, bids, ext

    def peek(self, tokens: Sequence[int], k: int) -> List[int]:
        """Read-only continuation probe for prompt-lookup drafting:
        walk the longest cached whole-block prefix of `tokens`, then
        follow the child chain whose keys continue the ragged tail and
        return up to `k` of the tokens that FOLLOW `tokens` in the
        tree. Unlike `match` this takes no allocator leases and does
        not touch recency or hit-rate stats — the caller only wants
        token VALUES to propose as a draft (the verify pass rejects
        bad guesses anyway), not the blocks behind them. Ties between
        sibling continuations go to the most recently used chain."""
        if k <= 0:
            return []
        with self._lock:
            node = self._root
            consumed = 0
            for chunk in self._chunks(tokens):
                child = node.children.get(chunk)
                if child is None:
                    break
                node = child
                consumed += self.block_size
            tail = tuple(int(t) for t in tokens[consumed:])
            out: List[int] = []
            while len(out) < k:
                best: Optional[_Node] = None
                for child in node.children.values():
                    if child.key[:len(tail)] != tail:
                        continue
                    if best is None or child.last_used > best.last_used:
                        best = child
                if best is None:
                    break
                out.extend(best.key[len(tail):])
                tail = ()
                node = best
            return [int(t) for t in out[:k]]

    def prefix_digest(self, max_entries: int = 64) -> List[int]:
        """Cheap placement fingerprint: the chain hash of every
        retained prefix (one 64-bit int per node — the blake2b of the
        parent's chain hash plus this node's block of tokens),
        MRU-first and truncated to `max_entries`.

        A fleet router compares these against
        :func:`prefix_hashes`(prompt) to score how deep each worker's
        tree covers a prompt WITHOUT shipping token lists around: the
        digest is O(entries) ints, refreshes on a knob-set interval,
        and staleness only mis-scores placement — never correctness
        (admission re-matches the real tree). Truncation drops the
        LRU tail first, which is exactly the part eviction takes
        next."""
        with self._lock:
            ranked: List[Tuple[int, int]] = []
            stack: List[Tuple[_Node, bytes]] = [(self._root, b"")]
            while stack:
                node, parent = stack.pop()
                if node is not self._root:
                    parent = _chain(parent, node.key)
                    ranked.append((node.last_used,
                                   int.from_bytes(parent, "little")))
                stack.extend((c, parent)
                             for c in node.children.values())
            ranked.sort(key=lambda e: -e[0])
            return [h for _, h in ranked[:max(0, int(max_entries))]]

    def insert(self, tokens: Sequence[int],
               block_ids: Sequence[int]) -> int:
        """Publish a block chain for `tokens` (full blocks only; a
        ragged tail is ignored). `block_ids[i]` must hold the K/V rows
        of tokens ``[i*bs, (i+1)*bs)``.

        Where the tree already retains an identical chunk the EXISTING
        block is kept (the caller's duplicate stays with the caller,
        who drops it at retire — dedup by token content). New chunks
        take one tree-owned reference on the caller's block. Returns
        the number of newly retained blocks, after trimming to the
        block budget."""
        fresh = 0
        with self._lock:
            node = self._root
            for i, chunk in enumerate(self._chunks(tokens)):
                child = node.children.get(chunk)
                if child is None:
                    bid = int(block_ids[i])
                    self.allocator.incref(bid)
                    child = _Node(chunk, bid, node)
                    node.children[chunk] = child
                    self._blocks_held += 1
                    self.total_inserts += 1
                    fresh += 1
                self._touch(child)
                node = child
            if self.budget_blocks is not None \
                    and self._blocks_held > self.budget_blocks:
                self._evict_locked(self._blocks_held - self.budget_blocks)
        return fresh

    # -- eviction ---------------------------------------------------------

    def evict(self, n: int) -> Tuple[int, int]:
        """Free up to `n` blocks by evicting idle leaf chains in LRU
        order. A leaf is evictable when the tree holds the ONLY
        reference (no live request reads it). Returns the
        ``(demoted, dropped)`` split — demoted blocks were accepted by
        the `demote_hook` tier before their device block freed,
        dropped ones are gone. Both free a device block, so
        ``sum(evict(n))`` is blocks freed — possibly 0 when everything
        retained is in use."""
        with self._lock:
            return self._evict_locked(n)

    def _chain_of(self, node: _Node) -> Tuple[bytes, bytes]:
        """(parent_hash, chain_hash) of `node`, by folding root→node."""
        keys: List[Tuple[int, ...]] = []
        walk: Optional[_Node] = node
        while walk is not None and walk is not self._root:
            keys.append(walk.key)
            walk = walk.parent
        parent = b""
        for k in reversed(keys[1:]):
            parent = _chain(parent, k)
        return parent, _chain(parent, node.key)

    def _evict_locked(self, n: int) -> Tuple[int, int]:
        demoted = dropped = 0
        while demoted + dropped < n:
            victim: Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node is self._root or node.children:
                    continue
                if self.allocator.refcount(node.bid) != 1:
                    continue          # a live request still reads it
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            kept = False
            hook = self.demote_hook
            if hook is not None:
                parent, chain = self._chain_of(victim)
                try:
                    # hook runs BEFORE the decref: the block is still
                    # tree-owned, so its rows are stable while the
                    # tier copies them out
                    kept = bool(hook(int.from_bytes(chain, "little"),
                                     int.from_bytes(parent, "little"),
                                     victim.key, victim.bid))
                except Exception:
                    kept = False      # a failing tier never blocks OOM
            self.allocator.decref(victim.bid)
            assert victim.parent is not None
            del victim.parent.children[victim.key]
            self._blocks_held -= 1
            self.total_evictions += 1
            if kept:
                demoted += 1
                self.total_demoted += 1
            else:
                dropped += 1
                self.total_dropped += 1
        if (demoted or dropped) and tracing.active_tracer() is not None:
            tracing.instant("cache.evict", "cache",
                            freed=demoted + dropped, demoted=demoted,
                            requested=n, held=self._blocks_held)
        return demoted, dropped

    def stats(self) -> Dict[str, float]:
        with self._lock:
            req, hit = self.tokens_requested, self.tokens_matched
            return {
                "blocks_held": self._blocks_held,
                "tokens_requested": req,
                "tokens_matched": hit,
                "hit_rate": (hit / req) if req else 0.0,
                "total_evictions": self.total_evictions,
                "total_demoted": self.total_demoted,
                "total_dropped": self.total_dropped,
                "total_inserts": self.total_inserts,
            }
