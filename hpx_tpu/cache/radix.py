"""Token-prefix radix tree: prompt prefixes → ref-counted block chains.

The serving-side reuse structure (the move SGLang's RadixAttention and
vLLM's prefix caching share): when a request retires, its FULL prompt
blocks are published here keyed by their token content; a later request
whose prompt starts with the same tokens matches the chain and skips
prefilling those positions entirely — admit prefills only the suffix.

Nodes are block-granular (each edge covers exactly `block_size`
tokens), which keeps the tree aligned with the unit of allocation:
matching, sharing, and eviction all move whole blocks, so a matched
chain can be handed to a `PageTable` verbatim and an evicted leaf frees
exactly one pool block. The tree holds ONE allocator reference per
retained block; matched requests take their own (dropped at retire), so
`refcount == 1` is precisely "retained but idle" — the evictable state.

Eviction is leaf-LRU under a configurable block budget (the HBM-budget
knob `hpx.cache.radix_budget_blocks`), plus on-demand via `evict(n)`
when the allocator reports OOM (serving's OOM→evict→retry path). A
logical clock orders recency — deterministic replay matters more here
than wall time.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..svc import tracing
from ..synchronization import Mutex
from .block_allocator import BlockAllocator

__all__ = ["RadixCache", "prefix_hashes"]


def _chunk_bytes(chunk: Sequence[int]) -> bytes:
    return b"".join(int(t).to_bytes(8, "little", signed=True)
                    for t in chunk)


def _chain(parent: bytes, chunk: Sequence[int]) -> bytes:
    return hashlib.blake2b(parent + _chunk_bytes(chunk),
                           digest_size=8).digest()


def prefix_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """The router-side mirror of :meth:`RadixCache.prefix_digest`: one
    64-bit chain hash per whole-block prefix of `tokens` — entry ``i``
    fingerprints ``tokens[:(i+1)*block_size]``. A worker whose digest
    contains entry ``i`` retains that ENTIRE prefix (chain hashing
    makes a match positional, not positional-chunk-coincidental), so
    the longest matching entry is the worker's cached-prefix depth for
    this prompt."""
    out: List[int] = []
    parent = b""
    for s in range(0, len(tokens) - block_size + 1, block_size):
        parent = _chain(parent, tokens[s:s + block_size])
        out.append(int.from_bytes(parent, "little"))
    return out


class _Node:
    __slots__ = ("key", "bid", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], bid: int,
                 parent: Optional["_Node"]) -> None:
        self.key = key
        self.bid = bid
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0


class RadixCache:
    """Block-granular prefix tree over an allocator's block ids."""

    def __init__(self, allocator: BlockAllocator,
                 budget_blocks: Optional[int] = None) -> None:
        self.allocator = allocator
        self.block_size = allocator.block_size
        self.budget_blocks = budget_blocks
        self._root = _Node((), -1, None)
        self._clock = 0
        self._blocks_held = 0
        self._lock = Mutex()
        # cumulative stats (cache/counters.py reads these)
        self.tokens_requested = 0
        self.tokens_matched = 0
        self.total_evictions = 0
        self.total_inserts = 0

    # -- helpers ----------------------------------------------------------

    def _chunks(self, tokens: Sequence[int]):
        bs = self.block_size
        for s in range(0, len(tokens) - bs + 1, bs):
            yield tuple(int(t) for t in tokens[s:s + bs])

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    # -- queries ----------------------------------------------------------

    @property
    def blocks_held(self) -> int:
        with self._lock:
            return self._blocks_held

    def hit_rate(self) -> float:
        """Lifetime prefix hit rate: matched / requested prefill
        tokens (0.0 before any request)."""
        with self._lock:
            if not self.tokens_requested:
                return 0.0
            return self.tokens_matched / self.tokens_requested

    # -- match / insert ---------------------------------------------------

    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix of `tokens`, in whole blocks.

        Returns ``(matched_tokens, block_ids)``; the caller receives
        ONE allocator reference per returned block (its read lease —
        dropped when the request retires). Callers that must leave a
        suffix to prefill (serving always needs the last prompt
        token's logits) pass ``tokens[:-1]``."""
        with self._lock:
            self.tokens_requested += len(tokens)
            node = self._root
            bids: List[int] = []
            for chunk in self._chunks(tokens):
                child = node.children.get(chunk)
                if child is None:
                    break
                self.allocator.incref(child.bid)
                bids.append(child.bid)
                self._touch(child)
                node = child
            matched = len(bids) * self.block_size
            self.tokens_matched += matched
        if tracing.active_tracer() is not None:
            tracing.instant("cache.match", "cache", matched=matched,
                            requested=len(tokens), blocks=len(bids))
        return matched, bids

    def peek(self, tokens: Sequence[int], k: int) -> List[int]:
        """Read-only continuation probe for prompt-lookup drafting:
        walk the longest cached whole-block prefix of `tokens`, then
        follow the child chain whose keys continue the ragged tail and
        return up to `k` of the tokens that FOLLOW `tokens` in the
        tree. Unlike `match` this takes no allocator leases and does
        not touch recency or hit-rate stats — the caller only wants
        token VALUES to propose as a draft (the verify pass rejects
        bad guesses anyway), not the blocks behind them. Ties between
        sibling continuations go to the most recently used chain."""
        if k <= 0:
            return []
        with self._lock:
            node = self._root
            consumed = 0
            for chunk in self._chunks(tokens):
                child = node.children.get(chunk)
                if child is None:
                    break
                node = child
                consumed += self.block_size
            tail = tuple(int(t) for t in tokens[consumed:])
            out: List[int] = []
            while len(out) < k:
                best: Optional[_Node] = None
                for child in node.children.values():
                    if child.key[:len(tail)] != tail:
                        continue
                    if best is None or child.last_used > best.last_used:
                        best = child
                if best is None:
                    break
                out.extend(best.key[len(tail):])
                tail = ()
                node = best
            return [int(t) for t in out[:k]]

    def prefix_digest(self, max_entries: int = 64) -> List[int]:
        """Cheap placement fingerprint: the chain hash of every
        retained prefix (one 64-bit int per node — the blake2b of the
        parent's chain hash plus this node's block of tokens),
        MRU-first and truncated to `max_entries`.

        A fleet router compares these against
        :func:`prefix_hashes`(prompt) to score how deep each worker's
        tree covers a prompt WITHOUT shipping token lists around: the
        digest is O(entries) ints, refreshes on a knob-set interval,
        and staleness only mis-scores placement — never correctness
        (admission re-matches the real tree). Truncation drops the
        LRU tail first, which is exactly the part eviction takes
        next."""
        with self._lock:
            ranked: List[Tuple[int, int]] = []
            stack: List[Tuple[_Node, bytes]] = [(self._root, b"")]
            while stack:
                node, parent = stack.pop()
                if node is not self._root:
                    parent = _chain(parent, node.key)
                    ranked.append((node.last_used,
                                   int.from_bytes(parent, "little")))
                stack.extend((c, parent)
                             for c in node.children.values())
            ranked.sort(key=lambda e: -e[0])
            return [h for _, h in ranked[:max(0, int(max_entries))]]

    def insert(self, tokens: Sequence[int],
               block_ids: Sequence[int]) -> int:
        """Publish a block chain for `tokens` (full blocks only; a
        ragged tail is ignored). `block_ids[i]` must hold the K/V rows
        of tokens ``[i*bs, (i+1)*bs)``.

        Where the tree already retains an identical chunk the EXISTING
        block is kept (the caller's duplicate stays with the caller,
        who drops it at retire — dedup by token content). New chunks
        take one tree-owned reference on the caller's block. Returns
        the number of newly retained blocks, after trimming to the
        block budget."""
        fresh = 0
        with self._lock:
            node = self._root
            for i, chunk in enumerate(self._chunks(tokens)):
                child = node.children.get(chunk)
                if child is None:
                    bid = int(block_ids[i])
                    self.allocator.incref(bid)
                    child = _Node(chunk, bid, node)
                    node.children[chunk] = child
                    self._blocks_held += 1
                    self.total_inserts += 1
                    fresh += 1
                self._touch(child)
                node = child
            if self.budget_blocks is not None \
                    and self._blocks_held > self.budget_blocks:
                self._evict_locked(self._blocks_held - self.budget_blocks)
        return fresh

    # -- eviction ---------------------------------------------------------

    def evict(self, n: int) -> int:
        """Free up to `n` blocks by dropping idle leaf chains in LRU
        order. A leaf is evictable when the tree holds the ONLY
        reference (no live request reads it). Returns blocks freed —
        possibly 0 when everything retained is in use."""
        with self._lock:
            return self._evict_locked(n)

    def _evict_locked(self, n: int) -> int:
        freed = 0
        while freed < n:
            victim: Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node is self._root or node.children:
                    continue
                if self.allocator.refcount(node.bid) != 1:
                    continue          # a live request still reads it
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            self.allocator.decref(victim.bid)
            assert victim.parent is not None
            del victim.parent.children[victim.key]
            self._blocks_held -= 1
            self.total_evictions += 1
            freed += 1
        if freed and tracing.active_tracer() is not None:
            tracing.instant("cache.evict", "cache", freed=freed,
                            requested=n, held=self._blocks_held)
        return freed

    def stats(self) -> Dict[str, float]:
        with self._lock:
            req, hit = self.tokens_requested, self.tokens_matched
            return {
                "blocks_held": self._blocks_held,
                "tokens_requested": req,
                "tokens_matched": hit,
                "hit_rate": (hit / req) if req else 0.0,
                "total_evictions": self.total_evictions,
                "total_inserts": self.total_inserts,
            }
