"""Local LCOs: channels, receive_buffer, and_gate, trigger, guards.

Reference analog: libs/core/lcos_local (hpx::lcos::local::channel,
one_element_channel, receive_buffer, and_gate, trigger, composable_guard).

These are futures-based coordination objects: get() returns a Future that
becomes ready when a matching set() arrives — producer and consumer never
need to rendezvous in time. receive_buffer is the halo-exchange workhorse
(1d_stencil_8 pattern): an indexed channel where slot t carries the
neighbor's boundary for timestep t.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Deque, Dict, Generic, List, Optional, TypeVar

from ..core.errors import Error, HpxError
from ..futures.future import Future, Promise, SharedState, make_ready_future
from ..synchronization import Mutex

T = TypeVar("T")


class Channel(Generic[T]):
    """Unbounded MPMC channel with futures-based receive.

    set(value): enqueue. get(): Future of the next value (FIFO pairing of
    pending gets with incoming sets). close(): further gets complete with
    an error; pending gets fail immediately (HPX channel semantics).
    """

    def __init__(self) -> None:
        self._lock = Mutex()
        self._values: Deque[Any] = collections.deque()
        self._waiters: Deque[SharedState] = collections.deque()
        self._closed = False

    def set(self, value: T) -> None:
        with self._lock:
            if self._closed:
                raise HpxError(Error.invalid_status, "channel is closed")
            waiter = self._waiters.popleft() if self._waiters else None
            if waiter is None:
                self._values.append(value)
        if waiter is not None:
            waiter.set_value(value)

    def get(self) -> Future[T]:
        with self._lock:
            if self._values:
                return make_ready_future(self._values.popleft())
            if self._closed:
                st: SharedState = SharedState()
                st.set_exception(
                    HpxError(Error.invalid_status, "channel is closed"))
                return Future(st)
            st = SharedState()
            self._waiters.append(st)
            return Future(st)

    def get_sync(self, timeout: Optional[float] = None) -> T:
        return self.get().get(timeout)

    def close(self) -> int:
        with self._lock:
            self._closed = True
            waiters = list(self._waiters)
            self._waiters.clear()
        for w in waiters:
            w.set_exception(HpxError(Error.invalid_status, "channel is closed"))
        return len(waiters)

    def __iter__(self):
        """Range-based iteration until close (HPX channel supports this)."""
        while True:
            try:
                yield self.get().get()
            except HpxError:
                return


class OneElementChannel(Generic[T]):
    """Single-slot channel: set blocks (fails) while a value is pending."""

    def __init__(self) -> None:
        self._lock = Mutex()
        self._slot: Optional[SharedState] = None  # ready value waiting
        self._waiter: Optional[SharedState] = None

    def set(self, value: T) -> None:
        with self._lock:
            if self._waiter is not None:
                w, self._waiter = self._waiter, None
            else:
                if self._slot is not None:
                    raise HpxError(Error.invalid_status,
                                   "one_element_channel already holds a value")
                self._slot = SharedState()
                self._slot.set_value(value)
                return
        w.set_value(value)

    def get(self) -> Future[T]:
        with self._lock:
            if self._slot is not None:
                f, self._slot = Future(self._slot), None
                return f
            if self._waiter is not None:
                raise HpxError(Error.invalid_status,
                               "one_element_channel already has a consumer")
            self._waiter = SharedState()
            return Future(self._waiter)


class ReceiveBuffer(Generic[T]):
    """Indexed channel: store_received(step, value) / receive(step)->Future.

    Reference analog: hpx::lcos::local::receive_buffer — the stencil halo
    buffer. Slots are created on first touch from either side; a consumed
    slot is erased.
    """

    def __init__(self) -> None:
        self._lock = Mutex()
        self._slots: Dict[int, SharedState] = {}

    def _slot(self, step: int) -> SharedState:
        st = self._slots.get(step)
        if st is None:
            st = self._slots[step] = SharedState()
        return st

    def store_received(self, step: int, value: T) -> None:
        with self._lock:
            st = self._slot(step)
        st.set_value(value)

    def receive(self, step: int) -> Future[T]:
        with self._lock:
            st = self._slot(step)
        # erase the slot once the pairing completes: each step is
        # produced and consumed exactly once
        st.add_callback(lambda _s: self._erase(step, st))
        return Future(st)

    def _erase(self, step: int, st: SharedState) -> None:
        with self._lock:
            if self._slots.get(step) is st:
                del self._slots[step]


class Trigger:
    """hpx::lcos::local::trigger: one-shot gate; wait() until set()."""

    def __init__(self) -> None:
        self._state = SharedState()

    def set(self) -> None:
        if not self._state.is_ready():
            try:
                self._state.set_value(None)
            except HpxError:
                pass

    def get_future(self) -> Future[None]:
        return Future(self._state)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._state.wait(timeout)


class AndGate:
    """hpx::lcos::local::and_gate: N-way synchronization generation.

    set(which) marks a slot; the gate's future fires when all N slots of
    the current generation are set; next_generation() re-arms. This is the
    building block HPX's collectives use server-side (SURVEY.md §3.6).
    """

    def __init__(self, count: int) -> None:
        self._count = count
        self._lock = Mutex()
        self._generation = 0
        self._set: set = set()
        self._state = SharedState()

    def set(self, which: int) -> None:
        with self._lock:
            if which in self._set:
                raise HpxError(Error.invalid_status,
                               f"and_gate slot {which} already set")
            self._set.add(which)
            fire = len(self._set) == self._count
            st = self._state
            gen = self._generation  # capture under lock: next_generation
            # may advance it before st.set_value runs
        if fire:
            st.set_value(gen)

    def get_future(self) -> Future[int]:
        return Future(self._state)

    def next_generation(self) -> int:
        with self._lock:
            if len(self._set) != self._count:
                raise HpxError(Error.invalid_status,
                               "and_gate generation still incomplete")
            self._generation += 1
            self._set.clear()
            self._state = SharedState()
            return self._generation

    @property
    def generation(self) -> int:
        return self._generation


_guard_swap_lock = Mutex()


class CompositeGuard:
    """composable_guard analog: serialize tasks touching a guarded object.

    async_(guard, f) runs f exclusively w.r.t. other tasks on the same
    guard(s), without blocking any thread: each guard keeps a tail future
    and new work is chained onto it via continuations.
    """

    def __init__(self) -> None:
        self._tail: Future = make_ready_future(None)

    def run(self, fn: Callable[[], Any]) -> Future:
        return run_guarded([self], fn)


def run_guarded(guards: List[CompositeGuard], fn: Callable[[], Any]) -> Future:
    """Run fn exclusively w.r.t. all given guards (hpx::run_guarded).

    Atomically swaps each guard's tail for this task's completion future,
    then fires fn once every previous tail is done. Lock-free execution:
    nothing blocks; exclusion is expressed purely through the future DAG.
    """
    from ..futures.combinators import when_all

    result: Promise = Promise()
    done = result.get_future()

    if not guards:
        from ..futures.async_ import async_
        return async_(fn)

    # Swap all tails atomically w.r.t. other run_guarded calls: two
    # concurrent multi-guard calls that interleave per-guard swaps would
    # otherwise each observe the other's completion future as a
    # predecessor — a circular dependency that never fires.
    with _guard_swap_lock:
        prevs: List[Future] = [g._tail for g in guards]
        for g in guards:
            g._tail = done

    def fire(_f: Future) -> None:
        try:
            result.set_value(fn())
        except BaseException as e:  # noqa: BLE001
            result.set_exception(e)

    # hpxlint: disable=HPX003 — fire() is the sink: it captures the
    # result/exception into `result`; the then-future is unused by design
    when_all(prevs).then(fire)
    return done
