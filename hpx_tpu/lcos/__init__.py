from .local import (  # noqa: F401
    AndGate,
    Channel,
    CompositeGuard,
    OneElementChannel,
    ReceiveBuffer,
    Trigger,
    run_guarded,
)
