"""Continuous batching: slot-based serving with per-slot positions.

Reference analog: none (HPX ships no serving runtime); this is the
standard TPU serving-loop shape — a FIXED batch of decode slots, each
at its OWN sequence position, stepping together in one jitted program.
Requests admit into free slots between steps (their prompt prefills on
the side as one window forward, then SPLICES into the slot's cache
rows) and retire on eos/max_new, so short requests never wait for long
ones and the chip never idles on a ragged batch. Static shapes
throughout: the per-row cache write is a batched scatter at the slot's
position vector, the causal mask compares against per-row positions,
and dead slots simply compute masked work (the XLA way — uniform work,
no dynamic batch).

Differential contract (the test): every request's tokens are EXACTLY
what transformer.generate() emits for that prompt alone — continuous
batching changes THROUGHPUT, never content.

Build on the single-sequence machinery in models/transformer.py; the
per-row-position block lives here (the scalar-position `_block_decode`
stays the lean fast path for uniform decode).
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.block_allocator import BlockAllocator, CacheOOM
from ..cache.page_table import PageTable, materialize
from ..cache.radix import RadixCache
from ..svc import tracing
from ..ops.paged_attention import gather_block_kv, paged_decode_attention
from .transformer import (
    _PREFILL_CHUNK,
    TransformerConfig,
    _cached_program,
    _decode_window,
    _dq,
    _ln,
    _prefill_window,
    _qkv_proj,
    _sample_row,
    _tree_key,
)

__all__ = ["ContinuousServer"]


def _normalize_key(key):
    """Coerce a user PRNG key to the raw uint32 layout the batched
    sampler needs: step() stacks the per-slot keys with jnp.stack, which
    fails (or silently mis-samples) on a mix of typed jax.random.key
    arrays and raw PRNGKey arrays. Typed keys are unwrapped via
    key_data; raw uint32 arrays pass through; anything else is rejected
    here at submit() instead of surfacing as a stack/shape error deep in
    step()."""
    try:
        arr = jnp.asarray(key)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"key is not a PRNG key (got {type(key).__name__}); pass "
            "jax.random.key(seed) or jax.random.PRNGKey(seed)") from e
    if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
        arr = jax.random.key_data(arr)
    raw = jax.random.PRNGKey(0)
    if arr.shape != raw.shape or arr.dtype != raw.dtype:
        raise ValueError(
            "key must be a typed jax.random.key(...) or a raw uint32 "
            f"jax.random.PRNGKey(...) of shape {raw.shape}; got shape "
            f"{arr.shape} dtype {arr.dtype}")
    return arr


def _rope_rows(x, pos, cfg: TransformerConfig):
    """Rotate-half RoPE with PER-ROW positions: x [B, 1, N, H],
    pos [B] int32 (transformer._rope takes one shared [S] vector)."""
    hd = x.shape[-1]
    half = hd // 2
    freq = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32)
                              / half)
    ang = pos.astype(jnp.float32)[:, None] * freq[None, :]  # [B, half]
    cos = jnp.cos(ang)[:, None, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _block_decode_rows(x, lp, kv, pos, cfg: TransformerConfig):
    """One decoder block for ONE new token per slot with PER-SLOT cache
    positions. x: [B, 1, D]; kv: (k_cache, v_cache) [B, Smax, Nkv, H];
    pos: [B] int32 — slot b's token lands at pos[b], and its query
    attends cache positions <= pos[b]. The write is a batched scatter
    (row b at pos[b]); everything else mirrors _block_decode."""
    kc, vc = kv
    b = x.shape[0]
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    if cfg.rope:
        q = _rope_rows(q, pos, cfg)
        k = _rope_rows(k, pos, cfg)
    rows = jnp.arange(b)
    kc = kc.at[rows, pos].set(k[:, 0])
    vc = vc.at[rows, pos].set(v[:, 0])
    nq, hd = q.shape[2], q.shape[3]
    nkv = kc.shape[2]
    g = nq // nkv
    qg = q.reshape(b, 1, nkv, g, hd)
    s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
    kpos = jnp.arange(kc.shape[1])
    live = kpos[None, :] <= pos[:, None]               # [B, Smax]
    s = jnp.where(live[:, None, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(b, 1, nq, hd)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        from .moe import moe_ffn
        from .transformer import _moe_cfg
        d = h.shape[-1]
        mcfg = dataclasses.replace(_moe_cfg(cfg),
                                   capacity_factor=float(cfg.n_experts))
        out, _aux = moe_ffn(h.reshape(b, d), lp["moe"], mcfg)
        return x + out.reshape(b, 1, d), (kc, vc)
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    return x + h, (kc, vc)


def _decode_rows(params, caches, tok, pos, cfg):
    """One token per slot through every block at per-slot positions;
    returns (caches, f32 logits [B, V])."""
    x = params["emb"][tok][:, None, :]
    new_caches = []
    for lp, kv in zip(params["layers"], caches):
        x, kv = _block_decode_rows(x, lp, kv, pos, cfg)
        new_caches.append(kv)
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return new_caches, logits[:, 0, :].astype(jnp.float32)


def _paged_block_rows(x, lp, pools, table, pos, cfg: TransformerConfig):
    """_block_decode_rows with the K/V rows living in a shared BLOCK
    POOL instead of per-slot dense buffers. x: [B, 1, D]; pools:
    (k_pool, v_pool) each [num_blocks, block_size, Nkv, H]; table:
    [B, max_blocks] int32 logical->physical block map; pos: [B] int32.
    Projections/rope/ffn are byte-identical to the dense path; only
    the cache write (scatter through the table) and read (gather in
    logical order — same row values at the same logical indices)
    differ, which is what keeps paged == dense token-exact."""
    kp, vp = pools
    b = x.shape[0]
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    if cfg.rope:
        q = _rope_rows(q, pos, cfg)
        k = _rope_rows(k, pos, cfg)
    att, kp, vp = paged_decode_attention(q, k[:, 0], v[:, 0], kp, vp,
                                         table, pos)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        from .moe import moe_ffn
        from .transformer import _moe_cfg
        d = h.shape[-1]
        mcfg = dataclasses.replace(_moe_cfg(cfg),
                                   capacity_factor=float(cfg.n_experts))
        out, _aux = moe_ffn(h.reshape(b, d), lp["moe"], mcfg)
        return x + out.reshape(b, 1, d), (kp, vp)
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    return x + h, (kp, vp)


def _paged_decode_rows(params, pools, tok, table, pos, cfg):
    """One token per slot through every block over paged pools;
    returns (pools, f32 logits [B, V]) — the _decode_rows analog."""
    x = params["emb"][tok][:, None, :]
    new_pools = []
    for lp, pl in zip(params["layers"], pools):
        x, pl = _paged_block_rows(x, lp, pl, table, pos, cfg)
        new_pools.append(pl)
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return new_pools, logits[:, 0, :].astype(jnp.float32)


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: Any                    # [plen] int32 host array
    max_new: int
    eos_id: Optional[int]
    temperature: float = 0.0       # 0: greedy; >0: sample with `key`
    key: Any = None
    tokens: List[int] = dataclasses.field(default_factory=list)


class ContinuousServer:
    """Slot-based continuous batching, per-request greedy or sampled.

    ::

        srv = ContinuousServer(params, cfg, slots=4, smax=256)
        a = srv.submit([3, 1, 4], max_new=16)
        b = srv.submit([2, 7], max_new=8, eos_id=0)
        out = srv.run()            # {a: [tokens...], b: [tokens...]}

    One jitted step decodes every live slot at its own position;
    finished slots retire and queued requests admit between steps
    (prompt prefilled as one window forward on a b=1 cache, K/V rows
    spliced into the slot). Dead slots compute masked no-op work
    (static shapes). PER-REQUEST decoding mode: greedy by default, or
    submit(..., temperature=t, key=k) to sample — the key folds follow
    generate()'s exactly (fold position, then row 0), so a sampled
    request emits the SAME tokens it would get from a solo
    generate(temperature=t, key=k) run. top_k truncation is not wired
    (it is a static shape choice; bucket by top_k if needed). Programs
    are memoized per (cfg, slots, smax) and per prompt length (bucket
    prompts in production)."""

    def __init__(self, params, cfg: TransformerConfig, slots: int = 4,
                 smax: int = 512, mesh=None, paged: bool = False,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 radix_budget_blocks: Optional[int] = None,
                 prefix_reuse: Optional[bool] = None):
        self.cfg = cfg
        self.slots = slots
        self.smax = smax
        self.mesh = mesh
        self.paged = bool(paged)
        nkv, hd = cfg.kv_heads, cfg.head_dim
        cache_sh = None
        if self.paged and mesh is not None:
            raise ValueError(
                "paged=True serving is single-device for now: shard "
                "the dense path (mesh=...) or run one paged server "
                "per replica")
        if mesh is not None:
            # GSPMD sharded serving: slots over dp, heads over tp. The
            # step/prefill/splice programs are UNCHANGED — placement
            # alone makes XLA partition them (einsum contractions over
            # the tp-sharded head dim close with compiler-inserted
            # all-reduces; no shard_map needed because nothing here
            # depends on per-device identity).
            from jax.sharding import NamedSharding, PartitionSpec as P
            from .transformer import (_decode_mesh_check,
                                      _decode_pspecs, _place)
            # the shared decode-mesh contract (axes, dense-only, head
            # divisibility); slots play the batch role
            try:
                _decode_mesh_check(cfg, mesh, slots)
            except ValueError as e:
                raise ValueError(str(e).replace("batch", "slots")) \
                    from None
            params = _place(params, _decode_pspecs(params, cfg), mesh)
            cache_sh = NamedSharding(mesh, P("dp", None, "tp", None))
        self.params = params
        self._cache_sh = cache_sh

        if self.paged:
            self._init_paged(block_size, num_blocks,
                             radix_budget_blocks, prefix_reuse)
            self._caches = None     # dense buffers never allocated
        else:
            def zeros():
                # allocate DIRECTLY in the sharded layout: a full
                # buffer on device 0 followed by a redistribute would
                # peak at the unsharded size there — the exact OOM
                # sharding avoids
                if cache_sh is not None:
                    return jnp.zeros((slots, smax, nkv, hd), cfg.dtype,
                                     device=cache_sh)
                return jnp.zeros((slots, smax, nkv, hd), cfg.dtype)
            self._caches = [(zeros(), zeros())
                            for _ in range(cfg.n_layers)]
        # windowed decode throughput, read by the serving counters
        from ..svc.performance_counters import RateCounter
        self._rate = RateCounter(window_s=5.0)
        # host-side slot state
        self._slot_req: List[Optional[_Request]] = [None] * slots
        self._pos = [0] * slots         # next write position per slot
        self._cur = [0] * slots         # token to feed next, per slot
        self._temp = [0.0] * slots      # per-slot temperature
        self._key = [jax.random.PRNGKey(0)] * slots
        self._queue: deque = deque()
        self._done: Dict[int, List[int]] = {}
        self._next_rid = 0
        from ..cache.counters import register_server
        self.counter_instance = register_server(self)

    def _init_paged(self, block_size, num_blocks, radix_budget_blocks,
                    prefix_reuse) -> None:
        """Resolve the hpx.cache.* knobs and build the paged state:
        one preallocated block pool per layer, the free-list/ref-count
        allocator over it, and the radix prefix tree."""
        from ..core.config import runtime_config
        cfg, slots, smax = self.cfg, self.slots, self.smax
        rc = runtime_config()
        if block_size is None:
            block_size = rc.get_int("hpx.cache.block_size", 16)
        bs = int(block_size)
        if bs < 1:
            raise ValueError(f"block_size must be >= 1, got {bs}")
        if smax % bs:
            raise ValueError(
                f"paged serving needs smax divisible by the block "
                f"size {bs}; got smax {smax} (use smax="
                f"{-(-smax // bs) * bs})")
        self.block_size = bs
        self._maxb = smax // bs     # table width: blocks per sequence
        if num_blocks is None:
            v = rc.get("hpx.cache.num_blocks", "auto")
            num_blocks = None if v in (None, "", "auto") else int(v)
        if num_blocks is None:
            # worst-case live demand (every slot at smax) + the trash
            # block + equal headroom for radix retention, so prefix
            # chains persist before OOM-eviction starts recycling them
            num_blocks = 2 * slots * self._maxb + 1
        if num_blocks < self._maxb + 1:
            raise ValueError(
                f"num_blocks {num_blocks} cannot hold one max-length "
                f"request ({self._maxb} blocks) plus the reserved "
                "trash block")
        if radix_budget_blocks is None:
            v = rc.get("hpx.cache.radix_budget_blocks", "auto")
            radix_budget_blocks = (None if v in (None, "", "auto")
                                   else int(v))
        if prefix_reuse is None:
            prefix_reuse = rc.get_bool("hpx.cache.prefix_reuse", True)
        self._prefix_reuse = bool(prefix_reuse)
        self._alloc = BlockAllocator(num_blocks, bs)
        # the trash block: dead slots' tables and table padding point
        # here, so masked decode lanes scatter into rows nothing reads
        self._trash = self._alloc.alloc()
        self._radix = RadixCache(self._alloc, radix_budget_blocks)
        nkv, hd = cfg.kv_heads, cfg.head_dim

        def pzeros():
            return jnp.zeros((num_blocks, bs, nkv, hd), cfg.dtype)
        self._pools = [(pzeros(), pzeros())
                       for _ in range(cfg.n_layers)]
        self._tables: List[Optional[PageTable]] = [None] * slots
        self._prefill_saved = 0
        self._prefill_computed = 0

    # -- jitted pieces (memoized on the baked constants) ----------------

    def _step_prog(self):
        cfg, slots, smax = self.cfg, self.slots, self.smax
        ck = ("cb_step", cfg, slots, smax, self.mesh,
              _tree_key(self.params))

        def build():
            cache_sh = self._cache_sh

            def step(params, caches, tok, pos, temp, keys):
                if cache_sh is not None:
                    caches = jax.tree.map(
                        lambda c: jax.lax.with_sharding_constraint(
                            c, cache_sh), caches)
                caches, logits = _decode_rows(params, caches, tok, pos,
                                              cfg)

                def pick(row, key, t, p):
                    greedy = jnp.argmax(row)
                    sampled = _sample_row(row, jnp.maximum(t, 1e-6),
                                          key, p, 0)
                    return jnp.where(t > 0, sampled, greedy)

                nxt = jax.vmap(pick)(logits, keys, temp, pos)
                return caches, nxt
            return jax.jit(step, donate_argnums=(1,))
        return _cached_program(ck, build)

    def _prefill_prog(self, plen: int):
        cfg, smax = self.cfg, self.smax
        ck = ("cb_prefill", cfg, plen, smax, self.mesh,
              _tree_key(self.params))

        def build():
            def prefill(params, prompt):
                nkv, hd = cfg.kv_heads, cfg.head_dim
                fresh = [
                    (jnp.zeros((1, smax, nkv, hd), cfg.dtype),
                     jnp.zeros((1, smax, nkv, hd), cfg.dtype))
                    for _ in range(cfg.n_layers)]
                # THE shared chunked prefill (same code path as
                # generate/beam/speculative): 128-token windows,
                # unembedding only on the last chunk
                return _prefill_window(params, cfg, fresh, prompt)
            return jax.jit(prefill)
        return _cached_program(ck, build)

    def _splice_prog(self, plen: int):
        slots, smax = self.slots, self.smax
        ck = ("cb_splice", self.cfg, plen, slots, smax, self.mesh,
              _tree_key(self.params))

        def build():
            cache_sh = self._cache_sh

            def splice(caches, one, slot):
                if cache_sh is not None:
                    caches = jax.tree.map(
                        lambda c: jax.lax.with_sharding_constraint(
                            c, cache_sh), caches)
                out = []
                for (kc, vc), (k1, v1) in zip(caches, one):
                    kc = jax.lax.dynamic_update_slice(
                        kc, k1[:, :plen].astype(kc.dtype),
                        (slot, 0, 0, 0))
                    vc = jax.lax.dynamic_update_slice(
                        vc, v1[:, :plen].astype(vc.dtype),
                        (slot, 0, 0, 0))
                    out.append((kc, vc))
                return out
            return jax.jit(splice, donate_argnums=(0,))
        return _cached_program(ck, build)

    # -- paged programs (models live in pools; tables map positions) -----

    def _paged_step_prog(self):
        cfg, slots, smax = self.cfg, self.slots, self.smax
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_step", cfg, slots, smax, nb, bs,
              _tree_key(self.params))

        def build():
            def step(params, pools, tok, pos, tables, temp, keys):
                pools, logits = _paged_decode_rows(params, pools, tok,
                                                   tables, pos, cfg)

                def pick(row, key, t, p):
                    greedy = jnp.argmax(row)
                    sampled = _sample_row(row, jnp.maximum(t, 1e-6),
                                          key, p, 0)
                    return jnp.where(t > 0, sampled, greedy)

                nxt = jax.vmap(pick)(logits, keys, temp, pos)
                return pools, nxt
            return jax.jit(step, donate_argnums=(1,))
        return _cached_program(ck, build)

    def _paged_prefill_prog(self, slen: int, plen: int):
        """Suffix prefill: gather the slot's (possibly prefix-matched)
        blocks into a contiguous b=1 scratch cache, then run ONLY the
        last `slen` prompt tokens through windowed forwards at their
        absolute positions — the prefix-reuse saving. slen == plen is
        the no-match case (and bitwise the dense prefill: the garbage
        scratch rows beyond the write frontier are causally masked to
        exact-zero weight, like the dense path's zeros)."""
        cfg, smax = self.cfg, self.smax
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_prefill", cfg, slen, plen, smax, nb, bs,
              _tree_key(self.params))

        def build():
            matched = plen - slen

            def prefill(params, pools, trow, suffix):
                caches = [(gather_block_kv(kp, trow[None]),
                           gather_block_kv(vp, trow[None]))
                          for kp, vp in pools]
                # windows on the ABSOLUTE chunk grid, so long-prompt
                # suffix chunking lines up with a from-zero prefill
                last = None
                s = matched
                while s < plen:
                    e = min(plen,
                            (s // _PREFILL_CHUNK + 1) * _PREFILL_CHUNK)
                    caches, lg = _decode_window(
                        params, caches,
                        suffix[:, s - matched:e - matched], s, cfg,
                        need_logits=e == plen)
                    if lg is not None:
                        last = lg
                    s = e
                return caches, last[:, -1]
            return jax.jit(prefill)
        return _cached_program(ck, build)

    def _paged_splice_prog(self, slen: int, plen: int):
        """Write the freshly prefilled suffix rows from the b=1
        scratch cache into the request's newly allocated pool blocks
        (whole-block scatter; the shared prefix blocks are untouched)."""
        cfg, smax = self.cfg, self.smax
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_splice", cfg, slen, plen, smax, nb, bs,
              _tree_key(self.params))

        def build():
            from ..ops.paged_attention import scatter_blocks
            matched = plen - slen
            nsuf = -(-slen // bs)      # suffix blocks (matched % bs == 0)
            lo, hi = matched, matched + nsuf * bs

            def splice(pools, one, bids):
                out = []
                for (kp, vp), (kc, vc) in zip(pools, one):
                    kseg = kc[0, lo:hi].reshape(nsuf, bs, *kc.shape[2:])
                    vseg = vc[0, lo:hi].reshape(nsuf, bs, *vc.shape[2:])
                    out.append((scatter_blocks(kp, bids, kseg),
                                scatter_blocks(vp, bids, vseg)))
                return out
            return jax.jit(splice, donate_argnums=(0,))
        return _cached_program(ck, build)

    def _copy_block_prog(self):
        """Device side of allocator copy-on-write: duplicate one
        block's rows src->dst across every layer's pools."""
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_copy", self.cfg, self.smax, nb, bs,
              _tree_key(self.params))

        def build():
            def copy(pools, src, dst):
                return [(kp.at[dst].set(kp[src]),
                         vp.at[dst].set(vp[src]))
                        for kp, vp in pools]
            return jax.jit(copy, donate_argnums=(0,))
        return _cached_program(ck, build)

    # -- paged host-side bookkeeping -------------------------------------

    def _alloc_block(self) -> int:
        """allocator.alloc with the OOM→evict→retry discipline: a full
        pool first evicts the least-recently-used idle radix chain
        (retained prefixes are a cache, not a reservation)."""
        try:
            return self._alloc.alloc()
        except CacheOOM:
            if not self._radix.evict(1):
                raise
            return self._alloc.alloc()

    def _ensure_block(self, slot: int, pos: int) -> None:
        """Before a decode write at `pos`: extend the slot's table to
        cover it, and make the target block exclusively ours (COW
        guard — unreachable under the publish-at-retire policy, since
        writes always land past the shared prefix, but correctness
        must not depend on the policy staying that way)."""
        pt = self._tables[slot]
        assert pt is not None
        while pt.capacity <= pos:
            pt.append_block(self._alloc_block())
        bid = pt.block_of(pos)
        if self._alloc.refcount(bid) > 1:
            new, copied = self._alloc.fork(bid)
            if copied:
                self._pools = self._copy_block_prog()(
                    self._pools, jnp.int32(bid), jnp.int32(new))
                pt.blocks[pos // self.block_size] = new

    def _admit_paged(self, req: "_Request"):
        """Paged admission: longest-cached-prefix lookup, fresh blocks
        for the suffix, suffix-only prefill, splice into the pool.
        Returns the last prompt position's logits [1, V]."""
        plen = len(req.prompt)
        matched, mbids = (0, [])
        if self._prefix_reuse:
            # always leave >= 1 suffix token: admission needs the LAST
            # prompt token's logits to seed generation
            matched, mbids = self._radix.match(req.prompt[:-1])
        pt = PageTable(self.block_size)
        pt.blocks.extend(mbids)
        try:
            while pt.capacity < plen:
                pt.append_block(self._alloc_block())
        except CacheOOM:
            for bid in pt.blocks:
                self._alloc.decref(bid)
            raise
        pt.tokens = plen
        slen = plen - matched
        with tracing.span("serving.prefill", "serving", rid=req.rid,
                          plen=plen, matched=matched, suffix=slen):
            trow = jnp.asarray(pt.as_row(self._maxb, self._trash))
            suffix = jnp.asarray([req.prompt[matched:]], jnp.int32)
            one, last_logits = self._paged_prefill_prog(slen, plen)(
                self.params, self._pools, trow, suffix)
            sbids = jnp.asarray(pt.blocks[matched // self.block_size:],
                                jnp.int32)
            self._pools = self._paged_splice_prog(slen, plen)(
                self._pools, one, sbids)
        self._prefill_saved += matched
        self._prefill_computed += slen
        return pt, last_logits

    def _release_slot(self, slot: int, req: "_Request") -> None:
        """Paged retire: publish the request's FULL prompt blocks into
        the radix tree (prefix reuse for future admits), then drop the
        request's references — shared blocks survive under the tree's
        ref, private ones return to the free list."""
        pt = self._tables[slot]
        if pt is None:
            return
        if self._prefix_reuse:
            nfull = len(req.prompt) // self.block_size
            if nfull:
                self._radix.insert(
                    req.prompt[:nfull * self.block_size],
                    pt.blocks[:nfull])
        for bid in pt.blocks:
            self._alloc.decref(bid)
        self._tables[slot] = None

    def cache_stats(self) -> Dict[str, float]:
        """Paged-mode observability snapshot (the same numbers the
        /cache{...} performance counters export)."""
        if not self.paged:
            raise ValueError("cache_stats() requires paged=True")
        st: Dict[str, float] = dict(self._alloc.stats())
        st.update(self._radix.stats())
        st["prefill_tokens_saved"] = self._prefill_saved
        st["prefill_tokens_computed"] = self._prefill_computed
        return st

    # -- public API ------------------------------------------------------

    def submit(self, prompt, max_new: int, eos_id: Optional[int] = None,
               temperature: float = 0.0, key=None) -> int:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("continuous batching needs a non-empty "
                             "prompt (unconditional generation: "
                             "transformer.generate)")
        if len(prompt) + max_new > self.smax:
            raise ValueError(
                f"plen {len(prompt)} + max_new {max_new} exceeds "
                f"smax {self.smax}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new} "
                             "(generate() handles max_new == 0)")
        if temperature > 0.0 and key is None:
            raise ValueError("temperature > 0 needs a PRNG key")
        if temperature <= 0.0 and key is not None:
            raise ValueError(
                "key has no effect at temperature=0 (greedy); pass "
                "temperature > 0 to sample")
        if key is not None:
            key = _normalize_key(key)
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(rid, prompt, max_new, eos_id,
                                    temperature, key))
        return rid

    def _admit(self) -> None:
        """Fill free slots from the queue: prefill the prompt on a b=1
        cache (one window forward; paged mode prefills only past the
        longest cached prefix), splice its K/V rows into the slot (or
        pool blocks), seed the slot's first generated token.

        A request that retires DURING admission (max_new == 1, or an
        instant eos) frees its slot immediately — the inner loop
        re-scans the same slot within this pass, so a burst of
        one-token requests drains through one slot without burning a
        full decode step per request on an empty batch."""
        for slot in range(self.slots):
            while self._slot_req[slot] is None and self._queue:
                req = self._queue.popleft()
                plen = len(req.prompt)
                with tracing.span("serving.admit", "serving",
                                  rid=req.rid, slot=slot, plen=plen):
                    if self.paged:
                        pt, last_logits = self._admit_paged(req)
                        self._tables[slot] = pt
                    else:
                        with tracing.span("serving.prefill", "serving",
                                          rid=req.rid, plen=plen):
                            prompt = jnp.asarray([req.prompt],
                                                 jnp.int32)
                            one, last_logits = self._prefill_prog(
                                plen)(self.params, prompt)
                            self._caches = self._splice_prog(plen)(
                                self._caches, one, jnp.int32(slot))
                    if req.temperature > 0.0:
                        # generate()'s tok0 draw: position plen-1, row 0
                        tok0 = int(_sample_row(last_logits[0],
                                               req.temperature,
                                               req.key, plen - 1, 0))
                    else:
                        tok0 = int(jnp.argmax(last_logits[0]))
                    req.tokens.append(tok0)
                    self._slot_req[slot] = req
                    self._pos[slot] = plen
                    self._cur[slot] = tok0
                    self._temp[slot] = req.temperature
                    self._key[slot] = (req.key if req.key is not None
                                       else jax.random.PRNGKey(0))
                    self._maybe_retire(slot)

    def _maybe_retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        if req is None:
            return
        hit_eos = (req.eos_id is not None
                   and req.tokens[-1] == req.eos_id)
        if len(req.tokens) >= req.max_new or hit_eos:
            if hit_eos:
                # generate() keeps emitting pinned eos to max_new; the
                # slot retires early and pads the same tail
                req.tokens = req.tokens + [req.eos_id] * (
                    req.max_new - len(req.tokens))
            with tracing.span("serving.retire", "serving",
                              rid=req.rid, slot=slot,
                              tokens=len(req.tokens), eos=hit_eos):
                self._done[req.rid] = req.tokens
                self._slot_req[slot] = None
                if self.paged:
                    self._release_slot(slot, req)

    def step(self) -> bool:
        """Admit + one decode step for every live slot. Returns True
        while any work remains (live slots or queued requests)."""
        self._admit()
        live = [s for s in range(self.slots)
                if self._slot_req[s] is not None]
        if not live:
            return bool(self._queue)
        with tracing.span("serving.decode", "serving",
                          live=len(live),
                          rids=[self._slot_req[s].rid for s in live]):
            tok = jnp.asarray(self._cur, jnp.int32)
            # dense: dead slots re-write their own last position
            # (harmless: never read — admission overwrites rows
            # 0..plen first). Paged: dead slots' tables are all-trash,
            # so their writes land in the reserved trash block instead
            # of a recycled live block.
            pos = jnp.asarray(self._pos, jnp.int32)
            temp = jnp.asarray(self._temp, jnp.float32)
            keys = jnp.stack(self._key)
            if self.paged:
                for s in live:
                    self._ensure_block(s, self._pos[s])
                tables = jnp.asarray(materialize(
                    self._tables, self._maxb, self._trash))
                self._pools, nxt = self._paged_step_prog()(
                    self.params, self._pools, tok, pos, tables, temp,
                    keys)
            else:
                self._caches, nxt = self._step_prog()(
                    self.params, self._caches, tok, pos, temp, keys)
            nxt_host = np.asarray(nxt).tolist()  # ONE device->host read
            self._rate.mark(float(len(live)))
            for s in live:
                req = self._slot_req[s]
                assert req is not None
                req.tokens.append(nxt_host[s])
                self._pos[s] += 1
                self._cur[s] = nxt_host[s]
                self._maybe_retire(s)
        return True

    def run(self) -> Dict[int, List[int]]:
        """Drive step() until every submitted request finishes; returns
        {request_id: tokens} (each exactly generate()'s output)."""
        while self.step():
            pass
        out, self._done = self._done, {}
        return out
