"""Continuous batching: slot-based serving with per-slot positions.

Reference analog: none (HPX ships no serving runtime); this is the
standard TPU serving-loop shape — a FIXED batch of decode slots, each
at its OWN sequence position, stepping together in one jitted program.
Requests admit into free slots between steps (their prompt prefills on
the side in BUCKETED CHUNKS, then SPLICES into the slot's cache rows)
and retire on eos/max_new, so short requests never wait for long ones
and the chip never idles on a ragged batch. Static shapes throughout:
the per-row cache write is a batched scatter at the slot's position
vector, the causal mask compares against per-row positions, and dead
slots simply compute masked work (the XLA way — uniform work, no
dynamic batch).

Three throughput disciplines shape the hot loop:

* BUCKETED prefill: prompts run through fixed-width chunk programs
  (widths from the ``hpx.serving.prefill_buckets`` ladder, padded then
  causally masked), so the program cache is O(buckets) instead of
  O(distinct prompt lengths) — mixed-length traffic compiles a handful
  of programs, ever.
* CHUNKED prefill interleaved with decode (Sarathi-style): a prompt
  longer than ``hpx.serving.prefill_chunk`` advances one chunk per
  step between decode dispatches, so an admit never stalls the live
  batch; pending prefills are served shortest-remaining-first, so a
  short prompt is never stuck behind a long one's tail chunks.
* ASYNC dispatch: the step loop feeds each step's sampled tokens back
  device-side and only syncs to the host when a token VALUE is needed
  (eos check, retirement) or the ``hpx.serving.max_async_steps`` cap
  hits — host Python overlaps device execution.
* SPECULATIVE decode steps (``hpx.serving.spec.*``): each step drafts
  k tokens per slot — zero-model prompt-lookup over the slot's own
  history (plus the radix prefix tree), or a smaller draft checkpoint
  — and verifies the window with ONE forward, emitting 1..k+1 tokens
  per sync instead of one. Acceptance compares drafts against the
  EXACT token the sequential step would pick (same ``_pick_row``
  key-fold contract), so spec output stays byte-identical, greedy and
  sampled; the paged path rolls rejected window blocks back
  (``PageTable.rollback``). Verify programs ride the prefill bucket
  ladder — still O(buckets) programs — and k adapts per slot on an
  acceptance EMA.

Differential contract (the test): every request's tokens are EXACTLY
what transformer.generate() emits for that prompt alone — continuous
batching changes THROUGHPUT, never content. Chunk padding preserves
this bit-for-bit: per-token hidden states and K/V rows are independent
of how the prompt is partitioned into windows (row-independent ops +
exact-zero causal masking of pad rows), and the first sampled token
comes from a 1-token logits probe of the last prompt position.

RESILIENCY (ROADMAP item 5): the step loop runs under a bounded
`svc.resiliency.sync_replay`. Every live slot keeps a host-side
`SlotCheckpoint` (tokens, position, feedback token, paged block pins)
captured at flush boundaries every ``hpx.serving.ckpt_every`` tokens;
a step-level fault — injected via `svc/faultinject`, or a KV-pool OOM
eviction couldn't clear — flushes the completed suffix, rewinds live
slots to their checkpoints and replays only the lost tail. The
differential contract is what makes this sha-provable: replayed steps
re-emit the SAME tokens, so a faulted run's outputs are byte-identical
to the fault-free run. Paged restores re-enter from still-resident
pinned blocks (no recompute); dense restores re-prefill prompt ++
emitted[:-1] through the bucketed chunk programs. Retry exhaustion,
admission OOM that outlives ``hpx.serving.admit_retries``, and lapsed
submit() deadlines shed requests with typed errors into `failed`.

Build on the single-sequence machinery in models/transformer.py; the
per-row-position block lives here (the scalar-position `_block_decode`
stays the lean fast path for uniform decode).
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.block_allocator import BlockAllocator, CacheOOM, block_bytes
from ..cache.ngram import propose as _ngram_propose
from ..cache.page_table import PageTable, materialize, occupancy
from ..cache.radix import RadixCache
from ..core.errors import Error, HpxError
from ..svc import faultinject, flight, tracing
from ..svc.resiliency import sync_replay
from ..ops.attention_pallas import resolve_paged_block_src
from ..ops.paged_attention import (
    gather_block_kv,
    paged_decode_attention,
    paged_window_attention,
    scatter_seq_blocks,
    scatter_seq_blocks_q,
)
from .transformer import (
    _PREFILL_CHUNK,
    TransformerConfig,
    _cached_program,
    _decode_window,
    _dq,
    _ln,
    _pick_row,
    _qkv_proj,
    _sample_row,
    _tree_key,
)

__all__ = ["ContinuousServer", "DeadlineExceededError",
           "RequestShedError", "ServerClosedError", "SlotCheckpoint"]

# the knob subset a LIVE server re-reads from the runtime config at
# flush boundaries (_reload_knobs). Only keys whose raw config value
# actually CHANGED since construction are applied — a constructor
# argument (e.g. a DecodeWorker's explicit prefill_chunk) must not be
# clobbered by an unrelated config write bumping the generation.
_RELOADABLE_KNOBS = (
    "hpx.serving.prefill_chunk",
    "hpx.serving.max_async_steps",
    "hpx.serving.ckpt_every",
    "hpx.serving.spec.k",
    "hpx.serving.moe.capacity_factor",
    "hpx.cache.radix_budget_blocks",
    "hpx.cache.tier.host_budget_mb",
)


class ServerClosedError(HpxError):
    """submit() after shutdown(). Typed (invalid_status) so a client
    can tell "server is draining" from a malformed request — before
    this error existed, post-shutdown submissions enqueued silently
    onto a server nobody was going to drive."""

    def __init__(self, message: str = ""):
        super().__init__(Error.invalid_status,
                         message or "server is shut down — submit() no "
                         "longer accepts requests (queued and in-flight "
                         "work still drains via run())",
                         "ContinuousServer.submit")


class RequestShedError(HpxError):
    """The server gave up on one request: step-retry exhaustion,
    admission OOM that outlived its deferral budget, or overload.
    Recorded per-rid in ``ContinuousServer.failed``; the code is
    service_unavailable — shed work is client-retryable, unlike a
    bad_parameter rejection."""

    def __init__(self, rid: int, reason: str):
        super().__init__(Error.service_unavailable,
                         f"request {rid} shed: {reason}",
                         "ContinuousServer")
        self.rid = rid
        self.reason = reason


class DeadlineExceededError(RequestShedError):
    """Shed because the submit()-time deadline lapsed while the
    request was still queued or prefilling — the overload fail-fast
    path (a starving queue sheds instead of aging out)."""

    def __init__(self, rid: int, deadline_s: Optional[float]):
        RequestShedError.__init__(
            self, rid,
            f"deadline of {deadline_s or 0.0:g}s lapsed before the "
            "request went live")
        self.deadline_s = deadline_s


def _normalize_key(key):
    """Coerce a user PRNG key to the raw uint32 layout the batched
    sampler needs: step() stacks the per-slot keys with jnp.stack, which
    fails (or silently mis-samples) on a mix of typed jax.random.key
    arrays and raw PRNGKey arrays. Typed keys are unwrapped via
    key_data; raw uint32 arrays pass through; anything else is rejected
    here at submit() instead of surfacing as a stack/shape error deep in
    step()."""
    try:
        arr = jnp.asarray(key)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"key is not a PRNG key (got {type(key).__name__}); pass "
            "jax.random.key(seed) or jax.random.PRNGKey(seed)") from e
    if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
        arr = jax.random.key_data(arr)
    raw = jax.random.PRNGKey(0)
    if arr.shape != raw.shape or arr.dtype != raw.dtype:
        raise ValueError(
            "key must be a typed jax.random.key(...) or a raw uint32 "
            f"jax.random.PRNGKey(...) of shape {raw.shape}; got shape "
            f"{arr.shape} dtype {arr.dtype}")
    return arr


def _resolve_buckets(spec, chunk: int) -> Tuple[int, ...]:
    """The chunk-width ladder: ``auto`` doubles from 8 up to the chunk
    size; a csv spec is parsed, clamped to the chunk (a chunk program
    never sees a wider window), and always completed with the full
    chunk width so every chunk has a bucket."""
    if spec is None or str(spec).strip() in ("", "auto"):
        ladder, w = [], 8
        while w < chunk:
            ladder.append(w)
            w *= 2
        ladder.append(chunk)
        return tuple(sorted(set(ladder)))
    vals: List[int] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        v = int(part)
        if v < 1:
            raise ValueError(
                f"hpx.serving.prefill_buckets entries must be >= 1, "
                f"got {v}")
        vals.append(min(v, chunk))
    if not vals:
        raise ValueError(
            f"hpx.serving.prefill_buckets parsed to nothing: {spec!r}")
    vals.append(chunk)
    return tuple(sorted(set(vals)))


def _resolve_kv_dtype(kv_dtype, rc) -> str:
    """The hpx.cache.kv_dtype resolution _init_paged applies, factored
    out so the perfdb boot consult can key on the RESOLVED dtype
    before the paged state is built."""
    if kv_dtype is None:
        kv_dtype = rc.get("hpx.cache.kv_dtype", "bf16")
    if kv_dtype not in ("bf16", "int8", "fp8"):
        raise ValueError(
            "hpx.cache.kv_dtype must be one of 'bf16' (pools in "
            "the model compute dtype), 'int8' (quantized blocks "
            "with absmax scale sidecars) or 'fp8' (e4m3 blocks "
            f"with the same sidecars), got {kv_dtype!r}")
    return kv_dtype


def _resolve_paged_kernel(paged_kernel, rc) -> str:
    """hpx.serving.paged_kernel resolution (auto -> fused on TPU,
    gather elsewhere), factored out of _init_paged for the same
    reason as _resolve_kv_dtype."""
    if paged_kernel is None:
        paged_kernel = rc.get("hpx.serving.paged_kernel", "auto")
    if paged_kernel in (None, "", "auto"):
        # the fused Pallas table-walk kernel is native on TPU;
        # everywhere else the XLA gather formulation is the fast
        # path (interpret-mode Pallas is a test vehicle, not a
        # serving path)
        paged_kernel = ("fused" if jax.default_backend() == "tpu"
                        else "gather")
    if paged_kernel not in ("gather", "fused", "fused_online"):
        raise ValueError(
            "hpx.serving.paged_kernel must be one of 'auto', "
            "'gather', 'fused' (bitwise Pallas table walk) or "
            "'fused_online' (O(block)-scratch online softmax), "
            f"got {paged_kernel!r}")
    return paged_kernel


def _rc_at_default(rc, key: str) -> bool:
    """True when the effective config value for ``key`` is its
    DECLARED default — the learned-ladder override policy: a value an
    operator set explicitly (ini/env/CLI/set()) always beats the
    perfdb, even when the store holds a hit for the shape."""
    from ..core import config_schema
    entry = config_schema.lookup(key)
    return entry is not None and rc.get(key) == entry.default


def _rope_win(x, posw, cfg: TransformerConfig):
    """Rotate-half RoPE over a PER-ROW position GRID: x [B, W, N, H],
    posw [B, W] int32 — each (row, window-column) pair rotates at its
    own absolute position (transformer._rope takes one shared [S]
    vector; `_rope_rows` is the W == 1 special case)."""
    hd = x.shape[-1]
    half = hd // 2
    freq = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32)
                              / half)
    ang = posw.astype(jnp.float32)[..., None] * freq  # [B, W, half]
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _rope_rows(x, pos, cfg: TransformerConfig):
    """Rotate-half RoPE with PER-ROW positions: x [B, 1, N, H],
    pos [B] int32."""
    return _rope_win(x, pos[:, None], cfg)


def _moe_rows(h2, lp, cfg, moe_cf=None, moe_ep=None, moe_sink=None,
              moe_ms=None):
    """Shared MoE branch of the serving block fns: expert FFN over the
    flattened [T, D] token block. `moe_cf` overrides the capacity
    factor (None = drop-free n_experts, the token-identity default);
    `moe_ep` = (axis_name, axis_size) routes expert-parallel through
    `moe_ffn_decode` — only valid inside a shard_map body; `moe_sink`
    (a list) collects the per-layer psum-complete stats vector;
    `moe_ms` is the replicated stats sharding for GSPMD bodies (see
    moe_ffn's stats_sharding)."""
    from .moe import moe_ffn, moe_ffn_decode
    from .transformer import _moe_cfg
    cf = float(cfg.n_experts) if moe_cf is None else float(moe_cf)
    mcfg = dataclasses.replace(_moe_cfg(cfg), capacity_factor=cf)
    if moe_ep is not None:
        out, _aux, stats = moe_ffn_decode(h2, lp["moe"], mcfg,
                                          moe_ep[0], moe_ep[1])
    else:
        out, _aux, stats = moe_ffn(h2, lp["moe"], mcfg,
                                   return_stats=True,
                                   stats_sharding=moe_ms)
    if moe_sink is not None:
        moe_sink.append(stats)
    return out


def _moe_fold(sink):
    """Fold the per-layer MoE stats vectors into ONE [2 + E] f32
    program output: routed / dropped-over-capacity claims SUM over
    layers, per-expert occupancy fractions AVERAGE over layers.
    Returns None (an empty pytree — legal jit/shard_map output) for
    dense models, so every driver can return it unconditionally."""
    if not sink:
        return None
    s = jnp.sum(jnp.stack(sink), axis=0)
    return jnp.concatenate([s[:2], s[2:] / len(sink)])


def _block_decode_rows(x, lp, kv, pos, cfg: TransformerConfig,
                       moe_cf=None, moe_ep=None, moe_sink=None,
                       moe_ms=None):
    """One decoder block for ONE new token per slot with PER-SLOT cache
    positions. x: [B, 1, D]; kv: (k_cache, v_cache) [B, Smax, Nkv, H];
    pos: [B] int32 — slot b's token lands at pos[b], and its query
    attends cache positions <= pos[b]. The write is a batched scatter
    (row b at pos[b]); everything else mirrors _block_decode. MoE
    layers route through `_moe_rows` (expert-parallel when `moe_ep`
    names a mesh axis)."""
    kc, vc = kv
    b = x.shape[0]
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    if cfg.rope:
        q = _rope_rows(q, pos, cfg)
        k = _rope_rows(k, pos, cfg)
    rows = jnp.arange(b)
    kc = kc.at[rows, pos].set(k[:, 0])
    vc = vc.at[rows, pos].set(v[:, 0])
    nq, hd = q.shape[2], q.shape[3]
    nkv = kc.shape[2]
    g = nq // nkv
    qg = q.reshape(b, 1, nkv, g, hd)
    s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
    kpos = jnp.arange(kc.shape[1])
    live = kpos[None, :] <= pos[:, None]               # [B, Smax]
    s = jnp.where(live[:, None, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(b, 1, nq, hd)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        d = h.shape[-1]
        out = _moe_rows(h.reshape(b, d), lp, cfg, moe_cf, moe_ep,
                        moe_sink, moe_ms)
        return x + out.reshape(b, 1, d), (kc, vc)
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    return x + h, (kc, vc)


def _decode_rows(params, caches, tok, pos, cfg, moe_cf=None,
                 moe_ep=None, moe_ms=None):
    """One token per slot through every block at per-slot positions;
    returns (caches, f32 logits [B, V], mstats) — mstats is the folded
    MoE stats vector (None for dense models)."""
    x = params["emb"][tok][:, None, :]
    new_caches = []
    sink = []
    for lp, kv in zip(params["layers"], caches):
        x, kv = _block_decode_rows(x, lp, kv, pos, cfg, moe_cf,
                                   moe_ep, sink, moe_ms)
        new_caches.append(kv)
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return (new_caches, logits[:, 0, :].astype(jnp.float32),
            _moe_fold(sink))


def _paged_block_rows(x, lp, pools, scales, table, pos,
                      cfg: TransformerConfig, fused=False,
                      tp_axis=None, moe_cf=None, moe_ep=None,
                      moe_sink=None):
    """_block_decode_rows with the K/V rows living in a shared BLOCK
    POOL instead of per-slot dense buffers. x: [B, 1, D]; pools:
    (k_pool, v_pool) each [num_blocks, block_size, Nkv, H]; scales:
    (k_scale, v_scale) [num_blocks, Nkv] f32 sidecars for int8 pools,
    or None; table: [B, max_blocks] int32 logical->physical block map;
    pos: [B] int32. Projections/rope/ffn are byte-identical to the
    dense path; only the cache write (scatter through the table) and
    read (gather in logical order — same row values at the same
    logical indices, or the fused Pallas table walk) differ, which is
    what keeps paged == dense token-exact.

    Under shard_map on a (dp, tp) mesh, `tp_axis` names the
    tensor-parallel axis: every shard sees its LOCAL kv-head slice of
    the pools (block axis replicated over dp) and the partial attention
    / ffn outputs close with explicit psums — the same two reduction
    points `_block_decode` uses."""
    kp, vp = pools
    b = x.shape[0]
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    if cfg.rope:
        q = _rope_rows(q, pos, cfg)
        k = _rope_rows(k, pos, cfg)
    if scales is None:
        att, kp, vp = paged_decode_attention(q, k[:, 0], v[:, 0], kp,
                                             vp, table, pos,
                                             fused=fused)
    else:
        ks, vs = scales
        att, kp, vp, ks, vs = paged_decode_attention(
            q, k[:, 0], v[:, 0], kp, vp, table, pos,
            k_scale=ks, v_scale=vs, fused=fused)
        scales = (ks, vs)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        d = h.shape[-1]
        out = _moe_rows(h.reshape(b, d), lp, cfg, moe_cf, moe_ep,
                        moe_sink)
        return x + out.reshape(b, 1, d), (kp, vp), scales
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    if tp_axis is not None:
        h = jax.lax.psum(h, tp_axis)
    return x + h, (kp, vp), scales


def _paged_decode_rows(params, pools, scales, tok, table, pos, cfg,
                       fused=False, tp_axis=None, moe_cf=None,
                       moe_ep=None):
    """One token per slot through every block over paged pools;
    returns (pools, scales, f32 logits [B, V], mstats) — the
    _decode_rows analog. `scales` is the per-layer list of
    (k_scale, v_scale) sidecars for int8 pools, or None (passed
    through untouched)."""
    x = params["emb"][tok][:, None, :]
    new_pools, new_scales = [], []
    sink = []
    for i, (lp, pl) in enumerate(zip(params["layers"], pools)):
        sc = None if scales is None else scales[i]
        x, pl, sc = _paged_block_rows(x, lp, pl, sc, table, pos, cfg,
                                      fused, tp_axis, moe_cf, moe_ep,
                                      sink)
        new_pools.append(pl)
        new_scales.append(sc)
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return (new_pools, None if scales is None else new_scales,
            logits[:, 0, :].astype(jnp.float32), _moe_fold(sink))


def _window_rows(x, lp, kv, pos0, cfg: TransformerConfig,
                 moe_cf=None, moe_ep=None, moe_sink=None,
                 moe_ms=None):
    """One decoder block for a W-token VERIFY WINDOW per slot at
    PER-SLOT positions: x [B, W, D]; slot b's window row i lands at
    cache position pos0[b] + i and attends positions <= pos0[b] + i.

    This is `_block_decode_rows` stretched to W columns — same
    projections, same einsum contractions over the same smax rows,
    same -inf mask and f32 softmax — so window column i's output is
    byte-identical to what the i-th SEQUENTIAL step would compute
    (K/V rows are functions of (token, position) alone, and column
    i's horizon includes exactly the window rows < i it would have
    already written). Window columns past smax-1 (a dead slot's stale
    cursor, or batch-width padding beyond a short slot's budget)
    scatter with ``mode="drop"``: clamping would corrupt row smax-1,
    which can hold live K/V."""
    kc, vc = kv
    b, w = x.shape[0], x.shape[1]
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    posw = pos0[:, None] + jnp.arange(w)[None, :]      # [B, W]
    if cfg.rope:
        q = _rope_win(q, posw, cfg)
        k = _rope_win(k, posw, cfg)
    rows = jnp.arange(b)[:, None]
    kc = kc.at[rows, posw].set(k, mode="drop")
    vc = vc.at[rows, posw].set(v, mode="drop")
    nq, hd = q.shape[2], q.shape[3]
    nkv = kc.shape[2]
    g = nq // nkv
    qg = q.reshape(b, w, nkv, g, hd)
    s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
    kpos = jnp.arange(kc.shape[1])
    live = kpos[None, None, :] <= posw[:, :, None]     # [B, W, Smax]
    s = jnp.where(live[:, None, None, :, :], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(b, w, nq, hd)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        d = h.shape[-1]
        out = _moe_rows(h.reshape(b * w, d), lp, cfg, moe_cf, moe_ep,
                        moe_sink, moe_ms)
        return x + out.reshape(b, w, d), (kc, vc)
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    return x + h, (kc, vc)


def _decode_window_rows(params, caches, toks, pos0, cfg, moe_cf=None,
                        moe_ep=None, moe_ms=None):
    """W tokens per slot through every block at per-slot positions
    (the speculative-verify forward); toks [B, W] int32, pos0 [B]
    int32. Returns (caches, f32 logits [B, W, V], mstats)."""
    x = params["emb"][toks]
    new_caches = []
    sink = []
    for lp, kv in zip(params["layers"], caches):
        x, kv = _window_rows(x, lp, kv, pos0, cfg, moe_cf, moe_ep,
                             sink, moe_ms)
        new_caches.append(kv)
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return new_caches, logits.astype(jnp.float32), _moe_fold(sink)


def _paged_window_rows(x, lp, pools, scales, table, pos0,
                       cfg: TransformerConfig, fused=False,
                       tp_axis=None, moe_cf=None, moe_ep=None,
                       moe_sink=None):
    """`_window_rows` over paged pools: the scatter/gather and the
    per-query horizon live in `ops.paged_attention.
    paged_window_attention`; projections/rope/ffn are byte-identical
    to the dense window, which keeps paged == dense token-exact under
    speculation too. `tp_axis` closes the per-shard partial sums under
    shard_map exactly as in `_paged_block_rows`."""
    kp, vp = pools
    b, w = x.shape[0], x.shape[1]
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    posw = pos0[:, None] + jnp.arange(w)[None, :]
    if cfg.rope:
        q = _rope_win(q, posw, cfg)
        k = _rope_win(k, posw, cfg)
    if scales is None:
        att, kp, vp = paged_window_attention(q, k, v, kp, vp, table,
                                             pos0, fused=fused)
    else:
        ks, vs = scales
        att, kp, vp, ks, vs = paged_window_attention(
            q, k, v, kp, vp, table, pos0,
            k_scale=ks, v_scale=vs, fused=fused)
        scales = (ks, vs)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        d = h.shape[-1]
        out = _moe_rows(h.reshape(b * w, d), lp, cfg, moe_cf, moe_ep,
                        moe_sink)
        return x + out.reshape(b, w, d), (kp, vp), scales
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    if tp_axis is not None:
        h = jax.lax.psum(h, tp_axis)
    return x + h, (kp, vp), scales


def _paged_decode_window_rows(params, pools, scales, toks, table, pos0,
                              cfg, fused=False, tp_axis=None,
                              moe_cf=None, moe_ep=None):
    """W tokens per slot over paged pools; returns (pools, scales, f32
    logits [B, W, V], mstats) — the `_decode_window_rows` analog."""
    x = params["emb"][toks]
    new_pools, new_scales = [], []
    sink = []
    for i, (lp, pl) in enumerate(zip(params["layers"], pools)):
        sc = None if scales is None else scales[i]
        x, pl, sc = _paged_window_rows(x, lp, pl, sc, table, pos0, cfg,
                                       fused, tp_axis, moe_cf, moe_ep,
                                       sink)
        new_pools.append(pl)
        new_scales.append(sc)
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return (new_pools, None if scales is None else new_scales,
            logits.astype(jnp.float32), _moe_fold(sink))


def _verify_tail(logits, toks, kvec, temp, keys, pos0, width):
    """Shared device-side tail of both verify programs: pick the
    target token at every window position with the SAME `_pick_row`
    the sequential step uses, then count the longest prefix of drafts
    agreeing with them.

    Window column i holds draft d_i (column 0 the committed cur
    token); target t_i = pick(logits[i]) is the token the sequential
    decode would emit after consuming column i. Draft d_i is accepted
    iff d_i == t_{i-1} AND every earlier draft was (cumprod), capped
    by the slot's real draft count kvec. The committed emission is
    t_0..t_acc — acc+1 tokens, always >= 1 — so content NEVER depends
    on the drafts, only on the targets the step program would have
    produced (greedy argmax, or the deterministic (key, pos)
    categorical draw: acceptance-rejection against a deterministic
    sampler collapses to exact token match). Everything returns in ONE
    packed [B, width+1] int32 array (targets ‖ acc) = one host read
    per spec step."""
    offs = jnp.arange(width)
    tgt = jax.vmap(
        lambda rows, key, t, p0: jax.vmap(
            lambda row, p: _pick_row(row, key, t, p))(rows, p0 + offs)
    )(logits, keys, temp, pos0)
    match = jnp.logical_and(toks[:, 1:] == tgt[:, :-1],
                            offs[None, 1:] <= kvec[:, None])
    acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
    return jnp.concatenate(
        [tgt.astype(jnp.int32), acc[:, None]], axis=1)


@dataclasses.dataclass
class SlotCheckpoint:
    """Host-side restore point for one LIVE slot, captured at flush
    boundaries (host and device agree there: ``pos = plen +
    len(tokens) - 1``, cache rows [0, pos) hold prompt ++ tokens[:-1],
    and ``cur = tokens[-1]`` is the next feedback token) every
    ``hpx.serving.ckpt_every`` emitted tokens.

    ``pins`` (paged mode) hold ONE extra allocator reference per FULL
    block below pos (rows [0, pos - pos % block_size)): the pin keeps
    eviction and slot-retire from recycling the block, and a full
    block is append-complete — this slot never writes it again, so
    the extra ref never provokes a `_cow_guard` fork (pinning the
    partial frontier block would: refcount >= 2 makes the very next
    token write fork+copy, one extra block per live slot — fatal in a
    barely-sized pool). The frontier block's rows [0, pos % bs) need
    no pin at all: KV rows are append-only (written exactly once, at
    their position) and a COW fork copies every row written so far,
    so the slot's CURRENT table always holds them byte-exact. Restore
    rebuilds the PageTable from pins ++ the live table's frontier
    block; the replayed decode suffix re-enters from still-resident
    KV. Dense mode pins nothing and restores by re-prefilling
    prompt ++ tokens[:-1] (byte-identical: K/V rows are functions of
    (token, position) alone)."""

    rid: int
    tokens: List[int]              # emitted tokens at capture (copy)
    pos: int                       # next write position per invariant
    cur: int                       # feedback token (= tokens[-1])
    slot_k: int                    # spec adaptive-k at capture
    slot_acc: float                # spec acceptance EMA at capture
    pins: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: Any                    # [plen] int32 host array
    max_new: int
    eos_id: Optional[int]
    temperature: float = 0.0       # 0: greedy; >0: sample with `key`
    key: Any = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    sent: int = 0                  # tokens DISPATCHED (>= len(tokens))
    t_submit: float = 0.0          # monotonic submit time (TTFT)
    deadline_s: Optional[float] = None   # submit()-time budget
    t_deadline: Optional[float] = None   # absolute monotonic deadline
    # disaggregated serving (admit_prefilled): prefill happened on a
    # REMOTE worker; admission splices these shipped KV rows instead
    # of computing a prefill. Host arrays only — no blocks are held
    # until the slot admits, so a shed queued transfer leaks nothing.
    xfer_rows: Any = None          # np [layers, 2, plen, n_kv, hd]
    xfer_seed: Optional[int] = None   # remote probe's seeded token


@dataclasses.dataclass
class _PendingPrefill:
    """One in-flight chunked prefill: owns a reserved slot and a b=1
    scratch cache; `done` is the absolute prompt cursor (starts at the
    radix-matched prefix length in paged mode)."""
    req: _Request
    slot: int
    caches: Any                    # b=1 [1, smax] scratch, per layer
    done: int                      # prompt tokens already in scratch
    seq: int                       # admission order (FIFO tiebreak)
    pt: Optional[PageTable] = None  # paged: blocks held for the request
    trow: Any = None               # paged: device [maxb] table row
    wrow: Any = None               # paged: splice WRITE row (matched
                                   # prefix entries point at trash)
    flow: Optional[int] = None     # tracing flow id chaining the chunks

    @property
    def remaining(self) -> int:
        return len(self.req.prompt) - self.done


class ContinuousServer:
    """Slot-based continuous batching, per-request greedy or sampled.

    ::

        srv = ContinuousServer(params, cfg, slots=4, smax=256)
        a = srv.submit([3, 1, 4], max_new=16)
        b = srv.submit([2, 7], max_new=8, eos_id=0)
        out = srv.run()            # {a: [tokens...], b: [tokens...]}

    One jitted step decodes every live slot at its own position;
    finished slots retire and queued requests admit between steps.
    Prompts prefill on a b=1 scratch cache in BUCKETED fixed-width
    chunks (pad-then-mask; widths from the ``hpx.serving.
    prefill_buckets`` ladder), then a 1-token probe of the last prompt
    position yields the seeding logits and the whole scratch splices
    into the slot — so the program cache holds O(buckets) prefill
    programs regardless of the prompt-length mix. A prompt whose
    remaining tokens exceed ``hpx.serving.prefill_chunk`` becomes a
    PENDING prefill: it advances one chunk per step interleaved with
    live decode (shortest-remaining-first across pendings), so admits
    never stall the running batch. Dead slots compute masked no-op
    work (static shapes).

    With ``hpx.serving.async_dispatch`` (default on) the step loop
    keeps the sampled-token feedback on device and defers the
    device->host read until a token value is needed (eos check or a
    retirement) or ``hpx.serving.max_async_steps`` steps are buffered;
    results and retirement timing are unchanged — only the forced
    per-step sync goes away.

    PER-REQUEST decoding mode: greedy by default, or submit(...,
    temperature=t, key=k) to sample — the key folds follow generate()'s
    exactly (fold position, then row 0), so a sampled request emits the
    SAME tokens it would get from a solo generate(temperature=t, key=k)
    run. top_k truncation is not wired (it is a static shape choice;
    bucket by top_k if needed).

    ``spec=True`` turns each decode step speculative: per-slot drafts
    (``spec_draft='prompt'`` mines the slot's token history;
    ``'model'`` runs ``draft_params``/``draft_cfg``) are verified by
    one window forward and committed only where they match the
    sequential pick — same tokens, fewer host syncs per token. See
    ``spec_stats()`` and the ``/serving{...}/spec/*`` counters."""

    def __init__(self, params, cfg: TransformerConfig, slots: int = 4,
                 smax: int = 512, mesh=None, paged: bool = False,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 radix_budget_blocks: Optional[int] = None,
                 prefix_reuse: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 prefill_buckets: Optional[str] = None,
                 async_dispatch: Optional[bool] = None,
                 spec: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_draft: Optional[str] = None,
                 paged_kernel: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 draft_params=None,
                 draft_cfg: Optional[TransformerConfig] = None):
        self.cfg = cfg
        self.slots = slots
        self.smax = smax
        self.mesh = mesh
        self.paged = bool(paged)
        nkv, hd = cfg.kv_heads, cfg.head_dim
        from ..core.config import runtime_config
        rc = runtime_config()
        cache_sh = None
        if self.paged and mesh is not None and \
                not rc.get_bool("hpx.serving.mesh.paged", True):
            # operational escape hatch back to the pre-sharded refusal
            raise ValueError(
                "sharded paged serving is disabled "
                "(hpx.serving.mesh.paged=0): shard the dense path "
                "(mesh=...) or run one paged server per replica")
        self._ep_axis, self._ep_size = None, 1
        if mesh is not None:
            # GSPMD sharded serving: slots over dp, heads over tp. The
            # dense step/prefill/splice programs are UNCHANGED —
            # placement alone makes XLA partition them (einsum
            # contractions over the tp-sharded head dim close with
            # compiler-inserted all-reduces; expert einsums partition
            # over the expert-sharded e dim). The PAGED decode/verify
            # steps instead run under shard_map (block tables are
            # per-dp-shard; the pool gather must stay shard-local),
            # with explicit psums over tp and MoE token routing over
            # the expert axis — see _paged_step_prog.
            from jax.sharding import NamedSharding, PartitionSpec as P
            from .transformer import (_decode_ep, _decode_mesh_check,
                                      _decode_pspecs, _place)
            # the shared decode-mesh contract (axes, expert and
            # head/slot divisibility); slots play the batch role
            try:
                _decode_mesh_check(cfg, mesh, slots)
            except ValueError as e:
                raise ValueError(str(e).replace("batch", "slots")) \
                    from None
            self._ep_axis, self._ep_size = _decode_ep(cfg, mesh)
            params = _place(params, _decode_pspecs(params, cfg, mesh),
                            mesh)
            cache_sh = NamedSharding(mesh, P("dp", None, "tp", None))
        self.params = params
        self._cache_sh = cache_sh
        # MoE decode state: the capacity-factor knob is an int PERCENT
        # (100 = GShard cf 1.0); 0 = auto = drop-free (cf = n_experts),
        # the token-identity default. Routed/dropped counts and
        # per-expert occupancy come back as one small f32 vector per
        # step program and drain at flush boundaries (async-safe).
        pct = rc.get_int("hpx.serving.moe.capacity_factor", 0)
        self._moe_capacity_pct = (cfg.n_experts * 100 if pct <= 0
                                  else max(1, int(pct)))
        self._moe_routed = 0.0
        self._moe_dropped = 0.0
        self._moe_occ = [0.0] * max(0, cfg.n_experts)
        self._moe_buf: deque = deque()

        # learned-ladder boot consult (svc/perfdb): with
        # hpx.perfdb.use_learned_ladders=1 the store is keyed on this
        # server's (device, shape, kv_dtype, kernel, mesh) and a
        # usable hit overrides the hand-picked ladder DEFAULTS below.
        # Explicit settings — constructor args, or config values moved
        # off their declared defaults — always win, and with the knob
        # off (or on a miss/stale entry) every resolution below is
        # byte-identical to the constants (pinned by
        # tests/test_perfdb.py).
        self._learned_ladder = None
        self._ladder_source = "default"
        self._block_size_src = "n/a"
        if rc.get_bool("hpx.perfdb.use_learned_ladders", False):
            from ..svc import perfdb as _perfdb
            _perfdb.ensure_counters()
            if self.paged:
                lk_kvd = _resolve_kv_dtype(kv_dtype, rc)
                lk_kern = _resolve_paged_kernel(paged_kernel, rc)
            else:
                lk_kvd, lk_kern = "-", "dense"
            self._learned_ladder = _perfdb.learned_ladder_for(
                cfg, lk_kvd, lk_kern, mesh)
        # "learned" only when a stored value actually lands — an
        # explicit constructor arg or operator config write beats the
        # store, and the source string must say so
        learned = self._learned_ladder or {}

        if prefill_chunk is None:
            if learned.get("prefill_chunk") and \
                    _rc_at_default(rc, "hpx.serving.prefill_chunk"):
                prefill_chunk = int(learned["prefill_chunk"])
                self._ladder_source = "learned"
            else:
                prefill_chunk = rc.get_int("hpx.serving.prefill_chunk",
                                           _PREFILL_CHUNK)
        self.prefill_chunk = max(1, int(prefill_chunk))
        if prefill_buckets is None:
            if learned.get("prefill_buckets") and \
                    _rc_at_default(rc, "hpx.serving.prefill_buckets"):
                prefill_buckets = ",".join(
                    str(int(b)) for b in learned["prefill_buckets"])
                self._ladder_source = "learned"
            else:
                prefill_buckets = rc.get("hpx.serving.prefill_buckets",
                                         "auto")
        self.prefill_buckets = _resolve_buckets(prefill_buckets,
                                                self.prefill_chunk)
        if async_dispatch is None:
            async_dispatch = rc.get_bool("hpx.serving.async_dispatch",
                                         True)
        self._async = bool(async_dispatch)
        self._max_async = max(1, rc.get_int(
            "hpx.serving.max_async_steps", 32))

        # speculative decoding (hpx.serving.spec.*): draft k tokens
        # per slot, verify the window in ONE forward. Spec steps sync
        # every step (the packed targets+acceptance read) — they
        # multiply tokens-per-host-sync instead of deferring the sync.
        if spec is None:
            spec = rc.get_bool("hpx.serving.spec.enable", False)
        self._spec = bool(spec)
        if spec_draft is None:
            spec_draft = rc.get("hpx.serving.spec.draft", "prompt")
            if draft_params is not None:
                spec_draft = "model"  # a checkpoint implies the source
        if spec_draft not in ("prompt", "model"):
            raise ValueError(
                "hpx.serving.spec.draft must be 'prompt' or 'model', "
                f"got {spec_draft!r}")
        self._spec_source = spec_draft
        if spec_k is None:
            sk = learned.get("spec_k") or {}
            if sk.get("best") and _rc_at_default(rc,
                                                "hpx.serving.spec.k"):
                spec_k = int(sk["best"])
                self._ladder_source = "learned"
            else:
                spec_k = rc.get_int("hpx.serving.spec.k", 4)
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        # the verify window (k drafts + the current token) rides the
        # prefill bucket ladder, so k is capped at the widest rung - 1
        self._spec_k = min(int(spec_k), self.prefill_buckets[-1] - 1)
        self._spec_ngram = max(1, rc.get_int(
            "hpx.serving.spec.ngram", 3))
        self._spec_min_accept = rc.get_float(
            "hpx.serving.spec.min_accept", 0.3)
        self._spec_adapt = rc.get_bool("hpx.serving.spec.adapt", True)
        self._slot_k = [self._spec_k] * slots   # per-slot adaptive k
        self._slot_acc = [1.0] * slots          # acceptance-rate EMA
        self._spec_drafted = 0                  # /serving/spec/* feed
        self._spec_accepted = 0
        self._spec_steps = 0
        self._spec_emitted = 0
        self._draft_params = None
        self._draft_cfg = None
        self._draft_caches = None
        if self._spec and self._spec_source == "model":
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "spec draft source 'model' needs draft_params and "
                    "draft_cfg (or use spec_draft='prompt' for "
                    "zero-model prompt-lookup drafting)")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab} != target vocab "
                    f"{cfg.vocab}")
            if mesh is not None:
                # the draft shares the serving mesh: same placement
                # contract, slots in the batch role
                from .transformer import (_decode_mesh_check,
                                          _decode_pspecs, _place)
                try:
                    _decode_mesh_check(draft_cfg, mesh, slots)
                except ValueError as e:
                    raise ValueError(
                        "draft model cannot share the serving mesh: "
                        + str(e).replace("batch", "slots")) from None
                draft_params = _place(
                    draft_params,
                    _decode_pspecs(draft_params, draft_cfg, mesh),
                    mesh)
            self._draft_params = draft_params
            self._draft_cfg = draft_cfg
            dn, dh = draft_cfg.kv_heads, draft_cfg.head_dim

            def dzeros():
                if cache_sh is not None:
                    return jnp.zeros((slots, smax, dn, dh),
                                     draft_cfg.dtype, device=cache_sh)
                return jnp.zeros((slots, smax, dn, dh),
                                 draft_cfg.dtype)
            self._draft_caches = [(dzeros(), dzeros())
                                  for _ in range(draft_cfg.n_layers)]

        if self.paged:
            self._init_paged(block_size, num_blocks,
                             radix_budget_blocks, prefix_reuse,
                             paged_kernel, kv_dtype)
            self._caches = None     # dense buffers never allocated
        else:
            if paged_kernel is not None or kv_dtype is not None:
                raise ValueError(
                    "paged_kernel / kv_dtype are paged-mode knobs; "
                    "pass paged=True to use them")
            def zeros():
                # allocate DIRECTLY in the sharded layout: a full
                # buffer on device 0 followed by a redistribute would
                # peak at the unsharded size there — the exact OOM
                # sharding avoids
                if cache_sh is not None:
                    return jnp.zeros((slots, smax, nkv, hd), cfg.dtype,
                                     device=cache_sh)
                return jnp.zeros((slots, smax, nkv, hd), cfg.dtype)
            self._caches = [(zeros(), zeros())
                            for _ in range(cfg.n_layers)]
        # live progprof producer attribution: while hpx.perfdb.record
        # is on, this server's key names the cost-surface point the
        # profiled programs belong to (see svc/perfdb.bank_profile)
        from ..svc import perfdb as _perfdb
        if _perfdb.record_enabled():
            _perfdb.ensure_counters()
            _perfdb.note_live_key(self.perf_key())
        # windowed decode throughput, read by the serving counters
        from ..svc.performance_counters import RateCounter
        self._rate = RateCounter(window_s=5.0)
        # host-side slot state
        self._slot_req: List[Optional[_Request]] = [None] * slots
        self._pos = [0] * slots         # next write position per slot
        self._cur = [0] * slots         # token to feed next, per slot
        self._temp = [0.0] * slots      # per-slot temperature
        self._key = [jax.random.PRNGKey(0)] * slots
        self._queue: deque = deque()
        self._done: Dict[int, List[int]] = {}
        self._next_rid = 0
        # chunked-prefill state: slot -> in-flight pending
        self._pending: Dict[int, _PendingPrefill] = {}
        self._pf_seq = 0
        # async-dispatch state: buffered (nxt, [(slot, req)]) steps
        # plus device-resident mirrors of the per-slot host vectors
        self._buf: deque = deque()
        self._cur_dev = None            # [slots] int32 token feedback
        self._temp_dev = None           # [slots] f32 (with _keys_dev)
        self._keys_dev = None
        # observability
        self._chunks = 0                # prefill chunk dispatches
        self._prog_hits = 0             # program-cache hits
        self._prog_misses = 0           # program-cache misses (compiles)
        self.ttft: Dict[int, float] = {}  # rid -> submit->seed seconds
        # resiliency: checkpoint cadence, step-retry policy, deadline
        # and shed accounting (ROADMAP item 5). `failed` is the typed
        # failure surface — run() keeps returning successes only.
        self._ckpt_every = max(1, rc.get_int(
            "hpx.serving.ckpt_every", 16))
        self._step_retries = max(1, rc.get_int(
            "hpx.serving.step_retries", 4))
        self._retry_backoff_s = max(0.0, rc.get_float(
            "hpx.serving.retry_backoff_s", 0.005))
        self._admit_retries = max(0, rc.get_int(
            "hpx.serving.admit_retries", 8))
        self._default_deadline_s = rc.get_float(
            "hpx.serving.default_deadline_s", 0.0)
        self._max_verify_faults = max(1, rc.get_int(
            "hpx.serving.spec.max_verify_faults", 2))
        self._ckpt: Dict[int, SlotCheckpoint] = {}
        self._closed = False
        self.failed: Dict[int, HpxError] = {}
        self._admit_defers: Dict[int, int] = {}  # rid -> OOM deferrals
        self._verify_faults = 0     # consecutive verify-site faults
        self._spec_degraded = False
        # /serving{...}/faults/* feed (see fault_stats)
        self._flt_injected = 0
        self._flt_retried = 0
        self._flt_restored = 0
        self._flt_shed = 0
        self._flt_degraded = 0
        # True while a bulk shed (retry exhaustion) records ONE
        # aggregate flight bundle instead of one per shed request
        self._flight_mute = False
        self._restored_by_site: Dict[str, int] = {}
        # SLO latency distributions (svc/metrics): live log-bucketed
        # histograms, one per family, registered (with derived pNN
        # counters) as /serving{...}/latency/* — plus the per-request
        # lifecycle timeline and checkpoint-restore timings (the
        # faults/restore-p99-s feed)
        from ..svc import metrics as _metrics
        self.hist: Dict[str, _metrics.HistogramCounter] = \
            _metrics.latency_histograms()
        self._restore_hist = _metrics.HistogramCounter()
        self.timeline = _metrics.RequestTimeline()
        self._last_step_t: Optional[float] = None
        self._stall_live = False
        # closed-loop adaptive tuning (svc/autotune): tick at flush
        # boundaries only — the one point where no step is in flight,
        # so a knob write cannot tear a dispatched program. Config
        # writes from OUTSIDE (operator set()) propagate through the
        # same boundary via _reload_knobs, keyed on the config
        # generation counter.
        self._cfg_gen = rc.generation()
        self._knob_raw = {k: rc.get(k) for k in _RELOADABLE_KNOBS}
        self._tune_stall_prev = None    # decode_stall snapshot at tick
        self._tuner = None
        if rc.get_bool("hpx.tune.enable", False):
            from ..svc.autotune import server_tuner
            self._tuner = server_tuner(self)
        # live observability (svc/exemplars, svc/slo_alerts,
        # svc/opsplane): every piece is None/empty unless its
        # hpx.obs.* knob is on, so the record and flush fast paths
        # keep their pre-observability cost (the hpx.trace.*
        # discipline). Exemplar reservoirs ride the SLO histograms;
        # the burn-rate evaluator ticks in _flush (built BEFORE
        # register_server so the /serving{...}/alerts/* counters see
        # it); the ops plane gets a weakref /statusz provider.
        from ..svc import exemplars as _exemplars
        _exemplars.attach_from_config(self.hist)
        self._alerts = None
        if rc.get_bool("hpx.obs.alerts", False):
            from ..svc.slo_alerts import server_alerts
            self._alerts = server_alerts(self)
        from ..cache.counters import register_server
        self.counter_instance = register_server(self)
        if self._alerts is not None:
            self._alerts.name = f"serving/{self.counter_instance}"
        from ..svc import opsplane as _opsplane
        if _opsplane.ensure_opsplane() is not None:
            _opsplane.register_provider(
                f"serving/{self.counter_instance}", self,
                ContinuousServer._statusz)

    def _init_paged(self, block_size, num_blocks, radix_budget_blocks,
                    prefix_reuse, paged_kernel=None,
                    kv_dtype=None) -> None:
        """Resolve the hpx.cache.* knobs and build the paged state:
        one preallocated block pool per layer (plus the [num_blocks,
        n_kv] f32 scale sidecars when ``hpx.cache.kv_dtype`` is a
        quantized dtype — ``int8`` or ``fp8``), the free-list/
        ref-count allocator over it, and the radix prefix tree."""
        from ..core.config import runtime_config
        cfg, slots, smax = self.cfg, self.slots, self.smax
        rc = runtime_config()
        self._kv_dtype = _resolve_kv_dtype(kv_dtype, rc)
        self._paged_kernel = paged_kernel = _resolve_paged_kernel(
            paged_kernel, rc)
        # the `fused=` mode threaded down to ops.paged_attention:
        # False -> gather oracle, True -> bitwise kernel, "online" ->
        # the O(block) online-softmax kernel
        self._paged_fused = {"gather": False, "fused": True,
                             "fused_online": "online"}[paged_kernel]
        learned = self._learned_ladder or {}
        if block_size is None:
            v = rc.get("hpx.cache.block_size", "auto")
            if v in (None, "", "auto"):
                if learned.get("block_size"):
                    # this shape's learned ladder carries its own
                    # block size — most specific tier, beats the
                    # (head_dim, kv_dtype)-keyed tables below
                    block_size = int(learned["block_size"])
                    self._block_size_src = "learned"
                else:
                    # perfdb learned-blocks tier, then the seed table
                    # banked by `benchmarks/flash_tune.py --paged`
                    # (ops/paged_blocks.json), then 16
                    block_size, self._block_size_src = \
                        resolve_paged_block_src(cfg.head_dim,
                                                self._kv_dtype, 16)
            else:
                block_size = int(v)
                self._block_size_src = "config"
        else:
            self._block_size_src = "arg"
        bs = int(block_size)
        if bs < 1:
            raise ValueError(f"block_size must be >= 1, got {bs}")
        if smax % bs:
            raise ValueError(
                f"paged serving needs smax divisible by the block "
                f"size {bs}; got smax {smax} (use smax="
                f"{-(-smax // bs) * bs})")
        self.block_size = bs
        self._maxb = smax // bs     # table width: blocks per sequence
        if num_blocks is None:
            v = rc.get("hpx.cache.num_blocks", "auto")
            num_blocks = None if v in (None, "", "auto") else int(v)
        if num_blocks is None:
            # worst-case live demand (every slot at smax) + the trash
            # block + equal headroom for radix retention, so prefix
            # chains persist before OOM-eviction starts recycling them
            num_blocks = 2 * slots * self._maxb + 1
        if num_blocks < self._maxb + 1:
            raise ValueError(
                f"num_blocks {num_blocks} cannot hold one max-length "
                f"request ({self._maxb} blocks) plus the reserved "
                "trash block")
        if radix_budget_blocks is None:
            v = rc.get("hpx.cache.radix_budget_blocks", "auto")
            radix_budget_blocks = (None if v in (None, "", "auto")
                                   else int(v))
        if prefix_reuse is None:
            prefix_reuse = rc.get_bool("hpx.cache.prefix_reuse", True)
        self._prefix_reuse = bool(prefix_reuse)
        self._alloc = BlockAllocator(num_blocks, bs,
                                     kv_dtype=self._kv_dtype)
        # the trash block: dead slots' tables and table padding point
        # here, so masked decode lanes scatter into rows nothing reads
        self._trash = self._alloc.alloc()
        self._radix = RadixCache(self._alloc, radix_budget_blocks)
        # host-RAM demotion tier (cache/tier.py): radix evictions
        # demote raw block rows + scale sidecars into host buffers,
        # and the two-tier match promotes them back through the
        # KVSegment framing when the crossover gate says restore
        # beats re-prefill
        self._tier = None
        self._tier_gate = None
        self._tier_rx = None
        self._tier_hist = None
        if rc.get_bool("hpx.cache.tier.enable", False):
            from ..cache.tier import HostTier, RestoreGate
            from ..cache.transfer import TransferReceiver
            from ..svc import metrics as _metrics
            budget_mb = rc.get_int("hpx.cache.tier.host_budget_mb",
                                   256)
            self._tier = HostTier(budget_mb << 20, block_size=bs)
            self._tier_gate = RestoreGate()
            self._tier_rx = TransferReceiver()
            self._tier_hist = _metrics.HistogramCounter()
            self._radix.demote_hook = self._demote_block
        nkv, hd = cfg.kv_heads, cfg.head_dim

        # sharded paged serving: pools/scales shard their kv-head axis
        # over tp and REPLICATE the block axis over dp (the allocator's
        # pool_pspec rule) — one global allocator/radix/table space,
        # every block id resolvable on every dp shard, so per-shard
        # table gathers never cross shards. Tables shard their slot
        # rows over dp (knob-controlled; see cache.page_table.
        # device_table).
        self._pool_sh = self._scale_sh = None
        self._table_residency = "sharded"
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._pool_sh = NamedSharding(
                self.mesh, P(*self._alloc.pool_pspec("tp")))
            self._scale_sh = NamedSharding(
                self.mesh, P(*self._alloc.scale_pspec("tp")))
            self._table_residency = rc.get(
                "hpx.serving.mesh.table_residency", "sharded")
            if self._table_residency not in ("sharded", "replicated"):
                raise ValueError(
                    "hpx.serving.mesh.table_residency must be "
                    "'sharded' or 'replicated', got "
                    f"{self._table_residency!r}")

        def pzeros():
            # allocate directly in the sharded layout (same OOM logic
            # as the dense zeros(): never materialize the full pool on
            # one device first)
            dt = {"int8": jnp.int8,
                  "fp8": jnp.float8_e4m3fn}.get(self._kv_dtype,
                                                cfg.dtype)
            if self._pool_sh is not None:
                return jnp.zeros((num_blocks, bs, nkv, hd), dt,
                                 device=self._pool_sh)
            return jnp.zeros((num_blocks, bs, nkv, hd), dt)
        self._pools = [(pzeros(), pzeros())
                       for _ in range(cfg.n_layers)]
        if self._kv_dtype in ("int8", "fp8"):
            def sones():
                # scale 1.0 is quantize_blocks' zero-block convention:
                # fresh pools dequantize to exact zeros
                if self._scale_sh is not None:
                    return jnp.ones((num_blocks, nkv), jnp.float32,
                                    device=self._scale_sh)
                return jnp.ones((num_blocks, nkv), jnp.float32)
            self._scales = [(sones(), sones())
                            for _ in range(cfg.n_layers)]
        else:
            self._scales = None
        self._tables: List[Optional[PageTable]] = [None] * slots
        self._tables_sig = None     # (uid, version) per slot
        self._tables_arr = None     # cached device [slots, maxb] map
        self._prefill_saved = 0
        self._prefill_computed = 0

    # -- jitted pieces (memoized on the baked constants) ----------------

    def _program(self, ck, build):
        """All program lookups go through here so the compile-cache
        hit/miss counters see every build (the /serving programs/*
        counters; the compile-count guard test reads them too).
        Builders that donate (donate_argnums) rely on callers
        rebinding the result over the donated binding — hpxlint
        HPX020 flags any other use after the donating call."""
        from .transformer import _PROGRAMS
        if ck in _PROGRAMS:
            self._prog_hits += 1
        else:
            self._prog_misses += 1
        return _cached_program(ck, build)

    def _moe_cf(self):
        """Effective decode capacity factor from the int-percent knob
        (None for dense models, so dense bodies never see the knob)."""
        if self.cfg.n_experts <= 0:
            return None
        return self._moe_capacity_pct / 100.0

    def _moe_ep(self):
        """(axis, size) for expert-parallel routing inside the
        shard_map paged bodies; None on a single shard — and for the
        GSPMD dense programs, which partition the expert einsums from
        placement alone and must never call collectives directly."""
        if self.cfg.n_experts <= 0 or self._ep_axis is None \
                or self._ep_size <= 1:
            return None
        return (self._ep_axis, self._ep_size)

    def _step_prog(self):
        cfg, slots, smax = self.cfg, self.slots, self.smax
        ck = ("cb_step", cfg, slots, smax, self._moe_capacity_pct,
              self.mesh, _tree_key(self.params))

        def build():
            cache_sh = self._cache_sh
            moe_cf = self._moe_cf()
            ms_sh = self._moe_stats_sh()

            def step(params, caches, tok, pos, temp, keys):
                if cache_sh is not None:
                    caches = jax.tree.map(
                        lambda c: jax.lax.with_sharding_constraint(
                            c, cache_sh), caches)
                caches, logits, ms = _decode_rows(
                    params, caches, tok, pos, cfg, moe_cf,
                    moe_ms=ms_sh)
                nxt = jax.vmap(_pick_row)(logits, keys, temp, pos)
                return caches, nxt, ms
            return jax.jit(step, donate_argnums=(1,))
        return self._program(ck, build)

    def _moe_stats_sh(self):
        """Replicated sharding for the MoE stats vector under GSPMD
        dense programs. The partitioner propagates the expert-sharded
        weight layout back into the (replicated-by-construction)
        dispatch tensor without reslicing it, so the stats sums come
        out multiplied by the expert-shard count; pinning the vector
        replicated makes XLA close the sums correctly. The shard_map
        paged programs psum explicitly and never need this."""
        if self.mesh is None or self.cfg.n_experts <= 0:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def _chunk_prog(self, width: int):
        """One bucketed prefill chunk: toks [1, width] (tail-padded
        with token 0) written into the b=1 scratch at absolute
        positions pos0..pos0+width-1. Keyed per LADDER WIDTH, not per
        prompt length — the whole point. Pad rows land past the real
        frontier; they are never attended (causal mask) and the next
        chunk or the decode steps overwrite them before their
        positions ever go live."""
        cfg, smax = self.cfg, self.smax
        ck = ("cb_chunk", cfg, width, smax, self.mesh,
              _tree_key(self.params))

        def build():
            def chunk(params, caches, toks, pos0):
                caches, _ = _decode_window(params, caches, toks, pos0,
                                           cfg, need_logits=False)
                return caches
            return jax.jit(chunk, donate_argnums=(1,))
        return self._program(ck, build)

    def _probe_prog(self):
        """Seed-logits probe: rerun the LAST prompt token at its own
        position (an idempotent K/V rewrite — same bytes) and return
        its logits. One program serves every prompt length, so the
        chunk programs never need a logits variant per bucket."""
        cfg, smax = self.cfg, self.smax
        ck = ("cb_probe", cfg, smax, self.mesh, _tree_key(self.params))

        def build():
            def probe(params, caches, tok, pos):
                caches, lg = _decode_window(params, caches, tok, pos,
                                            cfg, need_logits=True)
                return caches, lg[:, -1]
            return jax.jit(probe, donate_argnums=(1,))
        return self._program(ck, build)

    def _splice_prog(self):
        """Copy the b=1 scratch cache into one slot's rows — ALL smax
        rows, so one program serves every prompt length (the garbage
        rows past plen are exactly what the slot held before: never
        read until decode overwrites them)."""
        slots, smax = self.slots, self.smax
        ck = ("cb_splice", self.cfg, slots, smax, self.mesh,
              _tree_key(self.params))

        def build():
            cache_sh = self._cache_sh

            def splice(caches, one, slot):
                if cache_sh is not None:
                    caches = jax.tree.map(
                        lambda c: jax.lax.with_sharding_constraint(
                            c, cache_sh), caches)
                out = []
                for (kc, vc), (k1, v1) in zip(caches, one):
                    kc = jax.lax.dynamic_update_slice(
                        kc, k1.astype(kc.dtype), (slot, 0, 0, 0))
                    vc = jax.lax.dynamic_update_slice(
                        vc, v1.astype(vc.dtype), (slot, 0, 0, 0))
                    out.append((kc, vc))
                return out
            return jax.jit(splice, donate_argnums=(0,))
        return self._program(ck, build)

    # -- paged programs (models live in pools; tables map positions) -----

    def _paged_step_prog(self):
        cfg, slots, smax = self.cfg, self.slots, self.smax
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_step", cfg, slots, smax, nb, bs, self._kv_dtype,
              self._paged_kernel, self._moe_capacity_pct, self.mesh,
              _tree_key(self.params))

        def build():
            fused = self._paged_fused
            tp_axis = None if self.mesh is None else "tp"
            moe_cf = self._moe_cf()
            moe_ep = self._moe_ep()

            def step(params, pools, scales, tok, pos, tables, temp,
                     keys):
                pools, scales, logits, ms = _paged_decode_rows(
                    params, pools, scales, tok, tables, pos, cfg,
                    fused, tp_axis, moe_cf, moe_ep)
                nxt = jax.vmap(_pick_row)(logits, keys, temp, pos)
                if ms is not None and tp_axis is not None:
                    # fold the per-dp-group stats into one replicated
                    # vector: routed/dropped claims sum over groups,
                    # occupancy fractions average
                    ms = jnp.concatenate(
                        [jax.lax.psum(ms[:2], "dp"),
                         jax.lax.pmean(ms[2:], "dp")])
                return pools, scales, nxt, ms
            if self.mesh is None:
                return self._jit_step(step)
            # sharded paged decode runs under shard_map, NOT bare
            # GSPMD: each dp shard steps ITS slots against its LOCAL
            # pool replica (block tables are per-shard int32 into a
            # dp-replicated block axis — the gather can never cross
            # shards), tp shards the kv-head axis with explicit psums
            # in _paged_block_rows, and MoE layers route tokens over
            # the expert axis via moe_ffn_decode's tiled all_to_all.
            # Per-slot sampling (keys fold per slot, row 0) is
            # shard-local, so emitted tokens match the single-device
            # server exactly.
            from jax.sharding import PartitionSpec as P
            from ..utils.jaxcompat import shard_map
            pspecs, pool_sp, scale_sp = self._paged_shard_specs()
            return self._jit_step(shard_map(
                step, mesh=self.mesh,
                in_specs=(pspecs, pool_sp, scale_sp, P("dp"),
                          P("dp"), P("dp", None), P("dp"),
                          P("dp", None)),
                out_specs=(pool_sp, scale_sp, P("dp"), P())))
        return self._program(ck, build)

    def _jit_step(self, step):
        # scales donate too: for bf16 pools the arg is None (an empty
        # pytree), which donation treats as a no-op
        return jax.jit(step, donate_argnums=(1, 2))

    def _paged_shard_specs(self):
        """Spec trees for the shard_map-wrapped paged programs:
        (param pspecs, pool spec, scale spec). Pools replicate the
        block axis over dp and shard kv-heads over tp (the allocator's
        pool_pspec rule); the scale spec degrades to P() for bf16
        pools, where the scales argument is an empty pytree."""
        from jax.sharding import PartitionSpec as P
        from .transformer import _decode_pspecs
        pool_sp = P(*self._alloc.pool_pspec("tp"))
        scale_sp = (P(*self._alloc.scale_pspec("tp"))
                    if self._scales is not None else P())
        return (_decode_pspecs(self.params, self.cfg, self.mesh),
                pool_sp, scale_sp)

    def _paged_gather_prog(self):
        """Materialize one request's (possibly prefix-matched) blocks
        into a contiguous b=1 scratch cache the shared chunk/probe
        programs run over — int8 pools dequantize here, so the scratch
        (and every chunk program over it) stays in the compute dtype.
        Rows at/past `valid` (the matched prefix length) zero out:
        they gather from not-yet-written blocks and table padding, and
        stale quantized garbage can dequantize to values large enough
        to defeat additive attention masking (an fp8 byte times a
        stale f32 scale is unbounded) — zeroing makes the scratch a
        pure function of the matched content instead of allocation
        history. Keyed once per server shape."""
        cfg = self.cfg
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_gather", cfg, self.smax, nb, bs, self._kv_dtype,
              self.mesh, _tree_key(self.params))

        def build():
            dt = cfg.dtype
            rows = self._maxb * bs

            def gather(pools, scales, trow, valid):
                keep = (jnp.arange(rows) < valid)[None, :, None, None]
                if scales is None:
                    return [(jnp.where(keep,
                                       gather_block_kv(kp, trow[None]),
                                       0),
                             jnp.where(keep,
                                       gather_block_kv(vp, trow[None]),
                                       0))
                            for kp, vp in pools]
                return [(jnp.where(keep,
                                   gather_block_kv(kp, trow[None], ks,
                                                   dt), 0),
                         jnp.where(keep,
                                   gather_block_kv(vp, trow[None], vs,
                                                   dt), 0))
                        for (kp, vp), (ks, vs) in zip(pools, scales)]
            return jax.jit(gather)
        return self._program(ck, build)

    def _paged_splice_prog(self):
        """Write the request's padded block row back from the b=1
        scratch (chunked-prefill splice). One program for every
        (matched, plen) combination: the WRITE row (`_start_paged`'s
        `wrow`) redirects radix-matched prefix entries to the trash
        block, so shared prefix blocks are never rewritten — for bf16
        the skipped write was an identity copy of the bytes the gather
        read; for int8 it would be a dequant(bf16)->requant of a
        SHARED block (a ±1-quantum walk other readers would see), so
        skipping it is what keeps prefix reuse exact. The trash-padded
        tail (and the redirected prefix) is garbage-on-garbage (see
        scatter_seq_blocks); int8 splices quantize whole blocks here
        (scatter_seq_blocks_q)."""
        cfg = self.cfg
        nb, bs = self._alloc.num_blocks, self.block_size
        maxb = self._maxb
        ck = ("pg_splice", cfg, self.smax, nb, bs, self._kv_dtype,
              self.mesh, _tree_key(self.params))

        def build():
            pool_sh, scale_sh = self._pool_sh, self._scale_sh

            def splice(pools, scales, one, wrow):
                outp, outs = [], []
                for i, ((kp, vp), (kc, vc)) in enumerate(
                        zip(pools, one)):
                    kseg = kc[0].reshape(maxb, bs, *kc.shape[2:])
                    vseg = vc[0].reshape(maxb, bs, *vc.shape[2:])
                    if scales is None:
                        outp.append(
                            (scatter_seq_blocks(kp, wrow, kseg),
                             scatter_seq_blocks(vp, wrow, vseg)))
                    else:
                        ks, vs = scales[i]
                        kp, ks = scatter_seq_blocks_q(kp, ks, wrow,
                                                      kseg)
                        vp, vs = scatter_seq_blocks_q(vp, vs, wrow,
                                                      vseg)
                        outp.append((kp, vp))
                        outs.append((ks, vs))
                if pool_sh is not None:
                    # pin the sharded-pool layout: the scatter stays a
                    # per-device local write (block axis replicated
                    # over dp, kv-heads over tp) and donation reuses
                    # the input buffers in place — whole-block splice
                    # writes are therefore IDENTICAL on every dp
                    # replica, the coherence property radix prefix
                    # sharing on the mesh rests on
                    outp = jax.lax.with_sharding_constraint(
                        outp, pool_sh)
                    if outs:
                        outs = jax.lax.with_sharding_constraint(
                            outs, scale_sh)
                return outp, (None if scales is None else outs)
            return jax.jit(splice, donate_argnums=(0, 1))
        return self._program(ck, build)

    def _copy_block_prog(self):
        """Device side of allocator copy-on-write: duplicate one
        block's rows src->dst across every layer's pools (int8 pools
        copy the block's scale sidecar entries too — a forked block
        must dequantize identically to its source)."""
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_copy", self.cfg, self.smax, nb, bs, self._kv_dtype,
              self.mesh, _tree_key(self.params))

        def build():
            pool_sh, scale_sh = self._pool_sh, self._scale_sh

            def copy(pools, scales, src, dst):
                pools = [(kp.at[dst].set(kp[src]),
                          vp.at[dst].set(vp[src]))
                         for kp, vp in pools]
                if scales is not None:
                    scales = [(ks.at[dst].set(ks[src]),
                               vs.at[dst].set(vs[src]))
                              for ks, vs in scales]
                if pool_sh is not None:
                    # per-replica local copy: src's rows on each dp
                    # replica land in that replica's dst — exactly the
                    # COW semantics each owning shard needs
                    pools = jax.lax.with_sharding_constraint(
                        pools, pool_sh)
                    if scales is not None:
                        scales = jax.lax.with_sharding_constraint(
                            scales, scale_sh)
                return pools, scales
            return jax.jit(copy, donate_argnums=(0, 1))
        return self._program(ck, build)

    def _tier_restore_prog(self):
        """Host-tier promotion splice: write ONE restored block's RAW
        pool-dtype rows (and the f32 scale sidecars on quantized
        pools) at its promoted block id. Dequantize-free by
        construction — the bytes written are the bytes demoted, so a
        promoted block dequantizes bit-identically to the block the
        radix tree evicted (the sha-identity the crossover tests pin).
        One block per dispatch keeps the program shape fixed — a
        promotion chain costs N dispatches, never N compiles."""
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_tier_restore", self.cfg, self.smax, nb, bs,
              self._kv_dtype, self.mesh, _tree_key(self.params))

        def build():
            pool_sh, scale_sh = self._pool_sh, self._scale_sh

            def restore(pools, scales, bid, rows, scs):
                pools = [(kp.at[bid].set(rows[li, 0].astype(kp.dtype)),
                          vp.at[bid].set(rows[li, 1].astype(vp.dtype)))
                         for li, (kp, vp) in enumerate(pools)]
                if scales is not None:
                    scales = [(ks.at[bid].set(scs[li, 0]),
                               vs.at[bid].set(scs[li, 1]))
                              for li, (ks, vs) in enumerate(scales)]
                if pool_sh is not None:
                    # dp-replicated block axis: the restored rows land
                    # on every dp replica, same as a colocated write
                    pools = jax.lax.with_sharding_constraint(
                        pools, pool_sh)
                    if scales is not None:
                        scales = jax.lax.with_sharding_constraint(
                            scales, scale_sh)
                return pools, scales
            return jax.jit(restore, donate_argnums=(0, 1))
        return self._program(ck, build)

    # -- speculative programs (verify windows + draft model) -------------

    def _verify_prog(self, width: int):
        """Dense window-verify: ONE forward over a width-W window at
        per-slot positions, returning packed targets+acceptance. Keyed
        per LADDER WIDTH (same ladder as the prefill chunks), so the
        program cache stays O(buckets) however adaptive k wanders."""
        cfg, slots, smax = self.cfg, self.slots, self.smax
        ck = ("cb_verify", cfg, slots, smax, width,
              self._moe_capacity_pct, self.mesh,
              _tree_key(self.params))

        def build():
            cache_sh = self._cache_sh
            moe_cf = self._moe_cf()
            ms_sh = self._moe_stats_sh()

            def verify(params, caches, toks, pos0, kvec, temp, keys):
                if cache_sh is not None:
                    caches = jax.tree.map(
                        lambda c: jax.lax.with_sharding_constraint(
                            c, cache_sh), caches)
                caches, logits, ms = _decode_window_rows(
                    params, caches, toks, pos0, cfg, moe_cf,
                    moe_ms=ms_sh)
                return caches, _verify_tail(
                    logits, toks, kvec, temp, keys, pos0, width), ms
            return jax.jit(verify, donate_argnums=(1,))
        return self._program(ck, build)

    def _paged_verify_prog(self, width: int):
        cfg, slots, smax = self.cfg, self.slots, self.smax
        nb, bs = self._alloc.num_blocks, self.block_size
        ck = ("pg_verify", cfg, slots, smax, width, nb, bs,
              self._kv_dtype, self._paged_kernel,
              self._moe_capacity_pct, self.mesh,
              _tree_key(self.params))

        def build():
            fused = self._paged_fused
            tp_axis = None if self.mesh is None else "tp"
            moe_cf = self._moe_cf()
            moe_ep = self._moe_ep()

            def verify(params, pools, scales, toks, pos0, tables,
                       kvec, temp, keys):
                pools, scales, logits, ms = _paged_decode_window_rows(
                    params, pools, scales, toks, tables, pos0, cfg,
                    fused, tp_axis, moe_cf, moe_ep)
                if ms is not None and tp_axis is not None:
                    ms = jnp.concatenate(
                        [jax.lax.psum(ms[:2], "dp"),
                         jax.lax.pmean(ms[2:], "dp")])
                return pools, scales, _verify_tail(
                    logits, toks, kvec, temp, keys, pos0, width), ms
            if self.mesh is None:
                return jax.jit(verify, donate_argnums=(1, 2))
            # same shard_map layout as _paged_step_prog, stretched to
            # the verify window: toks/packed targets carry a width
            # column axis, everything else is the step's specs. The
            # _verify_tail pick is per-slot (shard-local) so spec
            # acceptance matches the single-device server exactly.
            from jax.sharding import PartitionSpec as P
            from ..utils.jaxcompat import shard_map
            pspecs, pool_sp, scale_sp = self._paged_shard_specs()
            return jax.jit(shard_map(
                verify, mesh=self.mesh,
                in_specs=(pspecs, pool_sp, scale_sp, P("dp", None),
                          P("dp"), P("dp", None), P("dp"), P("dp"),
                          P("dp", None)),
                out_specs=(pool_sp, scale_sp, P("dp", None), P())),
                donate_argnums=(1, 2))
        return self._program(ck, build)

    def _draft_step_prog(self):
        """One greedy draft-model step at per-slot positions. The
        draft ALWAYS proposes greedily — draft quality moves only the
        acceptance rate, never the emitted tokens."""
        dcfg, slots, smax = self._draft_cfg, self.slots, self.smax
        ck = ("cb_draft", dcfg, slots, smax, self.mesh,
              _tree_key(self._draft_params))

        def build():
            def step(params, caches, tok, pos):
                caches, logits, _ms = _decode_rows(params, caches, tok,
                                                   pos, dcfg)
                return caches, jnp.argmax(logits, axis=-1) \
                                  .astype(jnp.int32)
            return jax.jit(step, donate_argnums=(1,))
        return self._program(ck, build)

    def _draft_chunk_prog(self, width: int):
        """One bucketed prefill chunk for ONE slot of the draft-model
        cache: slice the slot's b=1 rows, run the shared window
        forward, write them back. Same ladder widths as the target's
        chunks — O(buckets) draft programs."""
        dcfg, smax = self._draft_cfg, self.smax
        ck = ("cb_dchunk", dcfg, width, smax, self.slots, self.mesh,
              _tree_key(self._draft_params))

        def build():
            def chunk(params, caches, toks, pos0, slot):
                one = [(jax.lax.dynamic_slice_in_dim(kc, slot, 1, 0),
                        jax.lax.dynamic_slice_in_dim(vc, slot, 1, 0))
                       for kc, vc in caches]
                one, _ = _decode_window(params, one, toks, pos0, dcfg,
                                        need_logits=False)
                out = []
                for (kc, vc), (k1, v1) in zip(caches, one):
                    kc = jax.lax.dynamic_update_slice(
                        kc, k1.astype(kc.dtype), (slot, 0, 0, 0))
                    vc = jax.lax.dynamic_update_slice(
                        vc, v1.astype(vc.dtype), (slot, 0, 0, 0))
                    out.append((kc, vc))
                return out
            return jax.jit(chunk, donate_argnums=(1,))
        return self._program(ck, build)

    # -- paged host-side bookkeeping -------------------------------------

    def _alloc_block(self) -> int:
        """allocator.alloc with the OOM→evict→retry discipline: a full
        pool first evicts the least-recently-used idle radix chain
        (retained prefixes are a cache, not a reservation). Injected
        OOM faults (`svc/faultinject`, site "alloc") walk the SAME
        ladder — counted, evicted against, retried — and escalate (to
        the step-level restore path or the admission defer/shed
        ladder) only when eviction has nothing left to give."""
        try:
            return self._alloc.alloc()
        except CacheOOM as e:
            injected = isinstance(e, faultinject.InjectedFault)
            if injected:
                self._flt_injected += 1
            if not sum(self._radix.evict(1)):
                raise
            if injected:
                self._flt_retried += 1
            return self._alloc.alloc()

    def _cow_guard(self, pt: PageTable, bi: int) -> None:
        """Make the block backing logical block `bi` exclusively ours
        before writing into it (copy-on-write fork + device copy)."""
        bid = pt.blocks[bi]
        if self._alloc.refcount(bid) > 1:
            new, copied = self._alloc.fork(bid)
            if copied:
                self._pools, self._scales = self._copy_block_prog()(
                    self._pools, self._scales, jnp.int32(bid),
                    jnp.int32(new))
                pt.replace_block(bi, new)

    def _ensure_block(self, slot: int, pos: int) -> None:
        """Before a decode write at `pos`: extend the slot's table to
        cover it, and make the target block exclusively ours (COW
        guard — unreachable under the publish-at-retire policy, since
        writes always land past the shared prefix, but correctness
        must not depend on the policy staying that way)."""
        pt = self._tables[slot]
        assert pt is not None
        while pt.capacity <= pos:
            pt.append_block(self._alloc_block())
        self._cow_guard(pt, pos // self.block_size)

    def _ensure_window(self, slot: int, pos0: int, last: int) -> None:
        """`_ensure_block` generalized to a speculative verify window:
        cover every write position in [pos0, last] and COW-guard each
        covered block — draft rows must never land in a radix-shared
        block. Window pad columns past `last` need no coverage: the
        table row pads with the trash block, so their scatters land in
        rows nothing ever reads."""
        last = min(last, self.smax - 1)
        pt = self._tables[slot]
        assert pt is not None
        while pt.capacity <= last:
            pt.append_block(self._alloc_block())
        for bi in range(pos0 // self.block_size,
                        last // self.block_size + 1):
            self._cow_guard(pt, bi)

    def _tables_dev(self):
        """The [slots, maxb] int32 device map for one decode step,
        rebuilt ONLY when some table mutated (PageTable.version) or a
        slot's table was swapped — steady-state decode re-uploads
        nothing. On a mesh the rows land per `hpx.serving.mesh.
        table_residency` (slot rows over dp by default) via
        cache.page_table.device_table; ids stay GLOBAL either way."""
        sig = tuple((pt.uid, pt.version) if pt is not None else None
                    for pt in self._tables)
        if sig != self._tables_sig or self._tables_arr is None:
            from ..cache.page_table import device_table
            self._tables_arr = device_table(
                self._tables, self._maxb, self._trash, mesh=self.mesh,
                residency=self._table_residency)
            self._tables_sig = sig
        return self._tables_arr

    def _release_slot(self, slot: int, req: "_Request") -> None:
        """Paged retire: publish the request's FULL prompt blocks into
        the radix tree (prefix reuse for future admits), then drop the
        request's references — shared blocks survive under the tree's
        ref, private ones return to the free list."""
        pt = self._tables[slot]
        if pt is None:
            return
        if self._prefix_reuse:
            nfull = len(req.prompt) // self.block_size
            if nfull:
                self._radix.insert(
                    req.prompt[:nfull * self.block_size],
                    pt.blocks[:nfull])
        for bid in pt.blocks:
            self._alloc.decref(bid)
        self._tables[slot] = None

    # -- host tier (cache/tier.py): demotion + gated promotion -----------

    def _demote_block(self, chain: int, parent: int, key, bid: int):
        """RadixCache demote hook: copy one evicted block's RAW pool
        rows (quantized bytes on int8/fp8 pools, plus the f32 scale
        sidecars) to the host tier. Runs under the radix lock BEFORE
        the tree reference drops, so the rows are stable; published
        blocks are immutable (COW + trash-redirected splices), so the
        snapshot is the block's final bytes. Returns the tier's
        verdict — False (budget refuses) counts the eviction as
        dropped, exactly the pre-tier behavior."""
        tier = self._tier
        if tier is None:
            return False
        layers = []
        scl = [] if self._scales is not None else None
        for li, (kp, vp) in enumerate(self._pools):
            layers.append(np.stack((np.asarray(kp[bid]),
                                    np.asarray(vp[bid]))))
            if scl is not None:
                ks, vs = self._scales[li]
                scl.append(np.stack((np.asarray(ks[bid]),
                                     np.asarray(vs[bid]))))
        rows = np.stack(layers)             # [L, 2, bs, n_kv, hd]
        scs = (np.stack(scl).astype(np.float32)
               if scl is not None else None)    # [L, 2, n_kv]
        return tier.demote(chain, parent, key, rows, scs)

    def _promote_tier(self, req: "_Request", matched: int,
                      mbids: List[int], ext) -> int:
        """Crossover-gated promotion of a host-tier hit: when restore
        beats re-prefill (RestoreGate), re-ship the tier entries'
        raw rows through the KVSegment framing (checksums, idempotent
        seq numbers — the disagg delivery discipline, exercised
        in-process), splice them dequantize-free at freshly allocated
        block ids, and republish the chain in the radix tree. Appends
        the promoted ids to `mbids` and returns the extra whole-block
        tokens restored (0 = gate declined or nothing could be held —
        the caller re-prefills, entries stay in the tier)."""
        from ..cache.transfer import make_segment
        bs = self.block_size
        promote, _est = self._tier_gate.should_promote(
            len(ext) * bs, sum(nb for _, _, nb in ext))
        if not promote:
            self._tier.declined(len(ext))
            return 0
        t0 = time.perf_counter()
        bids: List[int] = []
        try:
            for _ in ext:
                bids.append(self._alloc_block())
        except CacheOOM:
            pass        # a partial chain prefix is still a win
        if not bids:
            self._tier.declined(len(ext))
            return 0
        entries = []
        for h, _chunk, _nb in ext[:len(bids)]:
            e = self._tier.checkout(h)
            if e is None:
                break   # raced out by a concurrent demotion wave
            entries.append(e)
        n = len(entries)
        for bid in bids[n:]:
            self._alloc.decref(bid)
        bids = bids[:n]
        if not n:
            return 0
        rid = f"tier:{req.rid}:{self._pf_seq}"
        try:
            for i, e in enumerate(entries):
                self._tier_rx.ingest(make_segment(
                    rid, i, i * bs, n * bs, e.rows))
                if e.scales is not None:
                    self._tier_rx.ingest(make_segment(
                        "scale/" + rid, i, i, n,
                        e.scales[:, :, None, :]))
            rows = self._tier_rx.assemble(rid)
            scs = (self._tier_rx.assemble("scale/" + rid)
                   if entries[0].scales is not None else None)
        except HpxError:
            # corrupt/incomplete frame: keep the data (putback), free
            # the blocks, fall back to re-prefill — never a leak
            self._tier_rx.abort(rid)
            self._tier_rx.abort("scale/" + rid)
            for e in entries:
                self._tier.putback(e)
            for bid in bids:
                self._alloc.decref(bid)
            return 0
        for i, bid in enumerate(bids):
            blk = jnp.asarray(rows[:, :, i * bs:(i + 1) * bs])
            sblk = (None if scs is None
                    else jnp.asarray(scs[:, :, i]))
            self._pools, self._scales = self._tier_restore_prog()(
                self._pools, self._scales, jnp.int32(bid), blk, sblk)
        # republish: the tree takes its reference on the promoted
        # blocks (refcount 2 = tree + our lease, same as a hot match)
        self._radix.insert(req.prompt[:matched + n * bs],
                           list(mbids) + bids)
        mbids.extend(bids)
        for e in entries:
            self._tier.checkin(e)
        if self._tier_hist is not None:
            jax.block_until_ready(self._pools)
            self._tier_hist.record(time.perf_counter() - t0)
        return n * bs

    def cache_stats(self) -> Dict[str, float]:
        """Paged-mode observability snapshot (the same numbers the
        /cache{...} performance counters export)."""
        if not self.paged:
            raise ValueError("cache_stats() requires paged=True")
        st: Dict[str, float] = dict(self._alloc.stats())
        st.update(self._radix.stats())
        if self._tier is not None:
            st.update(self._tier.stats())
        st["prefill_tokens_saved"] = self._prefill_saved
        st["prefill_tokens_computed"] = self._prefill_computed
        st.update(self.hbm_read_stats())
        if self.mesh is not None:
            # per-dp-shard slot accounting: slots map to dp shards by
            # index range (the P("dp") slot-axis sharding), so shard
            # d's decode reads exactly these slots' mapped blocks —
            # the skew between shards is the load-balance signal
            dp = self.mesh.shape["dp"]
            per = self.slots // dp
            for d in range(dp):
                st[f"occupancy_dp{d}"] = occupancy(
                    self._tables[d * per:(d + 1) * per])
        return st

    def _kv_acct_dtype(self) -> str:
        """block_bytes key for the POOLS AS ALLOCATED: kv_dtype=bf16
        stores the model compute dtype, which tier-1's CPU configs set
        to f32 — account what is actually resident, not the label.
        int8 and fp8 pools store 1 byte/elem regardless of the compute
        dtype, so their labels pass through."""
        if self._kv_dtype in ("int8", "fp8"):
            return self._kv_dtype
        return ("f32" if jnp.dtype(self.cfg.dtype).itemsize == 4
                else "bf16")

    def perf_key(self) -> str:
        """This server's point on the perfdb cost surface —
        ``device|shape|kv_dtype|kernel|mesh`` (see svc/perfdb).  The
        key the learned-ladder boot consult resolves against, and the
        one producers bank this server's costs under."""
        from ..svc import perfdb as _perfdb
        return str(_perfdb.PerfKey(
            _perfdb.device_kind(), _perfdb.shape_str(self.cfg),
            self._kv_dtype if self.paged else "-",
            self._paged_kernel if self.paged else "dense",
            _perfdb.mesh_str(self.mesh)))

    def hbm_read_stats(self) -> Dict[str, Any]:
        """Modeled decode-attention HBM read cost per generated token,
        fed from pool dtype + table occupancy (the
        /cache{...}/{count,bytes}/hbm-read-per-token counters and the
        serving-bench roofline columns).

        Each decode step emits one token per live slot and streams
        every MAPPED block of that slot once per layer, K and V pools
        both (the fused kernels read the padded table tail too, but
        those entries all alias the single resident trash block —
        occupancy is the honest per-slot traffic). bytes/token uses
        `cache.block_allocator.block_bytes`, so the int8/fp8 sidecar
        scales are included: vs a bf16 compute dtype the quantized
        pools read ~0.5x, and vs tier-1's f32 compute dtype ~0.25x —
        the fp8 roofline ratio the acceptance gate pins at <= 0.30x."""
        if not self.paged:
            raise ValueError("hbm_read_stats() requires paged=True")
        live = sum(1 for pt in self._tables if pt is not None)
        blocks = occupancy(self._tables)
        per_tok = (blocks / live) if live else 0.0
        bb = block_bytes(self.block_size, self.cfg.kv_heads,
                         self.cfg.head_dim, self._kv_acct_dtype(),
                         layers=self.cfg.n_layers)
        return {
            "hbm_read_blocks_per_token": per_tok,
            "hbm_read_bytes_per_token": per_tok * bb,
            # where this server's block_size came from: arg | config |
            # env | learned (perfdb) | seed (paged_blocks.json) |
            # default — the satellite audit hook for learned ladders
            "block_size_source": self._block_size_src,
        }

    def spec_stats(self) -> Dict[str, float]:
        """Speculation observability snapshot (the same numbers the
        /serving{...}/spec/* performance counters export)."""
        drafted, steps = self._spec_drafted, self._spec_steps
        return {
            "drafted": float(drafted),
            "accepted": float(self._spec_accepted),
            "acceptance_rate": (self._spec_accepted / drafted)
                               if drafted else 0.0,
            "steps": float(steps),
            "emitted": float(self._spec_emitted),
            "tokens_per_step": (self._spec_emitted / steps)
                               if steps else 0.0,
        }

    def fault_stats(self) -> Dict[str, Any]:
        """Resiliency observability snapshot — the scalar fields feed
        the /serving{...}/faults/* performance counters; the chaos
        bench reads `restored_by_site` for its per-fault-class gate
        and `restore_p99_s` for the restore-latency column (a live
        HistogramCounter quantile — bounded relative error, O(buckets)
        memory — not a sorted sample list)."""
        p99 = self._restore_hist.quantile(0.99)
        return {
            "injected": self._flt_injected,
            "retried": self._flt_retried,
            "restored": self._flt_restored,
            "shed": self._flt_shed,
            "degraded": self._flt_degraded,
            "restore_p99_s": p99,
            "restored_by_site": dict(self._restored_by_site),
        }

    # -- public API ------------------------------------------------------

    def submit(self, prompt, max_new: int, eos_id: Optional[int] = None,
               temperature: float = 0.0, key=None,
               deadline_s: Optional[float] = None) -> int:
        if self._closed:
            raise ServerClosedError()
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("continuous batching needs a non-empty "
                             "prompt (unconditional generation: "
                             "transformer.generate)")
        if len(prompt) + max_new > self.smax:
            raise ValueError(
                f"plen {len(prompt)} + max_new {max_new} exceeds "
                f"smax {self.smax}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new} "
                             "(generate() handles max_new == 0)")
        if temperature > 0.0 and key is None:
            raise ValueError("temperature > 0 needs a PRNG key")
        if temperature <= 0.0 and key is not None:
            raise ValueError(
                "key has no effect at temperature=0 (greedy); pass "
                "temperature > 0 to sample")
        if key is not None:
            key = _normalize_key(key)
        if deadline_s is None:
            deadline_s = self._default_deadline_s or None
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}); omit it "
                "for no deadline")
        rid = self._next_rid
        self._next_rid += 1
        now = time.monotonic()
        self._queue.append(_Request(
            rid, prompt, max_new, eos_id, temperature, key,
            t_submit=now, deadline_s=deadline_s,
            t_deadline=(now + deadline_s) if deadline_s else None))
        self.timeline.event(rid, "submit", t=now, plen=len(prompt))
        return rid

    def admit_prefilled(self, prompt, kv_rows, seed_token: int,
                        max_new: int, eos_id: Optional[int] = None,
                        temperature: float = 0.0, key=None,
                        deadline_s: Optional[float] = None) -> int:
        """Submit a request whose prefill ALREADY HAPPENED on a remote
        prefill worker (disaggregated serving, `models/disagg`):
        `kv_rows` are the worker's raw compute-dtype scratch rows
        ([n_layers, 2, plen, n_kv, head_dim]) and `seed_token` is the
        token its probe seeded. Admission allocates blocks and splices
        the rows through the SAME `_paged_splice_prog` a colocated
        prefill uses, then decodes normally from pos=plen — emitted
        tokens match what a colocated submit() would produce. The rows
        stay a host array until a slot admits, so shedding a queued
        transfer can never leak pool blocks."""
        if not self.paged:
            raise ValueError(
                "admit_prefilled() requires paged=True (the transfer "
                "protocol ships block-granular KV)")
        if self._closed:
            raise ServerClosedError()
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("admit_prefilled needs a non-empty prompt")
        if len(prompt) + max_new > self.smax:
            raise ValueError(
                f"plen {len(prompt)} + max_new {max_new} exceeds "
                f"smax {self.smax}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if temperature > 0.0 and key is None:
            raise ValueError("temperature > 0 needs a PRNG key")
        if key is not None:
            key = _normalize_key(key)
        rows = np.asarray(kv_rows)
        nkv, hd = self.cfg.kv_heads, self.cfg.head_dim
        want = (self.cfg.n_layers, 2, len(prompt), nkv, hd)
        if tuple(rows.shape) != want:
            raise ValueError(
                f"kv_rows shape {tuple(rows.shape)} != expected {want}")
        if deadline_s is None:
            deadline_s = self._default_deadline_s or None
        rid = self._next_rid
        self._next_rid += 1
        now = time.monotonic()
        self._queue.append(_Request(
            rid, prompt, max_new, eos_id, temperature, key,
            t_submit=now, deadline_s=deadline_s,
            t_deadline=(now + deadline_s) if deadline_s else None,
            xfer_rows=rows, xfer_seed=int(seed_token)))
        return rid

    def export_prefix_rows(self, tokens):
        """The other direction of :meth:`admit_prefilled`: the longest
        radix-cached whole-block prefix of `tokens`, exported as raw
        compute-dtype host rows ``[n_layers, 2, matched, n_kv,
        head_dim]`` (the exact layout a prefill worker's scratch
        seeds from and a KV segment frames). Returns ``(matched,
        rows)`` — ``(0, None)`` on a cold tree.

        This is what lets a fleet router turn a placement HIT into a
        prefill SAVING: the rows a retired request published here get
        pulled once, shipped as ordinary retained segments, and the
        prefill worker computes only the suffix. Quantized pools
        dequantize through the same elementwise ops the fused kernels
        apply ((q * scale).astype(dtype)), so bf16/f32 pools roundtrip
        bit-exactly; int8/fp8 exports carry the pool's quantization —
        same contract as colocated prefix reuse on those pools. The
        match's block leases drop before returning (the caller gets
        BYTES, not references — nothing here can leak pool blocks)."""
        if not self.paged:
            raise ValueError("export_prefix_rows() requires paged=True")
        matched, bids = self._radix.match(tokens)
        if not matched:
            return 0, None
        try:
            nkv, hd = self.cfg.kv_heads, self.cfg.head_dim
            idx = jnp.asarray(bids, jnp.int32)
            layers = []
            for li, (kp, vp) in enumerate(self._pools):
                sides = []
                for side, pool in enumerate((kp, vp)):
                    # hpxlint: disable-next=HPX010 — host-side export
                    # of a few matched blocks (once per fleet
                    # placement hit), not the decode attention loop
                    g = pool[idx]                 # [nblk, bs, nkv, hd]
                    if self._scales is not None:
                        sc = self._scales[li][side][idx]
                        g = (g.astype(jnp.float32)
                             * sc[:, None, :, None])
                    g = g.astype(self.cfg.dtype)
                    sides.append(np.asarray(g).reshape(
                        matched, nkv, hd))
                layers.append(np.stack(sides))
            rows = np.stack(layers)
        finally:
            for bid in bids:
                self._alloc.decref(bid)
        return matched, rows

    def shutdown(self) -> None:
        """Close the intake: every later submit() raises
        ServerClosedError. Queued and in-flight requests are NOT
        cancelled — run()/step() still drain them (graceful drain);
        their results land in `run()`'s dict as usual."""
        self._closed = True

    # -- chunked prefill -------------------------------------------------

    def _bucket_width(self, n: int) -> int:
        """Smallest ladder width covering n chunk tokens."""
        for w in self.prefill_buckets:
            if w >= n:
                return w
        return self.prefill_buckets[-1]

    def _start_prefill(self, req: "_Request",
                       slot: int) -> _PendingPrefill:
        """Reserve `slot` and stand up the b=1 scratch cache (paged:
        match the radix prefix, hold blocks for the whole prompt, and
        gather them into the scratch)."""
        self._pf_seq += 1
        if self.paged:
            p = self._start_paged(req, slot)
        else:
            nkv, hd = self.cfg.kv_heads, self.cfg.head_dim

            def z():
                return jnp.zeros((1, self.smax, nkv, hd),
                                 self.cfg.dtype)
            scratch = [(z(), z()) for _ in range(self.cfg.n_layers)]
            p = _PendingPrefill(req=req, slot=slot, caches=scratch,
                                done=0, seq=self._pf_seq)
        self._pending[slot] = p
        self._admit_defers.pop(req.rid, None)   # admitted: ladder done
        return p

    def _start_paged(self, req: "_Request",
                     slot: int) -> _PendingPrefill:
        plen = len(req.prompt)
        matched, mbids, tier_ext = 0, [], []
        if self._prefix_reuse:
            # always leave >= 1 suffix token: admission needs the LAST
            # prompt token's logits to seed generation
            if self._tier is not None:
                matched, mbids, tier_ext = self._radix.match_tiered(
                    req.prompt[:-1], self._tier)
            else:
                matched, mbids = self._radix.match(req.prompt[:-1])
        if tier_ext:
            # crossover-gated restore: a promoted chain extends the
            # hot match (mbids grows, matched covers the restored
            # blocks, the write row below trash-redirects them), a
            # declined one re-prefills with entries left in the tier
            matched += self._promote_tier(req, matched, mbids,
                                          tier_ext)
        pt = PageTable(self.block_size)
        pt.extend_blocks(mbids)
        try:
            while pt.capacity < plen:
                pt.append_block(self._alloc_block())
        except CacheOOM:
            for bid in pt.blocks:
                self._alloc.decref(bid)
            raise
        pt.tokens = plen
        self._prefill_saved += matched
        self._prefill_computed += plen - matched
        row = pt.as_row(self._maxb, self._trash)
        trow = jnp.asarray(row)
        # the splice's WRITE row: radix-matched prefix blocks are
        # shared, so their entries redirect to the trash block — the
        # splice never rewrites them (see _paged_splice_prog)
        wnp = row.copy()
        wnp[:matched // self.block_size] = self._trash
        wrow = jnp.asarray(wnp)
        caches = self._paged_gather_prog()(self._pools, self._scales,
                                           trow, jnp.int32(matched))
        return _PendingPrefill(req=req, slot=slot, caches=caches,
                               done=matched, seq=self._pf_seq, pt=pt,
                               trow=trow, wrow=wrow)

    def _advance_chunk(self, p: _PendingPrefill) -> None:
        """Run ONE bucketed chunk of p's prompt into its scratch.

        Fault site "prefill": the check fires BEFORE the chunk
        dispatch and before any host mutation, so a fault here leaves
        the pending internally consistent — recovery restarts it from
        the prompt (`_restart_pending`; paged restarts re-match the
        radix prefix, so already-resident blocks are not recomputed).
        """
        faultinject.check("prefill")
        req, plen = p.req, len(p.req.prompt)
        n = min(self.prefill_chunk, plen - p.done)
        width = self._bucket_width(n)
        toks = req.prompt[p.done:p.done + n] + [0] * (width - n)
        with tracing.span("serving.prefill_chunk", "serving",
                          rid=req.rid, pos0=p.done, tokens=n,
                          width=width):
            if p.flow is not None:
                tracing.flow_end(p.flow, "serving.prefill_chunks")
                p.flow = None
            p.caches = self._chunk_prog(width)(
                self.params, p.caches, jnp.asarray([toks], jnp.int32),
                jnp.asarray(p.done, jnp.int32))
            p.done += n
            self._chunks += 1
            if p.done < plen:
                p.flow = tracing.flow_begin("serving.prefill_chunks")

    def _finish_prefill(self, p: _PendingPrefill) -> None:
        """Prompt fully chunked: probe the last position's logits,
        splice the scratch into the slot (dense rows / paged blocks),
        seed the first generated token, go live."""
        req, slot = p.req, p.slot
        plen = len(req.prompt)
        tok = jnp.asarray([[req.prompt[-1]]], jnp.int32)
        caches, logits = self._probe_prog()(
            self.params, p.caches, tok,
            jnp.asarray(plen - 1, jnp.int32))
        if p.flow is not None:
            tracing.flow_end(p.flow, "serving.prefill_chunks")
            p.flow = None
        if self.paged:
            self._pools, self._scales = self._paged_splice_prog()(
                self._pools, self._scales, caches, p.wrow)
            self._tables[slot] = p.pt
        else:
            self._caches = self._splice_prog()(
                self._caches, caches, jnp.asarray(slot, jnp.int32))
        del self._pending[slot]
        if req.temperature > 0.0:
            # generate()'s tok0 draw: position plen-1, row 0
            tok0 = int(_sample_row(logits[0], req.temperature,
                                   req.key, plen - 1, 0))
        else:
            tok0 = int(jnp.argmax(logits[0]))
        req.tokens.append(tok0)
        req.sent = 1
        self._slot_req[slot] = req
        self._pos[slot] = plen
        self._cur[slot] = tok0
        if self._cur_dev is not None:
            self._cur_dev = self._cur_dev.at[slot].set(tok0)
        self._temp[slot] = req.temperature
        self._key[slot] = (req.key if req.key is not None
                           else jax.random.PRNGKey(0))
        self._temp_dev = None          # rebuilt with keys next step
        if self._spec:
            self._slot_k[slot] = self._spec_k     # fresh adaptive k
            self._slot_acc[slot] = 1.0
            if self._draft_params is not None:
                self._draft_prefill(slot, req.prompt)
        ttft = time.monotonic() - req.t_submit
        self.ttft[req.rid] = ttft
        self.hist["ttft"].record(ttft, rid=req.rid)
        self.timeline.event(req.rid, "first_token", slot=slot)
        # seed checkpoint: a fault before the first cadence capture
        # restores to the freshly-admitted state instead of losing the
        # slot (the seed token is already part of the checkpoint)
        self._capture(slot)
        self._maybe_retire(slot)

    def _admit(self) -> None:
        """Fill free slots from the queue. A prompt whose remaining
        tokens fit one chunk prefills INLINE (admission latency = one
        chunk + probe, and instant retires drain without decode
        steps); a longer prompt reserves the slot as a PENDING prefill
        and advances chunk-by-chunk in _prefill_tick, interleaved with
        decode.

        A request that retires DURING admission (max_new == 1, or an
        instant eos) frees its slot immediately — the inner loop
        re-scans the same slot within this pass, so a burst of
        one-token requests drains through one slot without burning a
        full decode step per request on an empty batch.

        Admission OOM (the pool is full and `_alloc_block`'s
        evict→retry already failed, or an injected alloc fault
        escalated) walks `_defer_admit`'s ladder: requeue at the front
        for up to hpx.serving.admit_retries passes — retirements
        between steps free blocks — then shed with a typed error."""
        for slot in range(self.slots):
            while (self._slot_req[slot] is None
                   and slot not in self._pending and self._queue):
                req = self._queue.popleft()
                plen = len(req.prompt)
                # queue wait = submit -> first admission attempt (an
                # OOM-deferred request re-dequeues but records once)
                if req.rid not in self._admit_defers:
                    self.hist["queue_wait"].record(
                        time.monotonic() - req.t_submit,
                        rid=req.rid)
                    self.timeline.event(req.rid, "prefill_start",
                                        slot=slot)
                try:
                    with tracing.span("serving.admit", "serving",
                                      rid=req.rid, slot=slot,
                                      plen=plen):
                        if req.xfer_rows is not None:
                            self._admit_transferred(req, slot)
                            continue
                        p = self._start_prefill(req, slot)
                        if p.remaining <= self.prefill_chunk:
                            with tracing.span("serving.prefill",
                                              "serving", rid=req.rid,
                                              plen=plen,
                                              matched=p.done,
                                              suffix=p.remaining):
                                self._advance_chunk(p)
                                self._finish_prefill(p)
                        else:
                            p.flow = tracing.flow_begin(
                                "serving.prefill_chunks")
                except CacheOOM as e:
                    if slot in self._pending:
                        self._drop_pending(slot)
                    if not self._defer_admit(req, e):
                        return   # deferred: give retirements a step
                                 # to free blocks before re-admitting

    def _admit_transferred(self, req: "_Request", slot: int) -> None:
        """Admit a remotely-prefilled request: allocate its blocks,
        splice the shipped rows through the colocated splice program
        (identical quantization/padding semantics), seed the remote
        probe's token, go live at pos=plen. Mirrors `_finish_prefill`
        minus the compute — every downstream invariant (checkpoint
        capture, retire, COW discipline) sees a normal live slot."""
        plen = len(req.prompt)
        pt = PageTable(self.block_size)
        try:
            while pt.capacity < plen:
                pt.append_block(self._alloc_block())
        except CacheOOM:
            for bid in pt.blocks:
                self._alloc.decref(bid)
            raise
        pt.tokens = plen
        self._admit_defers.pop(req.rid, None)
        trow = jnp.asarray(pt.as_row(self._maxb, self._trash))
        nkv, hd = self.cfg.kv_heads, self.cfg.head_dim
        rows = req.xfer_rows
        scratch = []
        for li in range(self.cfg.n_layers):
            k = jnp.zeros((1, self.smax, nkv, hd), self.cfg.dtype)
            k = k.at[0, :plen].set(
                jnp.asarray(rows[li, 0], self.cfg.dtype))
            v = jnp.zeros((1, self.smax, nkv, hd), self.cfg.dtype)
            v = v.at[0, :plen].set(
                jnp.asarray(rows[li, 1], self.cfg.dtype))
            scratch.append((k, v))
        self._pools, self._scales = self._paged_splice_prog()(
            self._pools, self._scales, scratch, trow)
        self._tables[slot] = pt
        req.xfer_rows = None           # host copy no longer needed
        tok0 = int(req.xfer_seed)
        req.tokens.append(tok0)
        req.sent = 1
        self._slot_req[slot] = req
        self._pos[slot] = plen
        self._cur[slot] = tok0
        if self._cur_dev is not None:
            self._cur_dev = self._cur_dev.at[slot].set(tok0)
        self._temp[slot] = req.temperature
        self._key[slot] = (req.key if req.key is not None
                           else jax.random.PRNGKey(0))
        self._temp_dev = None          # rebuilt with keys next step
        if self._spec:
            self._slot_k[slot] = self._spec_k
            self._slot_acc[slot] = 1.0
            if self._draft_params is not None:
                self._draft_prefill(slot, req.prompt)
        ttft = time.monotonic() - req.t_submit
        self.ttft[req.rid] = ttft
        self.hist["ttft"].record(ttft, rid=req.rid)
        self.timeline.event(req.rid, "transfer_admit", slot=slot,
                            plen=plen)
        self._prefill_saved += plen    # prefill compute happened remotely
        self._capture(slot)
        self._maybe_retire(slot)

    def _defer_admit(self, req: "_Request", exc: CacheOOM) -> bool:
        """Admission OOM ladder, entered after evict→retry failed:
        requeue the request at the FRONT (bounded by
        hpx.serving.admit_retries), then shed. Returns True when the
        request was shed (the admit pass may continue with the next
        request), False when deferred (the pass should stop)."""
        n = self._admit_defers.get(req.rid, 0) + 1
        if n > self._admit_retries:
            self._admit_defers.pop(req.rid, None)
            self._shed_req(req, RequestShedError(
                req.rid,
                f"admission OOM persisted through {n} attempts "
                f"({exc})"))
            return True
        self._admit_defers[req.rid] = n
        self._flt_retried += 1
        self._queue.appendleft(req)
        return False

    def _prefill_tick(self) -> None:
        """Advance chunked prefills: ONE chunk per step, given to the
        pending with the FEWEST remaining prompt tokens (ready-chunk
        ordering — a short prompt admitted behind a long one overtakes
        its tail chunks; FIFO breaks ties). The finishing pending
        splices and goes live the same step."""
        if not self._pending:
            return
        p = min(self._pending.values(),
                key=lambda q: (q.remaining, q.seq))
        self._advance_chunk(p)
        if p.remaining == 0:
            with tracing.span("serving.prefill", "serving",
                              rid=p.req.rid, plen=len(p.req.prompt),
                              chunked=True):
                self._finish_prefill(p)

    # -- speculative decode ----------------------------------------------

    def _draft_prefill(self, slot: int, prompt: List[int]) -> None:
        """Build the draft model's K/V rows 0..plen-1 for a freshly
        admitted slot: bucketed chunks over the whole prompt (same
        ladder as the target's prefill, so draft chunk programs are
        O(buckets) too)."""
        done, plen = 0, len(prompt)
        while done < plen:
            n = min(self.prefill_chunk, plen - done)
            width = self._bucket_width(n)
            toks = prompt[done:done + n] + [0] * (width - n)
            self._draft_caches = self._draft_chunk_prog(width)(
                self._draft_params, self._draft_caches,
                jnp.asarray([toks], jnp.int32),
                jnp.asarray(done, jnp.int32),
                jnp.asarray(slot, jnp.int32))
            done += n

    def _prompt_drafts(self, live: List[int],
                       kcap: Dict[int, int]) -> Dict[int, List[int]]:
        """Zero-model draft proposals per live slot: n-gram
        continuation mining over the slot's own history (prompt +
        generated so far), falling back to the radix tree's cached
        continuations when the history has no recurring suffix (paged
        mode with prefix reuse keeps whole retired prompts around —
        `RadixCache.peek` reads them without taking leases)."""
        drafts: Dict[int, List[int]] = {}
        for s in live:
            req = self._slot_req[s]
            k = kcap[s]
            hist = req.prompt + req.tokens
            d = _ngram_propose(hist, k, self._spec_ngram) if k else []
            if not d and k and self.paged and self._prefix_reuse:
                d = self._radix.peek(hist, k)
            drafts[s] = d[:k]
        return drafts

    def _draft_model_tokens(self, kbatch: int):
        """kbatch+1 chained greedy draft-model steps, entirely
        device-side. The extra (kbatch+1)-th feed lands the LAST draft
        token's K/V rows so the next round's draft attention never
        reads a never-written position (speculative_generate's KV-hole
        discipline); its proposal is discarded. Positions clamp at
        smax-1 for lanes whose window runs past the budget — those
        rows are rewritten by the real feed at that position before
        the causal mask can ever expose them. Returns [slots,
        1 + kbatch] int32 (column 0 = the committed cur tokens)."""
        prog = self._draft_step_prog()
        tok = jnp.asarray(self._cur, jnp.int32)
        pos = jnp.asarray(self._pos, jnp.int32)
        cols = [tok]
        for i in range(kbatch + 1):
            self._draft_caches, tok = prog(
                self._draft_params, self._draft_caches, tok,
                jnp.minimum(pos + i, self.smax - 1))
            if i < kbatch:
                cols.append(tok)
        return jnp.stack(cols, axis=1)

    def _spec_adapt_k(self, slot: int, accepted: int,
                      drafted: int) -> None:
        """Per-slot adaptive k: EMA the acceptance rate; back off when
        it sinks below hpx.serving.spec.min_accept (wasted draft+verify
        work), creep back toward the configured k when acceptance runs
        high. The EMA resets on change so one adjustment gets a fresh
        measurement window before the next."""
        if not drafted or not self._spec_adapt:
            return
        ema = 0.5 * self._slot_acc[slot] + 0.5 * (accepted / drafted)
        self._slot_acc[slot] = ema
        if ema < self._spec_min_accept and self._slot_k[slot] > 1:
            self._slot_k[slot] -= 1
            self._slot_acc[slot] = 1.0
        elif ema > 0.8 and self._slot_k[slot] < self._spec_k:
            self._slot_k[slot] += 1
            self._slot_acc[slot] = 1.0

    def _spec_step(self, live: List[int]) -> None:
        """One speculative decode step: draft up to k tokens per live
        slot, verify the whole batch with ONE window forward at
        per-slot positions, commit the longest target-agreeing prefix
        plus the bonus target token. Content is byte-identical to the
        sequential step loop (see `_verify_tail`); only the number of
        tokens per host sync changes. Rejection is cheap by
        construction: dense scratch rows past the committed frontier
        are dead under the causal mask, and paged tables just rewind
        their cursor (`PageTable.rollback`) and drop window-extension
        blocks."""
        self._flush()              # spec commits synchronously
        kcap: Dict[int, int] = {}
        for s in live:
            req = self._slot_req[s]
            remaining = req.max_new - len(req.tokens)
            kcap[s] = max(0, min(self._slot_k[s], remaining - 1))
        kbatch = max(kcap.values())
        width = self._bucket_width(1 + kbatch)
        kvec_host = [0] * self.slots
        f_draft = tracing.flow_begin("serving.spec")
        with tracing.span("serving.spec.draft", "serving",
                          source=self._spec_source, k=kbatch,
                          slots=len(live)):
            tracing.flow_end(f_draft, "serving.spec.draft")
            f_verify = tracing.flow_begin("serving.spec")
            if self._draft_params is not None:
                toks = self._draft_model_tokens(kbatch)
                if width > 1 + kbatch:
                    toks = jnp.pad(toks,
                                   ((0, 0), (0, width - 1 - kbatch)))
                for s in live:
                    kvec_host[s] = kcap[s]
            else:
                mat = np.zeros((self.slots, width), np.int32)
                mat[:, 0] = self._cur
                for s, d in self._prompt_drafts(live, kcap).items():
                    mat[s, 1:1 + len(d)] = d
                    kvec_host[s] = len(d)
                toks = jnp.asarray(mat)
        drafted = sum(kvec_host[s] for s in live)
        with tracing.span("serving.spec.verify", "serving",
                          width=width, drafted=drafted,
                          slots=len(live)):
            tracing.flow_end(f_verify, "serving.spec.verify")
            # fault site "verify": before the window dispatch and
            # before any host commit — a fault here costs only the
            # (restorable) draft-cache advance; repeated ones walk the
            # degradation ladder in _recover and turn speculation off
            faultinject.check("verify")
            pos = jnp.asarray(self._pos, jnp.int32)
            kvec = jnp.asarray(kvec_host, jnp.int32)
            if self._temp_dev is None:
                self._temp_dev = jnp.asarray(self._temp, jnp.float32)
                self._keys_dev = jnp.stack(self._key)
            if self.paged:
                for s in live:
                    self._ensure_window(s, self._pos[s],
                                        self._pos[s] + kvec_host[s])
                self._pools, self._scales, packed, ms = \
                    self._paged_verify_prog(width)(
                        self.params, self._pools, self._scales, toks,
                        pos, self._tables_dev(), kvec, self._temp_dev,
                        self._keys_dev)
            else:
                self._caches, packed, ms = self._verify_prog(width)(
                    self.params, self._caches, toks, pos, kvec,
                    self._temp_dev, self._keys_dev)
            if ms is not None:
                self._moe_buf.append(ms)
            # the speculative step's single designed host sync: one
            # packed [slots, width+1] read carries every slot's target
            # tokens AND acceptance count together
            vals = np.asarray(packed)
        emitted_total = 0
        for s in live:
            req = self._slot_req[s]
            acc = int(vals[s, width])
            m = min(acc + 1, req.max_new - len(req.tokens))
            emis = [int(t) for t in vals[s, :m]]
            if req.eos_id is not None and req.eos_id in emis:
                emis = emis[:emis.index(req.eos_id) + 1]
            req.tokens.extend(emis)
            req.sent = len(req.tokens)
            self._pos[s] += len(emis)
            self._cur[s] = emis[-1]
            emitted_total += len(emis)
            self._spec_drafted += kvec_host[s]
            self._spec_accepted += min(acc, kvec_host[s])
            self._spec_adapt_k(s, min(acc, kvec_host[s]),
                               kvec_host[s])
            if self.paged:
                # rewind the table cursor past rejected draft rows;
                # _release_slot (below, on retire) must see the
                # post-rollback block list or it would double-release
                pt = self._tables[s]
                for bid in pt.rollback(self._pos[s]):
                    self._alloc.decref(bid)
            self._maybe_retire(s)
        self._spec_steps += 1
        self._spec_emitted += emitted_total
        self._rate.mark(float(emitted_total))
        self._cur_dev = None
        self._verify_faults = 0    # a committed verify resets the
                                   # degradation ladder
        self._ckpt_sweep()         # spec commits are flush boundaries

    # -- checkpoint / restore / shed (ROADMAP item 5) --------------------

    def _capture(self, slot: int) -> None:
        """Snapshot one live slot's restore point. Callers guarantee
        flush-consistency (``req.sent == len(req.tokens)``); paged
        pins take one extra ref per FULL block below pos — never the
        partial frontier block, whose pin would force a COW fork on
        the next token write (see SlotCheckpoint)."""
        req = self._slot_req[slot]
        pos = self._pos[slot]
        pins: List[int] = []
        if self.paged:
            pt = self._tables[slot]
            pins = list(pt.blocks[:pos // self.block_size])
            for bid in pins:
                self._alloc.incref(bid)
        old = self._ckpt.get(slot)
        self._ckpt[slot] = SlotCheckpoint(
            rid=req.rid, tokens=list(req.tokens), pos=pos,
            cur=self._cur[slot], slot_k=self._slot_k[slot],
            slot_acc=self._slot_acc[slot], pins=pins)
        if old is not None:
            for bid in old.pins:
                self._alloc.decref(bid)

    def _drop_ckpt(self, slot: int) -> None:
        ck = self._ckpt.pop(slot, None)
        if ck is not None:
            for bid in ck.pins:
                self._alloc.decref(bid)

    def _ckpt_sweep(self) -> None:
        """Advance checkpoints at a flush boundary: every live slot
        whose emissions grew by >= hpx.serving.ckpt_every since its
        last capture (or whose checkpoint is missing/stale) captures
        now. Runs at the end of _flush and after spec commits — the
        two points where host and device state provably agree."""
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None or req.sent != len(req.tokens):
                continue
            ck = self._ckpt.get(s)
            if (ck is None or ck.rid != req.rid
                    or len(req.tokens) - len(ck.tokens)
                    >= self._ckpt_every):
                self._capture(s)

    def _restore_slot(self, slot: int) -> None:
        """Rewind one live slot to its last checkpoint; the decode
        loop then replays ONLY the lost suffix. Paged: rebuild the
        table from the pinned full blocks plus the live table's
        frontier block — its rows [0, pos % bs) are byte-exact
        because KV rows are append-only and COW forks copy every row
        written so far. Dense: re-prefill prompt ++ tokens[:-1] through
        the bucketed chunk programs (byte-identical rows by the
        differential contract). Replayed tokens re-emit identically,
        so a restored run's outputs match the fault-free run."""
        ck = self._ckpt[slot]
        req = self._slot_req[slot]
        with tracing.span("serving.restore", "serving", rid=req.rid,
                          slot=slot, pos=ck.pos,
                          replayed=len(req.tokens) - len(ck.tokens)):
            req.tokens = list(ck.tokens)
            req.sent = len(req.tokens)
            self._pos[slot] = ck.pos
            self._cur[slot] = ck.cur
            self._slot_k[slot] = ck.slot_k
            self._slot_acc[slot] = ck.slot_acc
            if self.paged:
                pt = self._tables[slot]
                # pins cover the full blocks; the frontier block (if
                # ck.pos is not block-aligned) rides over from the
                # current table — it covered ck.pos at capture and
                # tables only grow, so it is still there
                keep = list(ck.pins)
                if pt is not None and ck.pos % self.block_size:
                    keep.append(pt.blocks[ck.pos // self.block_size])
                npt = PageTable(self.block_size)
                for bid in keep:
                    self._alloc.incref(bid)   # the new table's refs
                npt.extend_blocks(keep)
                npt.tokens = ck.pos
                if pt is not None:            # AFTER increfs: shared
                    for bid in pt.blocks:     # bids must not hit 0
                        self._alloc.decref(bid)
                self._tables[slot] = npt
            else:
                self._reprefill_dense(slot, req.prompt
                                      + req.tokens[:-1])
            if self._spec and self._draft_params is not None:
                self._draft_prefill(slot, req.prompt
                                    + req.tokens[:-1])
        self._flt_restored += 1

    def _reprefill_dense(self, slot: int, seq: List[int]) -> None:
        """Dense restore path: rebuild the slot's cache rows
        [0, len(seq)) by re-running bucketed prefill over the known
        token sequence into a fresh b=1 scratch, then splice. No
        probe: the checkpoint already knows the feedback token."""
        nkv, hd = self.cfg.kv_heads, self.cfg.head_dim

        def z():
            return jnp.zeros((1, self.smax, nkv, hd), self.cfg.dtype)
        scratch = [(z(), z()) for _ in range(self.cfg.n_layers)]
        done = 0
        while done < len(seq):
            n = min(self.prefill_chunk, len(seq) - done)
            width = self._bucket_width(n)
            toks = seq[done:done + n] + [0] * (width - n)
            scratch = self._chunk_prog(width)(
                self.params, scratch,
                jnp.asarray([toks], jnp.int32),
                jnp.asarray(done, jnp.int32))
            done += n
        self._caches = self._splice_prog()(
            self._caches, scratch, jnp.asarray(slot, jnp.int32))

    def _drop_pending(self, slot: int) -> _PendingPrefill:
        """Tear down one in-flight prefill (blocks decref'd, trace
        flow closed) and return it for requeue/restart."""
        p = self._pending.pop(slot)
        if p.flow is not None:
            tracing.flow_end(p.flow, "serving.prefill_chunks")
            p.flow = None
        if p.pt is not None:
            for bid in p.pt.blocks:
                self._alloc.decref(bid)
            p.pt = None
        return p

    def _restart_pending(self, slot: int) -> None:
        """Faulted mid-chunked-prefill: drop the pending's scratch and
        blocks and start over from the prompt — `_start_prefill`
        re-matches the radix prefix, so the paged restart recomputes
        only what was never resident. OOM on the restart requeues the
        request instead of failing recovery."""
        p = self._drop_pending(slot)
        try:
            self._start_prefill(p.req, slot)
        except CacheOOM:
            self._queue.appendleft(p.req)

    def _recover(self, attempt: int, exc: BaseException) -> None:
        """sync_replay's on_retry hook: repair serving state after a
        step-level fault so the retry runs against a consistent world.
        Every injection site raises BEFORE its jit dispatch, so each
        BUFFERED step is a completed device op: flush first (those
        tokens are real), then rewind live slots to their checkpoints
        and restart in-flight prefills. Device-side mirrors of the
        per-slot host vectors reset and rebuild on the next dispatch.
        """
        t0 = time.monotonic()
        site = getattr(exc, "site", type(exc).__name__)
        if isinstance(exc, faultinject.InjectedFault) \
                and not isinstance(exc, faultinject.InjectedOOM):
            self._flt_injected += 1   # OOMs were counted at the ladder
        self._flt_retried += 1
        if site == "verify":
            self._verify_faults += 1
            if (self._spec and not self._spec_degraded
                    and self._verify_faults
                    >= self._max_verify_faults):
                # degradation ladder: repeated verify faults turn
                # speculation OFF — sequential steps emit the same
                # tokens (differential contract), only the
                # tokens-per-sync multiplier is lost
                self._spec = False
                self._spec_degraded = True
                self._flt_degraded += 1
                tracing.instant("serving.spec_degraded", "serving",
                                faults=self._verify_faults)
        self._flush()
        restored = 0
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            ck = self._ckpt.get(s)
            if ck is not None and ck.rid == req.rid:
                self._restore_slot(s)
                restored += 1
            else:
                # unreachable while admission seeds a checkpoint, but
                # shedding beats decoding from corrupt state
                self._slot_req[s] = None
                self._drop_ckpt(s)
                if self.paged:
                    self._release_slot(s, req)
                self._shed_req(req, RequestShedError(
                    req.rid, "no checkpoint to restore from"))
        for s in list(self._pending):
            self._restart_pending(s)
        self._cur_dev = None
        self._temp_dev = None
        self._keys_dev = None
        if restored:
            self._restored_by_site[site] = \
                self._restored_by_site.get(site, 0) + 1
            self._restore_hist.record(time.monotonic() - t0)

    def _shed_req(self, req: "_Request", err: HpxError) -> None:
        """Fail one request with a typed error, surfaced via `failed`
        (run() keeps returning successes only)."""
        with tracing.span("serving.shed", "serving", rid=req.rid,
                          reason=type(err).__name__):
            self.failed[req.rid] = err
            self._admit_defers.pop(req.rid, None)
            self._flt_shed += 1
        if not self._flight_mute:
            flight.record_fault("shed", site="serving",
                                rid=req.rid, error=err)

    def _shed_expired(self) -> None:
        """Deadline policy: a queued or still-prefilling request whose
        submit()-time deadline lapsed sheds NOW — overload fails fast
        with a typed error instead of starving the queue. Live decode
        slots are exempt: they already hold device state and their
        remaining tokens are the cheapest in the system."""
        now = time.monotonic()
        if any(r.t_deadline is not None for r in self._queue):
            keep: deque = deque()
            while self._queue:
                req = self._queue.popleft()
                if req.t_deadline is not None \
                        and now >= req.t_deadline:
                    self._shed_req(req, DeadlineExceededError(
                        req.rid, req.deadline_s))
                else:
                    keep.append(req)
            self._queue = keep
        for s, p in list(self._pending.items()):
            req = p.req
            if req.t_deadline is not None and now >= req.t_deadline:
                self._drop_pending(s)
                self._shed_req(req, DeadlineExceededError(
                    req.rid, req.deadline_s))

    def _shed_everything(self, exc: BaseException) -> None:
        """Step-retry budget exhausted: fail FAST and typed. Completed
        requests keep their results (the flush below finalizes any
        whose tokens were still buffered); every in-flight and queued
        request sheds into `failed` — run() terminates instead of
        spinning on a fault that recovery could not clear."""
        self._flush()
        reason = f"step retries exhausted ({exc})"
        # sync_replay already black-boxed this exhaustion (one
        # "retry-exhausted" bundle at the pre-unwind moment); mute the
        # per-request shed captures below so a bulk shed stays ONE
        # bundle, not one per request
        self._flight_mute = True
        try:
            for s in range(self.slots):
                req = self._slot_req[s]
                if req is None:
                    continue
                self._slot_req[s] = None
                self._drop_ckpt(s)
                if self.paged:
                    self._release_slot(s, req)
                self._shed_req(req, RequestShedError(req.rid, reason))
            for s in list(self._pending):
                p = self._drop_pending(s)
                self._shed_req(p.req,
                               RequestShedError(p.req.rid, reason))
            while self._queue:
                q = self._queue.popleft()
                self._shed_req(q, RequestShedError(q.rid, reason))
        finally:
            self._flight_mute = False
        self._cur_dev = None
        self._temp_dev = None
        self._keys_dev = None

    # -- retirement ------------------------------------------------------

    def _maybe_retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        if req is None:
            return
        hit_eos = (req.eos_id is not None
                   and req.tokens[-1] == req.eos_id)
        if len(req.tokens) >= req.max_new or hit_eos:
            self._finalize(slot, req, hit_eos)

    def _finalize(self, slot: int, req: "_Request",
                  hit_eos: bool) -> None:
        """Retire one request: pad the eos tail exactly like
        generate()'s pinning, publish to _done, free the slot if it
        still holds this request (async max_new retires free it at
        dispatch time, before the token values arrive)."""
        if req.rid in self._done:
            return
        if hit_eos:
            # generate() keeps emitting pinned eos to max_new; the
            # slot retires early and pads the same tail
            req.tokens = req.tokens + [req.eos_id] * (
                req.max_new - len(req.tokens))
        with tracing.span("serving.retire", "serving",
                          rid=req.rid, slot=slot,
                          tokens=len(req.tokens), eos=hit_eos):
            self._done[req.rid] = req.tokens
            self.hist["e2e"].record(time.monotonic() - req.t_submit,
                                    rid=req.rid)
            self.timeline.event(req.rid, "retire",
                                tokens=len(req.tokens))
            if self._slot_req[slot] is req:
                self._slot_req[slot] = None
                self._drop_ckpt(slot)
                if self.paged:
                    self._release_slot(slot, req)

    def _flush(self) -> None:
        """Materialize every buffered step's token vector and replay
        the per-slot bookkeeping in dispatch order — the ONLY
        device->host read in the decode loop. Also the knob actuation
        boundary: external config writes land (_reload_knobs) and the
        adaptive tuner ticks HERE, never mid-step."""
        while self._buf:
            nxt, lanes = self._buf.popleft()
            vals = np.asarray(nxt)
            for s, req in lanes:
                t = int(vals[s])
                req.tokens.append(t)
                self._cur[s] = t
                hit_eos = (req.eos_id is not None
                           and t == req.eos_id)
                if hit_eos or len(req.tokens) >= req.max_new:
                    self._finalize(s, req, hit_eos)
        # MoE routing stats buffered by the step/verify programs: one
        # small [2+E] vector per dispatched step, read here so the
        # async window never gains an extra host sync
        while self._moe_buf:
            ms = np.asarray(self._moe_buf.popleft())
            self._moe_routed += float(ms[0])
            self._moe_dropped += float(ms[1])
            self._moe_occ = [float(v) for v in ms[2:]]
        self._ckpt_sweep()
        self._reload_knobs()
        # SLO burn evaluation shares the tuner's boundary: no step in
        # flight, so a flight-bundle capture sees consistent state. A
        # firing alert also holds the tuner — probing against
        # regressed traffic tunes toward the incident.
        alerting = False
        if self._alerts is not None:
            self._alerts.maybe_tick()
            alerting = self._alerts.active() > 0
        if self._tuner is not None:
            self._tuner.maybe_tick(self._tune_signals, hold=alerting)

    def _reload_knobs(self) -> None:
        """Propagate runtime config writes into the live server at
        the flush boundary. Cheap in the steady state: one generation
        read; the per-key compare only runs after a set() somewhere
        bumped the generation, and only keys whose raw value CHANGED
        are applied (constructor overrides survive unrelated writes).
        Values clamp to the baked ladders — the bucket ladder and
        smax are compile-time shape choices a live write cannot
        change."""
        from ..core.config import runtime_config
        rc = runtime_config()
        gen = rc.generation()
        if gen == self._cfg_gen:
            return
        self._cfg_gen = gen
        for key in _RELOADABLE_KNOBS:
            raw = rc.get(key)
            if raw == self._knob_raw[key]:
                continue
            self._knob_raw[key] = raw
            if raw is None or raw == "auto":
                continue
            if key == "hpx.serving.prefill_chunk":
                self.prefill_chunk = min(max(1, int(raw)),
                                         self.prefill_buckets[-1])
            elif key == "hpx.serving.max_async_steps":
                self._max_async = max(1, int(raw))
            elif key == "hpx.serving.ckpt_every":
                self._ckpt_every = max(1, int(raw))
            elif key == "hpx.serving.spec.k" and self._spec:
                self._spec_k = min(max(1, int(raw)),
                                   self.prefill_buckets[-1] - 1)
            elif key == "hpx.serving.moe.capacity_factor" \
                    and self.cfg.n_experts > 0:
                pct = int(raw)
                # 0 = auto = drop-free; the program cache re-keys on
                # the new percent (one compile per distinct value)
                self._moe_capacity_pct = (
                    self.cfg.n_experts * 100 if pct <= 0
                    else max(1, pct))
            elif key == "hpx.cache.radix_budget_blocks" and self.paged:
                self._radix.budget_blocks = max(1, int(raw))
            elif key == "hpx.cache.tier.host_budget_mb" and self.paged \
                    and self._tier is not None:
                # shrink applies on the next demotion's LRU sweep
                self._tier.budget_bytes = max(1, int(raw)) << 20

    def _tune_signals(self):
        """One TuneSignals sample for the tuner: decayed tokens/s,
        the decode-stall p99 over the window SINCE the last sample
        (histogram delta, not lifetime), queue depth, and progprof's
        cumulative compile seconds (None freezes compile-minting
        knobs). Host-only reads — no device sync."""
        from ..svc import progprof
        from ..svc.autotune import TuneSignals
        from ..svc.metrics import HistogramCounter
        h = self.hist["decode_stall"]
        prev, self._tune_stall_prev = self._tune_stall_prev, \
            h.snapshot()
        # quantile() on a DETACHED window copy, never on the live
        # histogram — the live scan is the O(buckets)-under-load read
        # hpxlint HPX023 bans from paths reachable off the flush
        # boundary (first tick: the snapshot just taken IS the window)
        p99 = HistogramCounter.from_snapshot(
            h.delta(prev) if prev is not None
            else self._tune_stall_prev).quantile(0.99)
        comp = None
        prof = progprof.active_profiler()
        if prof is not None:
            comp = sum(float(r.compile_s) for r in prof.records())
        return TuneSignals(
            tok_rate=self._rate.rate(), stall_p99=p99,
            queue_depth=float(len(self._queue)),
            compile_s_total=comp)

    def _statusz(self) -> Dict[str, Any]:
        """This server's /statusz section (svc/opsplane provider):
        live queue/slot state, the SLO alert burn state, tuner flight
        state, and tier occupancy — host-only reads, no device sync
        (an ops scrape must never stall the decode loop)."""
        doc: Dict[str, Any] = {
            "kind": "server",
            "instance": self.counter_instance,
            "paged": self.paged,
            "queue_depth": len(self._queue),
            "pending_prefills": len(self._pending),
            "live_slots": sum(1 for r in self._slot_req
                              if r is not None),
            "slots": self.slots,
            "done": len(self._done),
            "failed": len(self.failed),
            "tok_rate": float(self._rate.rate()),
            "timeline_rids": len(self.timeline),
        }
        if self._tuner is not None:
            doc["tuner"] = self._tuner.flight_state()
        if self._alerts is not None:
            doc["alerts"] = self._alerts.state()
        if self.paged:
            doc["cache"] = {
                "free_blocks": self._alloc.free_count,
                "num_blocks": self._alloc.num_blocks,
            }
            if self._tier is not None:
                doc["tier"] = self._tier.stats()
        return doc

    def step(self) -> bool:
        """Admit + one prefill chunk + one decode step for every live
        slot, wrapped in the recovery ladder. Returns True while any
        work remains (live slots, pending prefills, or queued
        requests).

        An injected/transient fault in the step body replays it up to
        ``hpx.serving.step_retries`` times through `sync_replay`;
        `_recover` runs before each retry (flush → restore slots from
        checkpoints → restart pendings), so the replay decodes the lost
        suffix against intact KV state and emits the SAME tokens the
        fault-free run would (differential contract). If the retry
        budget exhausts, every in-flight request sheds with a typed
        error into `failed` and the loop moves on."""
        self._shed_expired()
        # decode-stall feed: the gap between consecutive step() entries
        # while the PREVIOUS step left live slots — the inter-token
        # latency a streaming client would observe
        now = time.monotonic()
        if self._stall_live and self._last_step_t is not None:
            # the stall is shared by every live slot; attribute the
            # exemplar to the first live rid (deterministic pick — any
            # of them observed this inter-token gap)
            stall_rid = next((r.rid for r in self._slot_req
                              if r is not None), None)
            self.hist["decode_stall"].record(now - self._last_step_t,
                                             rid=stall_rid)
        self._last_step_t = now
        try:
            return sync_replay(
                self._step_retries, self._step_inner,
                retry_on=(faultinject.InjectedFault, CacheOOM),
                on_retry=self._recover,
                backoff_s=self._retry_backoff_s)
        except (faultinject.InjectedFault, CacheOOM) as e:
            self._shed_everything(e)
            return bool(self._queue or self._pending)
        finally:
            self._stall_live = any(r is not None
                                   for r in self._slot_req)

    def _step_inner(self) -> bool:
        self._admit()
        self._prefill_tick()
        live = [s for s in range(self.slots)
                if self._slot_req[s] is not None]
        if not live:
            self._flush()
            return bool(self._queue or self._pending)
        if self._spec:
            with tracing.span("serving.decode", "serving",
                              live=len(live), spec=True,
                              rids=[self._slot_req[s].rid
                                    for s in live]):
                self._spec_step(live)
            return True
        with tracing.span("serving.decode", "serving",
                          live=len(live),
                          rids=[self._slot_req[s].rid for s in live]):
            # fault site "decode": before the step dispatch and before
            # any host bookkeeping commits — at this point every
            # BUFFERED step already completed on device, so recovery's
            # flush-then-restore loses nothing
            faultinject.check("decode")
            # dense: dead slots re-write their own last position
            # (harmless: never read — admission overwrites rows
            # 0..plen first). Paged: dead slots' tables are all-trash,
            # so their writes land in the reserved trash block instead
            # of a recycled live block. Dead slots' feedback tokens
            # are stale argmax/sample outputs — always valid ids.
            tok = (jnp.asarray(self._cur, jnp.int32)
                   if self._cur_dev is None else self._cur_dev)
            pos = jnp.asarray(self._pos, jnp.int32)
            if self._temp_dev is None:
                self._temp_dev = jnp.asarray(self._temp, jnp.float32)
                self._keys_dev = jnp.stack(self._key)
            if self.paged:
                for s in live:
                    self._ensure_block(s, self._pos[s])
                self._pools, self._scales, nxt, ms = \
                    self._paged_step_prog()(
                        self.params, self._pools, self._scales, tok,
                        pos, self._tables_dev(), self._temp_dev,
                        self._keys_dev)
            else:
                self._caches, nxt, ms = self._step_prog()(
                    self.params, self._caches, tok, pos,
                    self._temp_dev, self._keys_dev)
            if ms is not None:
                self._moe_buf.append(ms)
            self._cur_dev = nxt
            self._rate.mark(float(len(live)))
            lanes = []
            need_sync = not self._async
            for s in live:
                req = self._slot_req[s]
                assert req is not None
                lanes.append((s, req))
                self._pos[s] += 1
                req.sent += 1
                if req.eos_id is not None:
                    # the eos check needs this step's VALUE before the
                    # next dispatch — retire timing must not drift
                    need_sync = True
                elif req.sent >= req.max_new:
                    # bookkeeping retire at dispatch: the slot frees
                    # NOW (admissible next step); token values land at
                    # the flush this triggers
                    self._slot_req[s] = None
                    self._drop_ckpt(s)
                    if self.paged:
                        self._release_slot(s, req)
                    need_sync = True
            self._buf.append((nxt, lanes))
            if need_sync or len(self._buf) >= self._max_async:
                self._flush()
        return True

    def run(self) -> Dict[int, List[int]]:
        """Drive step() until every submitted request finishes; returns
        {request_id: tokens} (each exactly generate()'s output).
        Requests shed by deadline/overload/retry-exhaustion are NOT in
        the result — their typed errors are in `self.failed`."""
        while self.step():
            pass
        out, self._done = self._done, {}
        return out
