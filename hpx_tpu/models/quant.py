"""Weight-only int8/int4 quantization for serving.

Reference analog: none (HPX has no ML serving); this is the standard
TPU serving memory/bandwidth lever — decode is weight-bandwidth-bound,
so storing the big matrices as int8 (or packed int4 — two values per
byte) with per-output-channel scales cuts their HBM footprint and read
traffic 2x (4x) vs bf16.

Scheme: symmetric absmax per OUTPUT channel — scales are computed over
the contraction axis of each weight's einsum (axis map below), so
dequantization is exact per channel and the quantization error is a
pure per-channel rounding of the inputs to the matmul. Weights
dequantize AT USE (`dequant`): under jit, XLA fuses the int8->bf16
convert + scale multiply into the matmul operand read, so no
full-precision copy of the weight lives in HBM.

Scope: the DECODE path (models/transformer.generate), dense AND MoE
layers (expert w1/w2 quantize per (expert, output channel); the router
stays dense — it decides WHICH experts run and is tiny). Training stays
full precision; the embedding stays dense (it is a gather table and
the tied loss head's quality anchor). Sharded (dp x tp) decode is
wired FOR DENSE MODELS: scales shard WITH their output channels
(quantized_param_specs — a scale's dim is size 1 exactly on the
contracted axes, so its spec is the weight's spec with those axes
unsharded), and dequantization stays shard-local and exact. MoE
decodes single-device (generate rejects MoE + mesh regardless of
quantization — drop-free routing is the serving contract there).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["QTensor", "QTensor4", "quantize_params", "dequant",
           "quantized_bytes", "quantized_param_specs",
           "shard_quantized", "quantized_bits"]


class QTensor(NamedTuple):
    """int8 values + broadcastable f32 scales (a pytree)."""
    q: jax.Array
    s: jax.Array


# contraction axis per layer weight (the einsums in _block_decode):
#   wqkv [3, d, nh, hd]  contracts d (axis 1)
#   wq   [d, nh, hd]     contracts d (axis 0)
#   wkv  [2, d, nkv, hd] contracts d (axis 1)
#   wo   [nh, hd, d]     contracts (nh, hd) (axes 0, 1)
#   w1   [d, f]          contracts d (axis 0)
#   w2   [f, d]          contracts f (axis 0)
_CONTRACT_AXES = {"wqkv": (1,), "wq": (0,), "wkv": (1,),
                  "wo": (0, 1), "w1": (0,), "w2": (0,)}

# MoE expert weights (the einsums in moe.moe_ffn): per-(expert,
# output-channel) scales — axis 0 is the expert dimension, never a
# contraction.  w1 [E, d, f] contracts d (axis 1); w2 [E, f, d]
# contracts f (axis 1). The router wg and b1 stay dense (routing
# precision decides WHICH experts run; it is tiny and quality-critical).
_MOE_CONTRACT_AXES = {"w1": (1,), "w2": (1,)}

# int4 packing axis per weight: a CONTRACTION axis (scales have size 1
# on every contraction axis, so any of them keeps nibble pairs under
# one scale), preferring one that is UNSHARDED in the decode specs —
# wo packs head_dim (axis 1), not the tp-sharded heads axis. w1/w2's
# only contraction axis (d_ff for w2) IS tp-sharded; shard_quantized
# validates the per-shard packed size stays whole.
_PACK_AXES = {"wqkv": 1, "wq": 0, "wkv": 1, "wo": 1, "w1": 0, "w2": 0}
_MOE_PACK_AXES = {"w1": 1, "w2": 1}


def _quantize(w: jax.Array, axes) -> QTensor:
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127
                 ).astype(jnp.int8)
    return QTensor(q=q, s=s)


# fp8 (e4m3) twin of _quantize, used by the paged KV pools
# (ops/paged_attention.quantize_blocks with kv_dtype=fp8). Same
# symmetric-absmax scheme and same 1 byte/elem storage as int8, but the
# values land on e4m3's FLOAT grid: the scale maps the group absmax
# onto ±448 (e4m3 finfo.max) and the dtype cast does the rounding —
# no clip/round ladder, and small values keep relative precision that
# int8's uniform grid loses. Zero groups get scale 1.0 so fresh pools
# roundtrip exactly (the _quantize convention).
_FP8_DTYPE = jnp.float8_e4m3fn
_FP8_MAX = 448.0          # jnp.finfo(float8_e4m3fn).max


def _quantize_fp8(w: jax.Array, axes) -> QTensor:
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    s = jnp.where(amax > 0, amax / _FP8_MAX, 1.0)
    q = (w.astype(jnp.float32) / s).astype(_FP8_DTYPE)
    return QTensor(q=q, s=s)


def dequant(x: Any, dtype=jnp.bfloat16) -> Any:
    """QTensor/QTensor4 -> dense (fused into the consuming matmul under
    jit); anything else passes through."""
    if isinstance(x, QTensor):
        return (x.q.astype(jnp.float32) * x.s).astype(dtype)
    if isinstance(x, QTensor4):
        return (_unpack4(x.q, x.axis).astype(jnp.float32)
                * x.s).astype(dtype)
    return x


def quantize_params(params: Dict[str, Any],
                    bits: int = 8) -> Dict[str, Any]:
    """Quantize every layer matmul weight; ln scales, biases, and the
    embedding stay in the model dtype. (Layer layout — MHA vs GQA —
    is discovered from the param dict keys.) bits=8 stores int8;
    bits=4 packs two values per byte (4x smaller than bf16; coarser
    15-level grid — measure quality on your model)."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")

    def qz(w, axes, pack_axis):
        if bits == 8:
            return _quantize(w, axes)
        return _quantize4(w, axes, pack_axis)

    out = {"emb": params["emb"], "ln_f": params["ln_f"], "layers": []}
    for lp in params["layers"]:
        qlp = {}
        for name, w in lp.items():
            if name == "moe":
                qlp["moe"] = {
                    mn: (qz(mw, _MOE_CONTRACT_AXES[mn],
                            _MOE_PACK_AXES[mn])
                         if mn in _MOE_CONTRACT_AXES else mw)
                    for mn, mw in w.items()}
                continue
            axes = _CONTRACT_AXES.get(name)
            qlp[name] = qz(w, axes, _PACK_AXES[name]) \
                if axes is not None else w
        out["layers"].append(qlp)
    return out


def quantized_param_specs(cfg, bits: int = 8) -> Dict[str, Any]:
    """PartitionSpecs matching quantize_params' tree: each quantized
    weight becomes QTensor(q=<dense weight spec>, s=<that spec with the
    contracted axes unsharded>). Scales keep dims of size 1 exactly on
    the contract axes (keepdims absmax), so sharding them there would
    be meaningless; on every output-channel axis they follow the weight
    (e.g. wqkv heads over tp -> scales over tp), keeping dequantization
    shard-local and exact under tensor parallelism."""
    from jax.sharding import PartitionSpec as P
    from .transformer import param_specs
    def qspec(wspec, axes, pack_axis):
        dims = list(wspec)
        for ax in axes:
            if ax < len(dims):
                dims[ax] = None
        if bits == 4:
            # packing halves the pack axis; where that axis is sharded
            # (w2's d_ff) shard_quantized validates divisibility
            return QTensor4(wspec, P(*dims), pack_axis)
        return QTensor(q=wspec, s=P(*dims))

    specs = param_specs(cfg)
    for lp in specs["layers"]:
        for name, axes in _CONTRACT_AXES.items():
            if name in lp:
                lp[name] = qspec(lp[name], axes, _PACK_AXES[name])
        if "moe" in lp:
            # param_specs shares ONE moe dict across layers (shallow
            # per-layer copies) — copy before mutating or every layer
            # re-wraps the same specs into nested QTensors
            m = dict(lp["moe"])
            for mn, axes in _MOE_CONTRACT_AXES.items():
                m[mn] = qspec(m[mn], axes, _MOE_PACK_AXES[mn])
            lp["moe"] = m
    return specs


def quantized_bits(tree: Any) -> int:
    """4 when the tree holds QTensor4 leaves, else 8."""
    has4 = any(isinstance(x, QTensor4) for x in jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, (QTensor, QTensor4))))
    return 4 if has4 else 8


def shard_quantized(qparams: Dict[str, Any], cfg, mesh) -> Dict[str, Any]:
    """shard_params for quantized trees (int8/packed-int4 q and f32 s
    placed by quantized_param_specs). int4: where a packed axis is also
    sharded (w2's d_ff over tp), every shard must hold a whole number
    of nibble pairs — validated here with a clear error instead of a
    device_put shape failure."""
    from .transformer import _place
    specs = quantized_param_specs(cfg, quantized_bits(qparams))

    def check(leaf, spec):
        if not isinstance(leaf, QTensor4):
            return
        name = list(spec.q)[leaf.axis] if leaf.axis < len(spec.q) \
            else None
        if name is None:
            return
        shards = mesh.shape[name]
        if leaf.q.shape[leaf.axis] % shards:
            raise ValueError(
                f"int4 packed axis {leaf.axis} (sharded over "
                f"'{name}'={shards}) holds {leaf.q.shape[leaf.axis]} "
                f"nibble pairs — not divisible; the original dim must "
                f"be a multiple of 2*{shards} for int4 + tp")

    jax.tree.map(check, qparams, specs,
                 is_leaf=lambda x: isinstance(x, (QTensor, QTensor4)))
    return _place(qparams, specs, mesh)


def quantized_bytes(tree: Any) -> int:
    """Weight bytes as stored (int8 q + f32 scales for QTensors)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        total += leaf.size * leaf.dtype.itemsize
    return total


# ---------------------------------------------------------------------------
# int4 weight-only (two nibbles per int8 byte)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class QTensor4:
    """Packed int4 values + broadcastable f32 scales. Adjacent pairs
    along `axis` (a CONTRACTION axis — never tp-sharded in the decode
    specs, so packing halves an unsharded dim) share one int8 byte:
    element 2i in the low nibble, 2i+1 in the high — `axis` is chosen
    per weight by _PACK_AXES (an unsharded contraction axis where one
    exists; shard_quantized validates the rest). `axis` is pytree aux
    data (static), q/s are leaves."""

    def __init__(self, q, s, axis: int):
        self.q, self.s, self.axis = q, s, axis

    def tree_flatten(self):
        return (self.q, self.s), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        return cls(children[0], children[1], axis)


def _pack4(q: jax.Array, axis: int) -> jax.Array:
    """int8 values in [-7, 7] -> packed nibbles along `axis`."""
    n = q.shape[axis]
    if n % 2:
        raise ValueError(
            f"int4 pack axis {axis} must be even-sized; got {n}")
    pre = q.shape[:axis] + (n // 2, 2) + q.shape[axis + 1:]
    qr = q.reshape(pre)
    lo = jnp.take(qr, 0, axis=axis + 1)
    hi = jnp.take(qr, 1, axis=axis + 1)
    return ((lo & 0x0F) | (hi << 4)).astype(jnp.int8)


def _unpack4(p: jax.Array, axis: int) -> jax.Array:
    """packed nibbles -> int8 values (sign via arithmetic shifts)."""
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)   # sign-extend low
    hi = jnp.right_shift(p, 4)                      # arithmetic: signed
    st = jnp.stack([lo, hi], axis=axis + 1)
    shape = p.shape[:axis] + (p.shape[axis] * 2,) + p.shape[axis + 1:]
    return st.reshape(shape)


def _quantize4(w: jax.Array, axes, pack_axis: int) -> QTensor4:
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    s = jnp.where(amax > 0, amax / 7.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -7, 7
                 ).astype(jnp.int8)
    return QTensor4(_pack4(q, pack_axis), s, pack_axis)
