"""Weight-only int8 quantization for serving.

Reference analog: none (HPX has no ML serving); this is the standard
TPU serving memory/bandwidth lever — decode is weight-bandwidth-bound,
so storing the big matrices as int8 with per-output-channel scales
cuts their HBM footprint and read traffic 2x vs bf16 (4x vs f32).

Scheme: symmetric absmax per OUTPUT channel — scales are computed over
the contraction axis of each weight's einsum (axis map below), so
dequantization is exact per channel and the quantization error is a
pure per-channel rounding of the inputs to the matmul. Weights
dequantize AT USE (`dequant`): under jit, XLA fuses the int8->bf16
convert + scale multiply into the matmul operand read, so no
full-precision copy of the weight lives in HBM.

Scope: the DECODE path (models/transformer.generate), dense AND MoE
layers (expert w1/w2 quantize per (expert, output channel); the router
stays dense — it decides WHICH experts run and is tiny). Training stays
full precision; the embedding stays dense (it is a gather table and
the tied loss head's quality anchor). Sharded (dp x tp) decode is
wired FOR DENSE MODELS: scales shard WITH their output channels
(quantized_param_specs — a scale's dim is size 1 exactly on the
contracted axes, so its spec is the weight's spec with those axes
unsharded), and dequantization stays shard-local and exact. MoE
decodes single-device (generate rejects MoE + mesh regardless of
quantization — drop-free routing is the serving contract there).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["QTensor", "quantize_params", "dequant", "quantized_bytes",
           "quantized_param_specs", "shard_quantized"]


class QTensor(NamedTuple):
    """int8 values + broadcastable f32 scales (a pytree)."""
    q: jax.Array
    s: jax.Array


# contraction axis per layer weight (the einsums in _block_decode):
#   wqkv [3, d, nh, hd]  contracts d (axis 1)
#   wq   [d, nh, hd]     contracts d (axis 0)
#   wkv  [2, d, nkv, hd] contracts d (axis 1)
#   wo   [nh, hd, d]     contracts (nh, hd) (axes 0, 1)
#   w1   [d, f]          contracts d (axis 0)
#   w2   [f, d]          contracts f (axis 0)
_CONTRACT_AXES = {"wqkv": (1,), "wq": (0,), "wkv": (1,),
                  "wo": (0, 1), "w1": (0,), "w2": (0,)}

# MoE expert weights (the einsums in moe.moe_ffn): per-(expert,
# output-channel) scales — axis 0 is the expert dimension, never a
# contraction.  w1 [E, d, f] contracts d (axis 1); w2 [E, f, d]
# contracts f (axis 1). The router wg and b1 stay dense (routing
# precision decides WHICH experts run; it is tiny and quality-critical).
_MOE_CONTRACT_AXES = {"w1": (1,), "w2": (1,)}


def _quantize(w: jax.Array, axes) -> QTensor:
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127
                 ).astype(jnp.int8)
    return QTensor(q=q, s=s)


def dequant(x: Any, dtype=jnp.bfloat16) -> Any:
    """QTensor -> dense (fused into the consuming matmul under jit);
    anything else passes through."""
    if isinstance(x, QTensor):
        return (x.q.astype(jnp.float32) * x.s).astype(dtype)
    return x


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize every layer matmul weight; ln scales, biases, and the
    embedding stay in the model dtype. (Layer layout — MHA vs GQA —
    is discovered from the param dict keys.)"""
    out = {"emb": params["emb"], "ln_f": params["ln_f"], "layers": []}
    for lp in params["layers"]:
        qlp = {}
        for name, w in lp.items():
            if name == "moe":
                qlp["moe"] = {
                    mn: (_quantize(mw, _MOE_CONTRACT_AXES[mn])
                         if mn in _MOE_CONTRACT_AXES else mw)
                    for mn, mw in w.items()}
                continue
            axes = _CONTRACT_AXES.get(name)
            qlp[name] = _quantize(w, axes) if axes is not None else w
        out["layers"].append(qlp)
    return out


def quantized_param_specs(cfg) -> Dict[str, Any]:
    """PartitionSpecs matching quantize_params' tree: each quantized
    weight becomes QTensor(q=<dense weight spec>, s=<that spec with the
    contracted axes unsharded>). Scales keep dims of size 1 exactly on
    the contract axes (keepdims absmax), so sharding them there would
    be meaningless; on every output-channel axis they follow the weight
    (e.g. wqkv heads over tp -> scales over tp), keeping dequantization
    shard-local and exact under tensor parallelism."""
    from jax.sharding import PartitionSpec as P
    from .transformer import param_specs
    def qspec(wspec, axes):
        dims = list(wspec)
        for ax in axes:
            if ax < len(dims):
                dims[ax] = None
        return QTensor(q=wspec, s=P(*dims))

    specs = param_specs(cfg)
    for lp in specs["layers"]:
        for name, axes in _CONTRACT_AXES.items():
            if name in lp:
                lp[name] = qspec(lp[name], axes)
        if "moe" in lp:
            # param_specs shares ONE moe dict across layers (shallow
            # per-layer copies) — copy before mutating or every layer
            # re-wraps the same specs into nested QTensors
            m = dict(lp["moe"])
            for mn, axes in _MOE_CONTRACT_AXES.items():
                m[mn] = qspec(m[mn], axes)
            lp["moe"] = m
    return specs


def shard_quantized(qparams: Dict[str, Any], cfg, mesh) -> Dict[str, Any]:
    """shard_params for quantized trees (int8 q and f32 s placed by
    quantized_param_specs)."""
    from .transformer import _place
    return _place(qparams, quantized_param_specs(cfg), mesh)


def quantized_bytes(tree: Any) -> int:
    """Weight bytes as stored (int8 q + f32 scales for QTensors)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        total += leaf.size * leaf.dtype.itemsize
    return total
