"""Disaggregated prefill/decode serving over the dist/AGAS layer.

Reference analog: none in HPX proper — this is the ROADMAP's MPMD
prefill/decode split (PAPERS.md "Scaling Deep Learning Training with
MPMD Pipeline Parallelism"), built with RESILIENCY as the design
center: every cross-worker edge is retried/timed-out/idempotent, and
every worker death has a typed, deterministic failover.

Topology::

    DisaggRouter (front end, admits by SLO class)
        ├── PrefillWorker × N   (dense chunk programs, b=1 scratch)
        │       │  KVSegments (cache/transfer: framed, checksummed,
        │       ▼   idempotent)
        └── DecodeWorker × M    (paged ContinuousServer pools)

The prefill worker computes prompt KV rows with the SAME bucketed
chunk + probe programs a colocated server uses and ships raw
compute-dtype rows block-by-block as they finish (the final, partial
block ships post-probe — the probe rewrites row plen-1). The decode
worker splices received rows through its own `_paged_splice_prog`
(`ContinuousServer.admit_prefilled`), so decode proceeds from KV
bytes a colocated prefill would have produced — which is what makes
failover REPLAY (not approximate): tokens are sha-identical to the
fault-free run.

Failure model (each detected via typed ``LocalityLost``/
``NetworkError`` from a worker call — real heartbeat promotion,
socket death, or the injected ``disagg.prefill``/``disagg.decode``
fault sites):

* **decode worker dies** — affected requests re-ship their
  router-retained segments to a surviving decode worker and re-admit;
  decode replays deterministically from the transferred KV. The last
  progress snapshot (``pump``'s live tokens) must be a prefix of the
  replayed output — checked, not assumed.
* **prefill worker dies** — a surviving prefill worker restarts from
  the already-shipped prefix (its scratch seeds from the router's
  retained rows); only the un-transferred suffix recomputes.
* **all workers of a role die** — the router degrades to a local
  colocated ``ContinuousServer`` and finishes every unfinished
  request there rather than erroring.

Config (``hpx.serving.disagg.*``)::

    hpx.serving.disagg.max_queue      router admission bound (64)
    hpx.serving.disagg.prefill_jobs   in-flight prefills per worker (slots)
    hpx.serving.disagg.pump_steps     decode steps per router tick (4)
    hpx.serving.disagg.xfer_retries   segment resend bound (4)
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.transfer import (KVSegment, TransferCorruptError,
                              TransferReceiver, make_segment)
from ..core.errors import (Error, FutureError, HpxError, LocalityLost,
                           NetworkError)
from ..svc import faultinject, flight, tracing
from ..svc import metrics as _metrics
from ..svc.resiliency import sync_replay
from .serving import (ContinuousServer, RequestShedError,
                      ServerClosedError, _normalize_key)
from .transformer import TransformerConfig, _sample_row

__all__ = [
    "DecodeWorker",
    "DisaggRouter",
    "InProcHandle",
    "PrefillWorker",
    "RemoteHandle",
    "register_worker",
]


# ---------------------------------------------------------------------------
# workers
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _PrefillJob:
    prompt: List[int]
    caches: Any                    # b=1 [1, smax] scratch, per layer
    done: int                      # prompt rows computed so far
    emitted: int                   # rows already framed into segments
    temperature: float
    key: Any


class _WorkerRing:
    """Per-worker span ring for cross-worker trace stitching.

    Workers live in their own event-loop turn (or their own process,
    behind a :class:`RemoteHandle`), so they cannot write into the
    router's tracer.  Instead each worker lazily mints a PRIVATE
    :class:`tracing.Tracer` the first time a span opens while the
    process tracer is active, and exposes the ring as a Chrome-trace
    doc via :meth:`trace_doc` — `trace_export.merge_traces` stitches
    those docs with the router's own export into one timeline.  When
    tracing is off the instrumentation is a shared no-op span."""

    _ring: Optional[tracing.Tracer] = None

    def _wspan(self, name: str, **args):
        if tracing.active_tracer() is None:
            return tracing.null_span()
        if self._ring is None:
            from ..core.config import runtime_config
            cap = runtime_config().get_int("hpx.trace.buffer_events",
                                           65536)
            self._ring = tracing.Tracer(capacity=cap,
                                        sample_counters=False)
        return self._ring.span(name, "serving", **args)

    def trace_doc(self) -> Optional[Dict[str, Any]]:
        """This worker's ring as a Chrome-trace doc (None if the ring
        never opened a span); carries the wall-clock anchor that
        merge_traces uses for clock alignment."""
        if self._ring is None:
            return None
        from ..svc.trace_export import to_chrome_trace
        return to_chrome_trace(self._ring.snapshot(),
                               self._ring.thread_names(),
                               self._ring.t0, self._ring.dropped,
                               t0_wall=self._ring.t0_wall)


class PrefillWorker(_WorkerRing):
    """Computes prompt KV on a b=1 dense scratch with the colocated
    server's OWN bucketed chunk/probe programs (an embedded dense
    ``ContinuousServer`` is the program cache), emitting block-aligned
    :class:`KVSegment`s as rows finish.

    Emission discipline: full blocks of ``[0, ((plen-1)//bs)*bs)`` may
    ship as soon as their rows are chunked (KV rows are append-only —
    functions of (token, position) alone); the FINAL segment ships
    only after the probe, which rewrites row plen-1 and yields the
    seeding logits. ``start`` with ``prefix_rows`` resumes a transfer
    whose original worker died: the scratch seeds from the
    already-shipped prefix and only the suffix recomputes."""

    def __init__(self, params, cfg: TransformerConfig, smax: int = 512,
                 block_size: Optional[int] = None,
                 **server_kwargs) -> None:
        if block_size is None:
            # the decode pool's geometry authority (env > perfdb
            # learned tier > seed table > default) — emitted segments
            # must match the pool the router splices them into
            from ..ops.attention_pallas import resolve_paged_block
            block_size = resolve_paged_block(cfg.head_dim)
        self.block_size = int(block_size)
        self._eng = ContinuousServer(params, cfg, slots=1, smax=smax,
                                     paged=False, async_dispatch=False,
                                     **server_kwargs)
        self._jobs: Dict[str, _PrefillJob] = {}

    def start(self, rid: str, prompt: List[int],
              temperature: float = 0.0, key=None,
              prefix_rows=None) -> int:
        """Open (or reopen) a prefill; returns the resume cursor."""
        with self._wspan("prefill.start", rid=rid, plen=len(prompt)):
            eng = self._eng
            prompt = [int(t) for t in prompt]
            nkv, hd = eng.cfg.kv_heads, eng.cfg.head_dim
            scratch = [(jnp.zeros((1, eng.smax, nkv, hd),
                                  eng.cfg.dtype),
                        jnp.zeros((1, eng.smax, nkv, hd),
                                  eng.cfg.dtype))
                       for _ in range(eng.cfg.n_layers)]
            done = 0
            if prefix_rows is not None:
                rows = np.asarray(prefix_rows)
                done = int(rows.shape[2])
                scratch = [
                    (k.at[0, :done].set(jnp.asarray(rows[li, 0],
                                                    eng.cfg.dtype)),
                     v.at[0, :done].set(jnp.asarray(rows[li, 1],
                                                    eng.cfg.dtype)))
                    for li, (k, v) in enumerate(scratch)]
            self._jobs[rid] = _PrefillJob(
                prompt=prompt, caches=scratch, done=done,
                emitted=done, temperature=float(temperature),
                key=_normalize_key(key) if key is not None else None)
            return done

    def step(self, rid: str) -> Dict[str, Any]:
        """Advance one bucketed chunk; returns ``{"segments", "seed",
        "done"}`` — newly completed block segments, plus the seeded
        first token when the prompt finished (probe ran)."""
        job = self._jobs[rid]
        eng, plen, bs = self._eng, len(job.prompt), self.block_size
        with self._wspan("prefill.step", rid=rid):
            if job.done < plen:
                n = min(eng.prefill_chunk, plen - job.done)
                width = eng._bucket_width(n)
                toks = (job.prompt[job.done:job.done + n]
                        + [0] * (width - n))
                job.caches = eng._chunk_prog(width)(
                    eng.params, job.caches,
                    jnp.asarray([toks], jnp.int32),
                    jnp.asarray(job.done, jnp.int32))
                job.done += n
            segs: List[KVSegment] = []
            # pre-probe emission cap: row plen-1 is rewritten by the
            # probe
            cap = ((plen - 1) // bs) * bs
            while job.emitted + bs <= min(job.done, cap):
                segs.append(self._emit(rid, job, job.emitted,
                                       job.emitted + bs, plen))
            seed: Optional[int] = None
            finished = job.done >= plen
            if finished:
                tok = jnp.asarray([[job.prompt[-1]]], jnp.int32)
                job.caches, logits = eng._probe_prog()(
                    eng.params, job.caches, tok,
                    jnp.asarray(plen - 1, jnp.int32))
                if job.temperature > 0.0:
                    # generate()'s tok0 draw: position plen-1, row 0
                    seed = int(_sample_row(logits[0], job.temperature,
                                           job.key, plen - 1, 0))
                else:
                    seed = int(jnp.argmax(logits[0]))
                segs.append(self._emit(rid, job, job.emitted, plen,
                                       plen))
                del self._jobs[rid]
            return {"segments": segs, "seed": seed, "done": finished}

    def _emit(self, rid: str, job: _PrefillJob, a: int, b: int,
              plen: int) -> KVSegment:
        rows = np.stack([np.stack([np.asarray(k[0, a:b]),
                                   np.asarray(v[0, a:b])])
                         for (k, v) in job.caches])
        job.emitted = b
        # seq = start // block_size: stable across failover restarts,
        # so a re-emitted block dedups against its original delivery
        return make_segment(rid, a // self.block_size, a, plen, rows)

    def abort(self, rid: str) -> None:
        self._jobs.pop(rid, None)

    def jobs(self) -> int:
        return len(self._jobs)

    def ping(self) -> str:
        return "pong"

    def close(self) -> None:
        self._jobs.clear()
        self._eng.shutdown()


class DecodeWorker(_WorkerRing):
    """Paged ``ContinuousServer`` plus a :class:`TransferReceiver`:
    ingests segments (idempotently), admits completed transfers via
    ``admit_prefilled``, and pumps decode steps, translating between
    router-global request ids and local server rids."""

    def __init__(self, params, cfg: TransformerConfig, slots: int = 4,
                 smax: int = 512, mesh=None, **server_kwargs) -> None:
        # `mesh=` mirrors ContinuousServer(mesh=...) exactly: None is
        # the single-device paged server, a (dp, tp) Mesh runs decode
        # + verify under shard_map (PR 10's sharded paged serving;
        # axis names in those bodies are hpxlint-HPX021-checked) —
        # one constructor for both, so a fleet mixes them freely
        self.srv = ContinuousServer(params, cfg, slots=slots,
                                    smax=smax, paged=True, mesh=mesh,
                                    **server_kwargs)
        self.recv = TransferReceiver()
        self._local_of: Dict[str, int] = {}
        self._global_of: Dict[int, str] = {}

    def block_size(self) -> int:
        return self.srv.block_size

    def prefix_digest(self, max_entries: int = 64) -> Dict[str, Any]:
        """Placement fingerprint for fleet routing: the radix tree's
        chain-hash digest (cache/radix.prefix_digest) plus the
        pressure signals the router folds into its score. Cheap by
        construction — O(entries) ints, no token lists, no leases."""
        srv = self.srv
        return {
            "hashes": srv._radix.prefix_digest(max_entries),
            # cold mirror: chains held only in the host tier — the
            # router scores these with the discounted w_tier weight
            "tier_hashes": (srv._tier.digest(max_entries)
                            if getattr(srv, "_tier", None) is not None
                            else []),
            "evictions": int(srv._radix.total_evictions),
            "blocks_held": int(srv._radix.blocks_held),
            "blocks_free": int(srv._alloc.free_count),
        }

    def fetch_prefix(self, prompt: List[int]) -> Dict[str, Any]:
        """Export this worker's longest cached whole-block prefix of
        `prompt` as raw host rows (ContinuousServer.
        export_prefix_rows) — the fleet router frames them as retained
        KV segments and seeds the prefill worker's scratch, so only
        the suffix recomputes."""
        matched, rows = self.srv.export_prefix_rows(prompt)
        return {"matched": matched, "rows": rows}

    def ingest(self, seg: KVSegment) -> Dict[str, Any]:
        with self._wspan("decode.ingest", rid=seg.rid, seq=seg.seq):
            return self.recv.ingest(seg)

    def admit(self, rid: str, prompt: List[int], seed: int,
              max_new: int, eos_id: Optional[int] = None,
              temperature: float = 0.0, key=None) -> int:
        with self._wspan("decode.admit", rid=rid, plen=len(prompt)):
            rows = self.recv.assemble(rid)
            local = self.srv.admit_prefilled(
                prompt, rows, seed, max_new, eos_id=eos_id,
                temperature=temperature, key=key)
            self._local_of[rid] = local
            self._global_of[local] = rid
            return local

    def pump(self, steps: int = 1) -> Dict[str, Any]:
        """Run up to `steps` server steps; returns ``{"done",
        "failed", "live", "busy"}`` keyed by router-global rid.
        ``live`` is each in-flight request's tokens so far — the
        router's progress checkpoint for post-failover replay
        verification."""
        busy = False
        with self._wspan("decode.pump", steps=steps):
            for _ in range(max(1, steps)):
                busy = self.srv.step()
                if not busy:
                    break
        done: Dict[str, List[int]] = {}
        for lrid in list(self.srv._done):
            grid = self._global_of.pop(lrid, None)
            if grid is None:
                continue
            done[grid] = self.srv._done.pop(lrid)
            self._local_of.pop(grid, None)
        failed: Dict[str, HpxError] = {}
        for lrid in list(self.srv.failed):
            grid = self._global_of.pop(lrid, None)
            if grid is None:
                continue
            failed[grid] = self.srv.failed.pop(lrid)
            self._local_of.pop(grid, None)
        live: Dict[str, List[int]] = {}
        for s in range(self.srv.slots):
            req = self.srv._slot_req[s]
            if req is not None and req.rid in self._global_of:
                live[self._global_of[req.rid]] = list(req.tokens)
        return {"done": done, "failed": failed, "live": live,
                "busy": busy}

    def stats(self) -> Dict[str, Any]:
        st = dict(self.srv._alloc.stats())
        st.update(self.recv.stats())
        return st

    def leaked_blocks(self) -> int:
        """Blocks still in use once the radix cache (a CACHE, not a
        reservation) is fully evicted — must be 0 after close().
        Excludes the server's one permanently resident trash block."""
        while sum(self.srv._radix.evict(1)):
            pass
        return int(self.srv._alloc.stats()["in_use"]) - 1

    def ping(self) -> str:
        return "pong"

    def close(self, drain: bool = False) -> None:
        """Stop intake; optionally drain in-flight decode, then abort
        pending transfers and release every slot/checkpoint block —
        zero allocator leak whether or not work was in flight."""
        if drain:
            self.srv.run()
        self.srv.shutdown()
        for rid in self.recv.pending():
            self.recv.abort(rid)
        self.srv._shed_everything(
            ServerClosedError("decode worker closed"))


# ---------------------------------------------------------------------------
# worker handles: one call surface for in-process and remote workers
# ---------------------------------------------------------------------------

class WorkerHandle:
    """Router-side proxy for one worker. ``call`` raises typed
    ``LocalityLost``/``NetworkError`` when the worker is gone —
    injected (``disagg.<role>`` fault sites) or real — and the router
    marks the handle dead permanently (a lost worker never
    resurrects mid-run; deterministic failover depends on that)."""

    role: str
    locality: int
    alive: bool
    # autoscale drain flag (svc/fleet): a draining worker finishes or
    # hands off what it owns but takes no NEW placements; the base
    # router only ever reads it (class default keeps plain disagg
    # topologies oblivious)
    draining: bool = False

    def call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def _check(self) -> None:
        if not self.alive:
            raise LocalityLost(
                self.locality,
                f"{self.role} worker at locality {self.locality} "
                f"is dead", "WorkerHandle.call")
        faultinject.check(f"disagg.{self.role}",
                          locality=self.locality)


class InProcHandle(WorkerHandle):
    """Same-process worker (tests, single-host serving, the chaos
    bench): direct method calls through the fault-site check."""

    def __init__(self, role: str, worker: Any,
                 locality: int = 0) -> None:
        self.role = role
        self.locality = locality
        self.alive = True
        self.worker = worker

    def call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        self._check()
        return getattr(self.worker, method)(*args, **kwargs)

    def kill(self) -> None:
        self.alive = False


_workers: Dict[str, Any] = {}


def register_worker(worker_id: str, worker: Any) -> str:
    """Publish a worker under `worker_id` for `hpx.disagg.invoke`
    parcels arriving at THIS locality."""
    _workers[worker_id] = worker
    return worker_id


def _disagg_invoke(worker_id: str, method: str, args: tuple,
                   kwargs: dict) -> Any:
    w = _workers.get(worker_id)
    if w is None:
        raise HpxError(Error.bad_parameter,
                       f"no disagg worker {worker_id!r} registered "
                       f"at this locality")
    return getattr(w, method)(*args, **kwargs)


def _disagg_die() -> None:
    """Chaos harness: hard-kill this locality's process (no cleanup,
    no goodbye — the failure detector must notice the honest way)."""
    os._exit(0)


class RemoteHandle(WorkerHandle):
    """Worker on another locality, reached via `resilient_action`:
    per-attempt timeout, bounded backoff retry, idempotency keys (a
    retried parcel is deduplicated, never re-executed)."""

    def __init__(self, role: str, locality: int, worker_id: str,
                 timeout_s: float = 30.0, retries: int = 3) -> None:
        self.role = role
        self.locality = locality
        self.worker_id = worker_id
        self.timeout_s = timeout_s
        self.retries = retries
        self.alive = True

    def call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        self._check()
        from ..dist.actions import resilient_action
        return resilient_action(
            "hpx.disagg.invoke", self.locality, self.worker_id,
            method, args, kwargs, timeout_s=self.timeout_s,
            retries=self.retries).get()

    def kill(self) -> None:
        from ..dist.actions import post_action
        try:
            post_action("hpx.disagg.die", self.locality)
        except (NetworkError, HpxError):
            pass               # already dead — which is the goal
        self.alive = False


class _WorkerDown(Exception):
    """Internal: a worker call failed with a connectivity-class error;
    carries WHICH handle so the router step loop can fail it over."""

    def __init__(self, handle: WorkerHandle, cause: BaseException):
        super().__init__(f"{handle.role}@{handle.locality}: {cause}")
        self.handle = handle
        self.cause = cause


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _RouterReq:
    rid: int
    prompt: List[int]
    max_new: int
    eos_id: Optional[int]
    temperature: float
    key: Any
    slo: str
    state: str = "queued"          # queued|prefill|decode|done|failed
    prefill_h: Optional[WorkerHandle] = None
    decode_h: Optional[WorkerHandle] = None
    segments: List[KVSegment] = dataclasses.field(default_factory=list)
    seed: Optional[int] = None
    progress: List[int] = dataclasses.field(default_factory=list)

    @property
    def grid(self) -> str:
        return f"r{self.rid}"


class DisaggRouter:
    """Front end of the disaggregated topology: admits by SLO class
    (bounded queue; ``batch`` sheds before ``interactive``),
    dispatches prefill, streams KV segments to the least-loaded
    decode worker, pumps decode, and runs the failover policy of the
    module docstring. `run()` returns ``{rid: tokens}`` exactly like
    ``ContinuousServer.run`` — shed/failed requests land typed in
    ``failed``."""

    def __init__(self, params, cfg: TransformerConfig,
                 prefill_workers: int = 1, decode_workers: int = 1, *,
                 slots: int = 4, smax: int = 512,
                 decode_mesh=None,
                 prefill_handles: Optional[List[WorkerHandle]] = None,
                 decode_handles: Optional[List[WorkerHandle]] = None,
                 server_kwargs: Optional[dict] = None) -> None:
        from ..core.config import runtime_config
        rc = runtime_config()
        self.params, self.cfg = params, cfg
        self.slots, self.smax = slots, smax
        self.decode_mesh = decode_mesh
        self._srv_kwargs = dict(server_kwargs or {})
        self.max_queue = rc.get_int("hpx.serving.disagg.max_queue", 64)
        self._pump_steps = max(1, rc.get_int(
            "hpx.serving.disagg.pump_steps", 4))
        self._prefill_jobs = max(1, rc.get_int(
            "hpx.serving.disagg.prefill_jobs", slots))
        self._xfer_retries = max(1, rc.get_int(
            "hpx.serving.disagg.xfer_retries", 4))
        if decode_handles is None:
            decode_handles = [
                InProcHandle("decode", self._make_decode_worker(),
                             locality=0)
                for _ in range(decode_workers)]
        self._decode = list(decode_handles)
        self.failovers = {"prefill": 0, "decode": 0}
        # prefill segments (and placement prefix hashes) must be
        # block-aligned to the DECODE pool's grid; a decode worker
        # already dead at construction just fails over to the next
        # for the query
        bs = None
        for h in self._decode:
            try:
                bs = int(h.call("block_size"))
                break
            except (NetworkError, FutureError):
                h.alive = False
                self.failovers["decode"] += 1
        if bs is None:
            bs = 16   # every decode worker dead: the first step
                      # degrades to colocated; bs is moot
        self._block_size = bs
        if prefill_handles is None:
            prefill_handles = [
                InProcHandle("prefill", PrefillWorker(
                    params, cfg, smax=smax, block_size=bs),
                    locality=0)
                for _ in range(prefill_workers)]
        self._prefill = list(prefill_handles)
        # closed-loop tuning under a router: each embedded server
        # already built its own tuner (hpx.tune.enable); the in-proc
        # ones join ONE router-level arbiter so the prefill and decode
        # sides never probe a shared-budget knob (radix HBM budget,
        # queue bound) concurrently — two workers growing one budget
        # together would double-spend it and corrupt each other's
        # probe measurements
        from ..svc.autotune import TuneArbiter, attach_arbiter
        self._tune_arbiter = TuneArbiter()
        for i, h in enumerate(self._decode):
            attach_arbiter(h, self._tune_arbiter, f"decode#{i}")
        for i, h in enumerate(self._prefill):
            attach_arbiter(h, self._tune_arbiter, f"prefill#{i}")
        self._reqs: Dict[int, _RouterReq] = {}
        self._qi: deque = deque()      # interactive rids
        self._qb: deque = deque()      # batch rids
        self._next_rid = 0
        self._closed = False
        self.results: Dict[int, List[int]] = {}
        self.failed: Dict[int, HpxError] = {}
        self.shed = 0
        self._degraded = False
        self._local: Optional[ContinuousServer] = None
        self._local_map: Dict[int, int] = {}   # local rid -> router rid
        self.ttft: Dict[int, float] = {}
        self._t_submit: Dict[int, float] = {}
        # -- SLO metrics plane: per-decode-worker latency histograms
        # (keyed by creation-order index, stable across failover) plus
        # a rid-keyed lifecycle timeline.  merged_hist() folds the
        # per-worker histograms into the fleet-wide view.
        self._worker_idx: Dict[int, int] = {}
        self._next_widx = 0
        self.whist: Dict[int, Dict[str, _metrics.HistogramCounter]] = {}
        self.timeline = _metrics.RequestTimeline()
        self._last_pump_t: Dict[int, float] = {}
        # live ops plane: one weakref /statusz provider per router,
        # so the router port exposes the merged fleet view (workers
        # roll up through merged_hist / stats). None unless
        # hpx.obs.port enables the plane.
        from ..svc import opsplane as _opsplane
        if _opsplane.ensure_opsplane() is not None:
            _opsplane.register_provider(
                f"router/{id(self):x}", self, type(self)._statusz)

    # -- admission --------------------------------------------------------

    def submit(self, prompt, max_new: int,
               eos_id: Optional[int] = None,
               temperature: float = 0.0, key=None,
               slo: str = "interactive") -> int:
        if self._closed:
            raise ServerClosedError("router is closed")
        if slo not in ("interactive", "batch"):
            raise ValueError(
                f"slo must be 'interactive' or 'batch', got {slo!r}")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("disagg serving needs a non-empty prompt")
        if len(prompt) + max_new > self.smax:
            raise ValueError(
                f"plen {len(prompt)} + max_new {max_new} exceeds "
                f"smax {self.smax}")
        rid = self._next_rid
        self._next_rid += 1
        req = _RouterReq(rid, prompt, max_new, eos_id,
                         float(temperature),
                         _normalize_key(key) if key is not None
                         else None, slo)
        self._reqs[rid] = req
        self._t_submit[rid] = time.monotonic()
        self.timeline.event(req.grid, "submit", slo=slo,
                            plen=len(prompt))
        # bounded admission: shed BATCH work first (newest first), an
        # overflowing batch submit sheds itself, and only a queue full
        # of interactive work sheds an interactive submit
        while len(self._qi) + len(self._qb) >= self.max_queue:
            if self._qb:
                self._shed(self._reqs[self._qb.pop()],
                           "admission queue full (batch shed first)")
                continue
            self._shed(req, "admission queue full of interactive work")
            return rid
        if self._degraded:
            self._submit_local(req)
            return rid
        (self._qi if slo == "interactive" else self._qb).append(rid)
        return rid

    def _shed(self, req: _RouterReq, reason: str) -> None:
        req.state = "failed"
        req.segments = []
        err = RequestShedError(req.rid, reason)
        self.failed[req.rid] = err
        self.shed += 1
        flight.record_fault("shed", site="disagg", rid=req.grid,
                            error=err, timeline=self.timeline)

    # -- the step loop ----------------------------------------------------

    def _call(self, h: WorkerHandle, method: str, *args: Any,
              **kwargs: Any) -> Any:
        try:
            return h.call(method, *args, **kwargs)
        except (NetworkError, FutureError) as e:
            raise _WorkerDown(h, e) from e

    def step(self) -> bool:
        """One router tick: admit → advance prefills (shipping
        segments) → pump decode. A worker death detected anywhere in
        the tick runs failover immediately; the tick's remaining work
        happens on later ticks (state only ever advances, so a
        half-finished tick is safe to abandon)."""
        if self._degraded:
            return self._local_step()
        try:
            self._dispatch_prefills()
            self._advance_prefills()
            self._pump_decodes()
        except _WorkerDown as wd:
            self._on_worker_failure(wd.handle, wd.cause)
        return self._unfinished() > 0

    def run(self) -> Dict[int, List[int]]:
        while self.step():
            pass
        out, self.results = self.results, {}
        return out

    def _unfinished(self) -> int:
        return sum(1 for r in self._reqs.values()
                   if r.state not in ("done", "failed"))

    def _alive(self, handles: List[WorkerHandle]) -> List[WorkerHandle]:
        return [h for h in handles if h.alive]

    def _make_decode_worker(self) -> DecodeWorker:
        """Mint one decode worker on this router's construction recipe
        — the default-handle path AND the fleet autoscaler both come
        through here, so scaled-up workers are indistinguishable from
        constructed ones (same mesh, same kwargs, same program-cache
        keys)."""
        return DecodeWorker(self.params, self.cfg, slots=self.slots,
                            smax=self.smax, mesh=self.decode_mesh,
                            **self._srv_kwargs)

    def _decode_load(self) -> Dict[int, int]:
        """In-flight requests per decode handle (by id) — the shared
        currency of every placement policy here and in svc/fleet."""
        load = {id(h): 0 for h in self._decode}
        for r in self._reqs.values():
            if (r.state in ("prefill", "decode")
                    and r.decode_h is not None
                    and id(r.decode_h) in load):
                load[id(r.decode_h)] += 1
        return load

    # -- SLO metrics plane ------------------------------------------------

    def _widx(self, h: Optional[WorkerHandle]) -> int:
        """Creation-order index of a decode handle — stable across
        failover and autoscale (-1 covers the degraded / no-worker
        path)."""
        if h is None:
            return -1
        key = id(h)
        if key not in self._worker_idx:
            self._worker_idx[key] = self._next_widx
            self._next_widx += 1
        return self._worker_idx[key]

    def _whist(self, h: Optional[WorkerHandle]
               ) -> Dict[str, _metrics.HistogramCounter]:
        """The latency histograms attributed to one decode worker,
        minted lazily on first touch."""
        idx = self._widx(h)
        hist = self.whist.get(idx)
        if hist is None:
            hist = self.whist[idx] = _metrics.latency_histograms()
            from ..svc import exemplars as _exemplars
            _exemplars.attach_from_config(hist)
        return hist

    def merged_hist(self) -> Dict[str, _metrics.HistogramCounter]:
        """The fleet-wide latency view: a fold of every per-worker
        histogram under :meth:`HistogramCounter.merge`, computed at
        query time — so the fleet-wide quantiles EQUAL the merge of
        the per-worker histograms by construction."""
        out = _metrics.latency_histograms()
        for per in self.whist.values():
            for k in _metrics.LATENCY_KEYS:
                out[k] = out[k].merge(per[k])
        return out

    def worker_trace_docs(self) -> List[Tuple[str, Dict[str, Any]]]:
        """Chrome-trace docs from every live worker's private span
        ring, labelled ``role#index`` — feed these together with the
        router's own export to ``trace_export.merge_traces`` for the
        single stitched fleet timeline."""
        docs: List[Tuple[str, Dict[str, Any]]] = []
        for role, pool in (("prefill", self._prefill),
                           ("decode", self._decode)):
            for i, h in enumerate(pool):
                if not h.alive:
                    continue
                try:
                    doc = self._call(h, "trace_doc")
                except _WorkerDown:
                    continue
                if doc is not None:
                    docs.append((f"{role}#{i}", doc))
        return docs

    def _placeable_decode(self) -> List[WorkerHandle]:
        """Candidates for NEW placements: alive and not draining. A
        fleet drain empties the pool's tail, never the whole pool, but
        failover must still find a home if it somehow does — fall back
        to anything alive rather than strand a request."""
        alive = self._alive(self._decode)
        return [h for h in alive if not h.draining] or alive

    def _least_loaded_decode(self) -> WorkerHandle:
        cands = self._placeable_decode()
        load = self._decode_load()
        return min(cands, key=lambda h: (load[id(h)],
                                         self._decode.index(h)))

    def _place_decode(self, req: _RouterReq) -> WorkerHandle:
        """Pick the decode worker for one request. The base policy is
        least-loaded; svc/fleet overrides this with prefix-cache-aware
        scoring. Called with the request still QUEUED (a worker death
        inside placement re-places on a later tick)."""
        return self._least_loaded_decode()

    def _start_prefill_job(self, req: _RouterReq,
                           h: WorkerHandle) -> None:
        """Open the prefill job on `h` — the one cross-worker send of
        dispatch. svc/fleet overrides this to seed the job with the
        placed decode worker's cached prefix rows first."""
        self._call(h, "start", req.grid, req.prompt,
                   req.temperature, req.key)

    def _dispatch_prefills(self) -> None:
        alive = self._alive(self._prefill)
        if not alive or not self._alive(self._decode):
            if self._unfinished():
                self._degrade()
            return
        jobs = {id(h): 0 for h in alive}
        for r in self._reqs.values():
            if r.state == "prefill" and id(r.prefill_h) in jobs:
                jobs[id(r.prefill_h)] += 1
        while self._qi or self._qb:
            h = min(alive, key=lambda w: (jobs[id(w)],
                                          self._prefill.index(w)))
            if jobs[id(h)] >= self._prefill_jobs:
                return
            q = self._qi if self._qi else self._qb
            # peek: a death during start must leave the rid queued
            # for re-dispatch
            req = self._reqs[q[0]]
            with tracing.span("serving.place", "serving",
                              rid=req.grid):
                req.prefill_h = h
                req.decode_h = self._place_decode(req)
                self._start_prefill_job(req, h)
            q.popleft()
            req.state = "prefill"
            jobs[id(h)] += 1
            now = time.monotonic()
            self._whist(req.decode_h)["queue_wait"].record(
                now - self._t_submit[req.rid], rid=req.grid)
            self.timeline.event(req.grid, "place", t=now,
                                worker=self._widx(req.decode_h))
            self.timeline.event(req.grid, "prefill_start", t=now)

    def _advance_prefills(self) -> None:
        for rid in sorted(r.rid for r in self._reqs.values()
                          if r.state == "prefill"):
            req = self._reqs[rid]
            out = self._call(req.prefill_h, "step", req.grid)
            req.segments.extend(out["segments"])  # retain BEFORE
            if out["done"]:                       # shipping: failover
                # prefill is over (the worker dropped the job) — from
                # here on a decode death re-ships + re-admits; it must
                # NOT re-step a prefill that no longer exists
                req.seed = int(out["seed"])
                req.state = "decode"
            for seg in out["segments"]:
                self._ship(req, seg)              # re-ships these
            if out["done"]:
                self._admit_decode(req)

    def _ship(self, req: _RouterReq, seg: KVSegment) -> None:
        """Deliver one segment, re-sending on checksum corruption
        (bounded, backed off); connectivity errors propagate to the
        failover path."""
        if seg.seq == 0:
            self.timeline.event(req.grid, "kv_transfer",
                                worker=self._widx(req.decode_h))
        with tracing.span("serving.transfer", "serving", rid=req.grid,
                          seq=seg.seq), \
                self._whist(req.decode_h)["transfer"].record():
            sync_replay(self._xfer_retries,
                        lambda: self._call(req.decode_h, "ingest",
                                           seg),
                        retry_on=(TransferCorruptError,),
                        backoff_s=0.005)

    def _admit_decode(self, req: _RouterReq) -> None:
        # transition BEFORE the call: prefill is finished (its job is
        # gone), so a decode death mid-admit must re-admit on the
        # survivor, not re-step a prefill that no longer exists
        req.state = "decode"
        self._call(req.decode_h, "admit", req.grid, req.prompt,
                   req.seed, req.max_new, req.eos_id,
                   req.temperature, req.key)

    def _pump_decodes(self) -> None:
        for h in self._alive(self._decode):
            widx = self._widx(h)
            assigned = any(r.decode_h is h and r.state == "decode"
                           for r in self._reqs.values())
            if not assigned:
                self._last_pump_t.pop(widx, None)
                continue
            # decode stall: the gap since this worker's previous pump
            # returned while it still held live work
            now = time.monotonic()
            last = self._last_pump_t.get(widx)
            if last is not None:
                # attribute the stall exemplar to the first live grid on
                # this worker (deterministic: lowest rid)
                stall_rid = next(
                    (self._reqs[r].grid for r in sorted(self._reqs)
                     if self._reqs[r].decode_h is h
                     and self._reqs[r].state == "decode"), None)
                self._whist(h)["decode_stall"].record(now - last,
                                                      rid=stall_rid)
            out = self._call(h, "pump", self._pump_steps)
            self._last_pump_t[widx] = time.monotonic()
            for grid, toks in sorted(out["done"].items()):
                self._finish(self._req_of(grid), toks)
            for grid, err in sorted(out["failed"].items()):
                req = self._req_of(grid)
                req.state = "failed"
                req.segments = []
                self.failed[req.rid] = err
            for grid, toks in out["live"].items():
                req = self._req_of(grid)
                req.progress = toks
                if req.rid not in self.ttft and toks:
                    ttft = time.monotonic() - self._t_submit[req.rid]
                    self.ttft[req.rid] = ttft
                    self._whist(req.decode_h)["ttft"].record(
                        ttft, rid=req.grid)
                    self.timeline.event(req.grid, "first_token",
                                        worker=widx)

    def _req_of(self, grid: str) -> _RouterReq:
        return self._reqs[int(grid[1:])]

    def _finish(self, req: _RouterReq, toks: List[int]) -> None:
        if req.progress and toks[:len(req.progress)] != req.progress:
            raise HpxError(
                Error.assertion_failure,
                f"request {req.rid}: post-failover replay diverged "
                f"from its last progress checkpoint",
                "DisaggRouter._finish")
        req.state = "done"
        req.segments = []
        self.results[req.rid] = toks
        now = time.monotonic()
        if req.rid not in self.ttft:
            ttft = now - self._t_submit[req.rid]
            self.ttft[req.rid] = ttft
            self._whist(req.decode_h)["ttft"].record(ttft,
                                                     rid=req.grid)
            self.timeline.event(req.grid, "first_token",
                                worker=self._widx(req.decode_h))
        self._whist(req.decode_h)["e2e"].record(
            now - self._t_submit[req.rid], rid=req.grid)
        self.timeline.event(req.grid, "retire", tokens=len(toks))

    # -- failover ---------------------------------------------------------

    def _on_worker_failure(self, h: WorkerHandle,
                           cause: BaseException) -> None:
        """A worker call surfaced a connectivity-class error: the
        worker is DEAD for the rest of this run. Re-route everything
        it owned; degrade to colocated when a role has no survivors."""
        if h.alive:
            h.alive = False
        self.failovers[h.role] += 1
        flight.record_fault("failover", site=h.role, error=cause)
        if not self._alive(self._prefill) \
                or not self._alive(self._decode):
            self._degrade()
            return
        if h.role == "prefill":
            # decoding requests no longer need their prefill worker
            affected = [r for r in self._reqs.values()
                        if r.state == "prefill" and r.prefill_h is h]
        else:
            # a decode death strands both decoding requests AND
            # mid-prefill requests whose segments streamed to it
            affected = [r for r in self._reqs.values()
                        if r.state in ("prefill", "decode")
                        and r.decode_h is h]
        affected.sort(key=lambda r: r.rid)
        try:
            for req in affected:
                if h.role == "decode":
                    self._failover_decode(req)
                else:
                    self._failover_prefill(req)
        except _WorkerDown as wd:
            # cascading loss: the failover target died too
            self._on_worker_failure(wd.handle, wd.cause)

    def _failover_decode(self, req: _RouterReq) -> None:
        """Re-ship the retained segments to a survivor; if decode was
        already running, re-admit — the survivor replays the whole
        decode from the transferred KV, deterministically emitting the
        tokens the dead worker lost."""
        req.decode_h = self._place_decode(req)
        for seg in req.segments:
            self._ship(req, seg)
        if req.state == "decode":
            self._admit_decode(req)

    def _failover_prefill(self, req: _RouterReq) -> None:
        """Restart ONLY the un-transferred suffix on a survivor: the
        replacement's scratch seeds from the rows already shipped (the
        router retains every segment until the request finishes)."""
        alive = self._alive(self._prefill)
        req.prefill_h = alive[0]
        prefix = None
        if req.segments:
            segs = sorted(req.segments, key=lambda s: s.start)
            prefix = np.concatenate([s.payload for s in segs], axis=2)
        self._call(req.prefill_h, "start", req.grid, req.prompt,
                   req.temperature, req.key, prefix)

    def _degrade(self) -> None:
        """A worker role has no survivors: colocated fallback. Every
        unfinished request restarts from its prompt on a LOCAL paged
        server — slower, but the tokens are identical (the same
        differential contract every path here rides)."""
        if self._degraded:
            return
        self._degraded = True
        flight.record_fault("degrade", site="disagg")
        self._local = ContinuousServer(
            self.params, self.cfg, slots=self.slots, smax=self.smax,
            paged=True, **self._srv_kwargs)
        self._qi.clear()
        self._qb.clear()
        for rid in sorted(self._reqs):
            req = self._reqs[rid]
            if req.state in ("done", "failed"):
                continue
            self._submit_local(req)

    def _submit_local(self, req: _RouterReq) -> None:
        lrid = self._local.submit(
            req.prompt, req.max_new, eos_id=req.eos_id,
            temperature=req.temperature, key=req.key)
        self._local_map[lrid] = req.rid
        req.state = "decode"
        req.segments = []

    def _local_step(self) -> bool:
        busy = self._local.step()
        for lrid in list(self._local._done):
            rid = self._local_map.pop(lrid, None)
            if rid is None:
                continue
            self._finish(self._reqs[rid], self._local._done.pop(lrid))
        for lrid in list(self._local.failed):
            rid = self._local_map.pop(lrid, None)
            if rid is None:
                continue
            req = self._reqs[rid]
            req.state = "failed"
            self.failed[rid] = self._local.failed.pop(lrid)
        return busy or self._unfinished() > 0

    # -- lifecycle --------------------------------------------------------

    def _statusz(self) -> Dict[str, Any]:
        """This router's /statusz section (svc/opsplane provider):
        queue split, request-state census, per-worker liveness and
        per-worker SLO sample counts, plus the stats() roll-up —
        ONE port answers for the whole fleet.  Host-only reads; no
        worker calls (a scrape must not touch a dead worker)."""
        states: Dict[str, int] = {}
        for r in self._reqs.values():
            states[r.state] = states.get(r.state, 0) + 1
        return {
            "kind": "router",
            "queue": {"interactive": len(self._qi),
                      "batch": len(self._qb)},
            "requests": states,
            "workers": {
                "prefill": [
                    {"locality": getattr(h, "locality", 0),
                     "alive": h.alive} for h in self._prefill],
                "decode": [
                    {"widx": self._widx(h),
                     "locality": getattr(h, "locality", 0),
                     "alive": h.alive,
                     "samples": {k: v.count for k, v in sorted(
                         self.whist.get(self._widx(h), {}).items())}}
                    for h in self._decode],
            },
            "timeline_rids": len(self.timeline),
            "stats": self.stats(),
        }

    def stats(self) -> Dict[str, Any]:
        merged = self.merged_hist()
        return {
            "failovers": dict(self.failovers),
            "shed": self.shed,
            "degraded": self._degraded,
            "unfinished": self._unfinished(),
            "prefill_alive": len(self._alive(self._prefill)),
            "decode_alive": len(self._alive(self._decode)),
            # fleet-wide quantiles from LIVE histograms — the merge of
            # the per-worker views, not a post-hoc sort of raw samples
            "latency": {
                k: {_metrics.quantile_label(q): merged[k].quantile(q)
                    for q in _metrics.configured_quantiles()}
                for k in _metrics.LATENCY_KEYS},
        }

    def leaked_blocks(self) -> int:
        """Sum of post-eviction in-use blocks across every surviving
        decode worker (and the colocated fallback) — the chaos gate's
        zero-leak check."""
        total = 0
        for h in self._alive(self._decode):
            try:
                total += int(self._call(h, "leaked_blocks"))
            except _WorkerDown:
                continue
        if self._local is not None:
            while sum(self._local._radix.evict(1)):
                pass
            # minus the fallback server's resident trash block
            total += int(self._local._alloc.stats()["in_use"]) - 1
        return total

    def close(self, drain: bool = True) -> None:
        """Stop intake (later submit() raises ServerClosedError).
        ``drain=True`` finishes in-flight work first; ``drain=False``
        sheds it typed. Either way every worker's pending transfers
        abort and pinned blocks release — no allocator leak."""
        if self._closed:
            return
        self._closed = True
        if drain:
            while self.step():
                pass
        else:
            for rid in sorted(self._reqs):
                req = self._reqs[rid]
                if req.state not in ("done", "failed"):
                    self._shed(req, "router closed before completion")
        for h in self._alive(self._prefill):
            try:
                self._call(h, "close")
            except _WorkerDown:
                continue
        for h in self._alive(self._decode):
            try:
                self._call(h, "close", drain)
            except _WorkerDown:
                continue
        if self._local is not None:
            self._local.shutdown()
            self._local._shed_everything(
                ServerClosedError("router closed"))


from ..dist.actions import plain_action as _pa  # noqa: E402
_pa(_disagg_invoke, name="hpx.disagg.invoke")
_pa(_disagg_die, name="hpx.disagg.die")
