from . import stencil1d  # noqa: F401
