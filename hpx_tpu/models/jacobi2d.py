"""2-D Jacobi workloads — the reference's examples/jacobi ladder (config #5).

Reference analog: examples/jacobi/ and examples/jacobi_smp/ (row-block
decomposition with dataflow dependencies between iterations), plus the
block_executor NUMA configuration the reference's Jacobi benchmarks use.
Physics: 5-point Laplace smoothing with Dirichlet boundaries (top edge
held at 1, other edges at 0 — the heated-plate problem), identical across
all variants so they can be differentially tested:

  jacobi_serial    whole-grid sweeps in one jitted fori_loop — the honest
                   single-program TPU baseline.
  jacobi_dataflow  row-block decomposition; each iteration builds
                   dataflow(jacobi_part, up, mid, down) nodes exchanging
                   1-row halos — the examples/jacobi dependency DAG with
                   device dispatches as task bodies.
  jacobi_sharded   production path: grid sharded over a 2-D device mesh,
                   per-sweep halos via lax.ppermute on both axes, many
                   sweeps fused per dispatch (parallel/halo2d.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..exec.tpu import TpuExecutor
from ..futures.async_ import Launch
from ..futures.dataflow import dataflow
from ..futures.future import Future, make_ready_future


@dataclasses.dataclass
class JacobiParams:
    nx: int = 256           # grid rows
    ny: int = 256           # grid cols
    nb: int = 8             # row blocks (dataflow variant)
    iterations: int = 100

    @property
    def grid(self) -> Tuple[int, int]:
        return self.nx, self.ny


def init_grid(p: JacobiParams) -> jax.Array:
    """Zero interior; top boundary row = 1 (heated plate)."""
    u = jnp.zeros((p.nx, p.ny), dtype=jnp.float32)
    return u.at[0, 1:-1].set(1.0)


def _sweep(u: jax.Array) -> jax.Array:
    """One whole-grid Jacobi sweep; boundary rows/cols carried through."""
    interior = 0.25 * (u[:-2, 1:-1] + u[2:, 1:-1] +
                       u[1:-1, :-2] + u[1:-1, 2:])
    return u.at[1:-1, 1:-1].set(interior)


# -- serial -------------------------------------------------------------------

def jacobi_serial(p: JacobiParams, u0: Optional[jax.Array] = None,
                  ) -> jax.Array:
    u = init_grid(p) if u0 is None else u0

    @jax.jit
    def run(u):
        return jax.lax.fori_loop(0, p.iterations, lambda _i, s: _sweep(s), u)

    return run(u)


def residual(u_prev: jax.Array, u_next: jax.Array) -> jax.Array:
    return jnp.sum((u_next - u_prev) ** 2)


# -- dataflow over row blocks (examples/jacobi dependency DAG) ---------------

def jacobi_part(top: jax.Array, mid: jax.Array, bot: jax.Array
                ) -> jax.Array:
    """Update one row block given 1-row neighbor halos.

    top/bot are (1, ny) halo rows (the neighbor block's adjacent row; the
    block's own outer row where the block touches the global boundary —
    the caller passes the block's own edge row there, which keeps
    Dirichlet cells fixed because the 5-point update is masked below).
    """
    ext = jnp.concatenate([top, mid, bot], axis=0)
    interior = 0.25 * (ext[:-2, 1:-1] + ext[2:, 1:-1] +
                       ext[1:-1, :-2] + ext[1:-1, 2:])
    return mid.at[:, 1:-1].set(interior)


# jitted once at module scope: repeated jacobi_dataflow calls with the
# same block shapes hit jit's trace cache instead of recompiling
_part = jax.jit(jacobi_part)


@jax.jit
def _part_top(mid: jax.Array, bot: jax.Array) -> jax.Array:
    # first block: row 0 is Dirichlet — update rows 1.., restore row 0
    new = jacobi_part(mid[:1], mid, bot)
    return new.at[0].set(mid[0])


@jax.jit
def _part_bot(top: jax.Array, mid: jax.Array) -> jax.Array:
    new = jacobi_part(top, mid, mid[-1:])
    return new.at[-1].set(mid[-1])


@jax.jit
def _part_single(mid: jax.Array) -> jax.Array:
    # nb == 1: the block owns BOTH Dirichlet rows — restore both
    new = jacobi_part(mid[:1], mid, mid[-1:])
    new = new.at[0].set(mid[0])
    return new.at[-1].set(mid[-1])


def jacobi_dataflow(p: JacobiParams,
                    executor: Optional[TpuExecutor] = None,
                    u0: Optional[jax.Array] = None) -> List[Future]:
    """Row-block DAG: U[t+1][b] = dataflow(jacobi_part, U[t][b-1] tail,
    U[t][b], U[t][b+1] head). Global top/bottom blocks mask their boundary
    row by passing their own edge row as the halo AND restoring it after
    the update (the update would otherwise smooth the Dirichlet row)."""
    assert p.nx % p.nb == 0, (p.nx, p.nb)
    bh = p.nx // p.nb
    ex = executor or TpuExecutor()
    full = init_grid(p) if u0 is None else u0
    blocks = [full[b * bh:(b + 1) * bh] for b in range(p.nb)]
    u: List[Future] = [make_ready_future(x) for x in blocks]

    def node(b: int, uf: Future, df: Future, bf2: Future) -> Future:
        if p.nb == 1:
            return ex.async_execute_raw(_part_single, df.get())
        if b == 0:
            return ex.async_execute_raw(_part_top, df.get(), bf2.get()[:1])
        if b == p.nb - 1:
            return ex.async_execute_raw(_part_bot, uf.get()[-1:], df.get())
        return ex.async_execute_raw(
            _part, uf.get()[-1:], df.get(), bf2.get()[:1])

    for _t in range(p.iterations):
        u = [
            dataflow(node, b, u[max(b - 1, 0)], u[b],
                     u[min(b + 1, p.nb - 1)], policy=Launch.sync)
            for b in range(p.nb)
        ]
    return u


def gather_blocks(u: List[Future]) -> jax.Array:
    return jnp.concatenate([f.get() for f in u], axis=0)


# -- sharded over a 2-D mesh (production path) -------------------------------

def jacobi_sharded(p: JacobiParams, mesh, ax: str = "x", ay: str = "y",
                   u0: Optional[jax.Array] = None,
                   steps_per_dispatch: Optional[int] = None):
    """Run p.iterations sweeps sharded over `mesh`; returns (u, residual).

    The grid lives sharded P(ax, ay) for the whole run; each dispatch
    fuses `steps_per_dispatch` sweeps (default: all of them).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.halo2d import sharded_jacobi_multistep

    u = init_grid(p) if u0 is None else u0
    u = jax.device_put(u, NamedSharding(mesh, P(ax, ay)))
    if p.iterations <= 0:
        return u, jnp.zeros((), u.dtype)
    spd = steps_per_dispatch or p.iterations
    step = sharded_jacobi_multistep(mesh, p.grid, spd, ax, ay)
    done, res = 0, None
    while done + spd <= p.iterations:
        u, res = step(u)
        done += spd
    if done < p.iterations:  # remainder program for the tail
        tail = sharded_jacobi_multistep(mesh, p.grid,
                                        p.iterations - done, ax, ay)
        u, res = tail(u)
    return u, res
