"""Mixture-of-experts FFN with expert parallelism over a mesh axis.

The reference (HPX) has no ML layers; this is part of the mandated
model family (SURVEY.md §2.9), built GShard/Switch-style for TPU:
STATIC shapes throughout (top-k gating lowered to one-hot einsums with
a fixed per-expert capacity), experts sharded over a mesh axis, and
token exchange as ONE tiled `lax.all_to_all` each way — the same
collective substrate ulysses_attention rides (SURVEY.md §5.7).

Layout (inside shard_map; the "ep" axis may be a dedicated mesh axis or
an existing data axis — tokens must be sharded over it, expert weights
sharded over it, everything else replicated over it):

    tokens   x       [T, D]           (T = local tokens)
    gate     wg      [D, E]           replicated
    experts  w1      [E/P, D, F]      sharded over ep
             b1      [E/P, F]
             w2      [E/P, F, D]

    dispatch [T, E, C] one-hot   -> einsum -> [E, C, D]
    reshape  [P, E/P, C, D] -> all_to_all -> [E/P, P*C, D]
    expert FFN (batched einsum over the local experts)
    all_to_all back -> combine [T, E, C] -> [T, D]

Everything is differentiable (einsums + all_to_all transpose); dropped
tokens (over capacity) contribute zero output and zero gradient, the
standard Switch behavior. The auxiliary load-balance loss
(Switch §2.2: E * sum_e f_e * p_e) is returned for the trainer to add.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["MoeConfig", "init_moe_params", "moe_ffn", "moe_ffn_decode",
           "moe_param_specs"]


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    n_experts: int = 4
    top_k: int = 2                 # 1 = Switch, 2 = GShard default
    capacity_factor: float = 1.5   # C = ceil(T*k*cf / E)
    d_model: int = 64
    d_ff: int = 128                # per-expert hidden
    dtype: Any = jnp.float32


def init_moe_params(cfg: MoeConfig, key: jax.Array) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)
    e, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    s = 1.0 / math.sqrt(d)
    return {
        "wg": (jax.random.normal(k1, (d, e)) * s).astype(cfg.dtype),
        "w1": (jax.random.normal(k2, (e, d, f)) * s).astype(cfg.dtype),
        "b1": jnp.zeros((e, f), cfg.dtype),
        "w2": (jax.random.normal(k3, (e, f, d)) / math.sqrt(f)
               ).astype(cfg.dtype),
    }


def moe_param_specs(axis: str = "ep",
                    tp_axis: Any = None) -> Dict[str, Any]:
    """PartitionSpecs: experts sharded over `axis`; with tp_axis set,
    each expert's d_ff additionally shards Megatron-style over it (the
    caller must psum the MoE output over tp_axis, exactly like the
    dense MLP's row-parallel close)."""
    from jax.sharding import PartitionSpec as P
    return {"wg": P(),
            "w1": P(axis, None, tp_axis),
            "b1": P(axis, tp_axis),
            "w2": P(axis, tp_axis, None)}


def _top_k_dispatch(gates: jax.Array, k: int, capacity: int,
                    token_mask: Any = None):
    """One-hot dispatch/combine tensors for top-k routing.

    gates [T, E] (softmax rows). Returns (dispatch [T, E, C] one-hot,
    combine [T, E, C] weighted, aux_loss scalar). GShard order: the
    k-th choice claims capacity AFTER all earlier choices, so first
    choices are never bumped by second choices.

    token_mask [T] (optional; truthy = real token): masked rows claim
    NO capacity and get all-zero dispatch/combine rows — the decode
    path's padding rows route nowhere and contribute exact-zero output.

    Overflow is the paged-splice trash-row idiom: positions clip into a
    [.., C+1] one-hot whose last (trash) column is sliced off, so an
    over-capacity claim writes through the trash row and contributes
    exact-zero output and gradient.
    """
    t, e = gates.shape
    masks = []
    g = gates
    for _ in range(k):
        idx = jnp.argmax(g, axis=-1)
        m = jax.nn.one_hot(idx, e, dtype=gates.dtype)      # [T, E]
        if token_mask is not None:
            m = m * token_mask.astype(gates.dtype)[:, None]
        masks.append(m)
        g = g * (1.0 - m)                  # mask out the chosen expert

    # capacity positions: later choices rank after every earlier
    # choice's claims (GShard's cumsum-with-offset)
    dispatch = jnp.zeros((t, e, capacity), gates.dtype)
    combine = jnp.zeros((t, e, capacity), gates.dtype)
    used = jnp.zeros((1, e), gates.dtype)  # tokens claimed per expert
    for m in masks:
        pos = jnp.cumsum(m, axis=0) - m + used             # [T, E]
        slot = jnp.minimum(pos, capacity).astype(jnp.int32)
        oh = (jax.nn.one_hot(slot, capacity + 1, dtype=gates.dtype)
              * m[..., None])[..., :capacity]
        dispatch = dispatch + oh
        combine = combine + oh * jnp.sum(gates * m, axis=-1,
                                         keepdims=True)[..., None]
        used = used + jnp.sum(m, axis=0, keepdims=True)

    # Switch load-balance loss on FIRST choices: E * sum_e f_e * p_e
    f_e = jnp.mean(masks[0], axis=0)
    p_e = jnp.mean(gates, axis=0)
    aux = e * jnp.sum(f_e * p_e)
    return dispatch, combine, aux


def moe_ffn(x: jax.Array, params: Dict[str, Any], cfg: MoeConfig,
            axis: str = "", axis_size: int = 1,
            token_mask: Any = None, return_stats: bool = False,
            stats_sharding: Any = None) -> Tuple[jax.Array, ...]:
    """MoE feed-forward on a [T, D] token block.

    axis: mesh axis the experts are sharded over ("" = single shard —
    all experts local, no collective). Call from INSIDE shard_map when
    axis != "". token_mask [T]: rows with a falsy mask claim no
    capacity and produce exact-zero output (decode padding rows).
    Returns (out [T, D], aux_load_balance_loss); with return_stats
    also a psum-complete f32 stats vector [2 + E]:
    [claims routed, claims dropped over capacity,
    per-expert occupancy fraction of capacity].

    stats_sharding (GSPMD callers only, never inside shard_map): a
    replicated NamedSharding pinned onto the dispatch tensor for the
    stats sums. Under expert-sharded weights the partitioner
    propagates the e-sharded layout back into dispatch (which every
    device computes in full from replicated gate weights) without
    reslicing it, so an unpinned sum comes out multiplied by the
    expert-shard count; the pin makes XLA close the sums correctly.
    """
    t, d = x.shape
    e = cfg.n_experts
    p = max(axis_size, 1)
    if e % p:
        raise ValueError(f"n_experts ({e}) not divisible by ep={p}")
    if cfg.top_k > e:
        # an all-masked gate row would silently re-route to expert 0
        raise ValueError(f"top_k ({cfg.top_k}) > n_experts ({e})")
    e_loc = e // p
    capacity = max(1, math.ceil(t * cfg.top_k
                                * cfg.capacity_factor / e))

    xf = x.astype(jnp.float32)
    gates = jax.nn.softmax(xf @ params["wg"].astype(jnp.float32),
                           axis=-1)
    dispatch, combine, aux = _top_k_dispatch(
        gates, cfg.top_k, capacity, token_mask=token_mask)

    # [T, E, C] x [T, D] -> [E, C, D] in the compute dtype
    xd = x.astype(cfg.dtype)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype), xd)

    if p > 1:
        # exchange over the ep axis: [P, E/P, C, D] -> [E/P, P*C, D]
        ei = expert_in.reshape(p, e_loc, capacity, d)
        ei = jax.lax.all_to_all(ei, axis, split_axis=0, concat_axis=2,
                                tiled=True)
        ei = ei.reshape(e_loc, p * capacity, d)
    else:
        ei = expert_in                                 # [E, C, D]

    # expert weights may arrive int8-quantized for serving
    # (models/quant.QTensor); dequantization happens AT USE so XLA
    # fuses the convert into the matmul operand read
    from .quant import dequant
    h = jnp.einsum("ecd,edf->ecf", ei, dequant(params["w1"], cfg.dtype))
    h = jax.nn.gelu(h + params["b1"][:, None, :])
    eo = jnp.einsum("ecf,efd->ecd", h, dequant(params["w2"], cfg.dtype))

    if p > 1:
        eo = eo.reshape(1, e_loc, p * capacity, d)
        eo = jax.lax.all_to_all(eo, axis, split_axis=2, concat_axis=0,
                                tiled=True)            # [P, E/P, C, D]
        eo = eo.reshape(e, capacity, d)

    out = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), eo)
    if not return_stats:
        return out.astype(x.dtype), aux
    # every gate row claims exactly top_k slots (argmax always picks
    # an expert), masked rows none — a static count, immune to the
    # propagation hazard stats_sharding documents
    claims = (jnp.float32(t * cfg.top_k) if token_mask is None
              else cfg.top_k * jnp.sum(token_mask.astype(jnp.float32)))
    disp = dispatch
    if stats_sharding is not None:
        disp = jax.lax.with_sharding_constraint(disp, stats_sharding)
    kept = jnp.sum(disp)
    occ = jnp.sum(disp, axis=(0, 2)) / capacity            # [E]
    if axis and p > 1:
        kept = jax.lax.psum(kept, axis)
        claims = jax.lax.psum(claims, axis)
        # each rank claims up to `capacity` rows per expert, so the
        # global occupancy fraction is the mean of the rank fractions
        occ = jax.lax.psum(occ, axis) / p
    stats = jnp.concatenate(
        [jnp.stack([kept, claims - kept]), occ]).astype(jnp.float32)
    return out.astype(x.dtype), aux, stats


def moe_ffn_decode(x: jax.Array, params: Dict[str, Any],
                   cfg: MoeConfig, axis: str = "", axis_size: int = 1
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Expert-parallel MoE FFN for DECODE shard_map bodies, where the
    token block x [T, D] arrives REPLICATED over the expert axis
    (decode shards batch over dp and heads over tp; experts ride the
    tp — or a dedicated ep — axis). Each rank takes an equal slice of
    the tokens (padded up to a multiple of axis_size; pad rows carry a
    zero token_mask, so they claim no capacity and contribute
    exact-zero output), routes it through :func:`moe_ffn`'s tiled
    all_to_all exchange, and the rank-local outputs close with a psum
    over the axis — the same row-parallel close as the dense MLP —
    yielding the replicated [T, D] block the decode body expects.

    Returns (out [T, D], aux, stats [2 + E]); stats are psum-complete
    (see moe_ffn). axis_size == 1 degenerates to the single-shard
    moe_ffn (no collective)."""
    t, d = x.shape
    p = max(axis_size, 1)
    if p == 1:
        return moe_ffn(x, params, cfg, return_stats=True)
    tl = -(-t // p)                        # ceil(T / P) tokens per rank
    xp = jnp.pad(x, ((0, p * tl - t), (0, 0)))
    start = jax.lax.axis_index(axis) * tl
    xl = jax.lax.dynamic_slice_in_dim(xp, start, tl, axis=0)
    mask = (start + jnp.arange(tl)) < t
    out_l, aux, stats = moe_ffn(xl, params, cfg, axis=axis,
                                axis_size=p, token_mask=mask,
                                return_stats=True)
    full = jnp.zeros((p * tl, d), out_l.dtype)
    full = jax.lax.dynamic_update_slice_in_dim(full, out_l, start,
                                               axis=0)
    out = jax.lax.psum(full, axis)[:t]
    return out, jax.lax.pmean(aux, axis), stats
