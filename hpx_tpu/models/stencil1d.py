"""1-D heat-equation workloads — the reference's flagship example ladder.

Reference analog: examples/1d_stencil/1d_stencil_{1,4}.cpp (BASELINE
config #2). The ladder is kept so the programming models can be compared
on identical physics:

  stencil_serial    1d_stencil_1: whole-domain update loop (here: one
                    fused XLA program per step batch — the honest TPU
                    "serial" baseline).
  stencil_dataflow  1d_stencil_4: the domain is split into np partitions,
                    each timestep builds hpx.dataflow(unwrapping(heat_part),
                    left, mid, right) — the future DAG throttled only by
                    dependencies. Partition updates are device dispatches;
                    halos are 1-element array slices; the host never
                    blocks inside the loop.
  stencil_fused     TPU-first production path: T steps fused per dispatch
                    (ops/stencil.multistep — pallas in-VMEM when it fits).

All use periodic boundaries and u0[i] = i (the reference's init), so
results are directly comparable across variants and to the reference.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..exec.tpu import TpuExecutor
from ..futures.async_ import Launch
from ..futures.dataflow import dataflow, unwrapping
from ..futures.future import Future, make_ready_future
from ..ops.stencil import heat_step, multistep


@dataclasses.dataclass
class StencilParams:
    nx: int = 1024          # points per partition
    np_: int = 16           # number of partitions
    nt: int = 100           # timesteps
    k: float = 0.5          # heat transfer coefficient
    dt: float = 1.0
    dx: float = 1.0

    @property
    def coef(self) -> float:
        return self.k * self.dt / (self.dx * self.dx)

    @property
    def total(self) -> int:
        return self.nx * self.np_


def init_domain(p: StencilParams) -> jax.Array:
    return jnp.arange(p.total, dtype=jnp.float32)


# -- serial (1d_stencil_1 analog) -------------------------------------------

def stencil_serial(p: StencilParams, u0: Optional[jax.Array] = None) -> jax.Array:
    u = init_domain(p) if u0 is None else u0
    coef = jnp.float32(p.coef)
    step = jax.jit(heat_step)
    for _ in range(p.nt):
        u = step(u, coef)
    return u


# -- dataflow over partitions (1d_stencil_4 analog) -------------------------

def heat_part(left: jax.Array, middle: jax.Array,
              right: jax.Array, coef) -> jax.Array:
    """Update one partition given 1-element neighbor boundary arrays.

    Reference: heat_part in examples/1d_stencil/1d_stencil_4.cpp — there
    left/right are whole neighbor partitions; shipping only the boundary
    element is the same optimization 1d_stencil_8 makes for the
    distributed case, and the right call for device memory traffic.
    """
    um = jnp.concatenate([left, middle, right])
    return um[1:-1] + coef * (um[:-2] - 2.0 * um[1:-1] + um[2:])


def stencil_dataflow(p: StencilParams,
                     executor: Optional[TpuExecutor] = None,
                     u0: Optional[jax.Array] = None) -> List[Future]:
    """The 1d_stencil_4 DAG: U[t+1][i] = dataflow(heat_part, U[t][i-1],
    U[t][i], U[t][i+1]). Returns the final vector of partition futures."""
    ex = executor or TpuExecutor()
    coef = jnp.float32(p.coef)
    full = init_domain(p) if u0 is None else u0
    parts = [full[i * p.nx:(i + 1) * p.nx] for i in range(p.np_)]
    u: List[Future] = [make_ready_future(x) for x in parts]

    compiled = jax.jit(heat_part)

    def node(lf: Future, mf: Future, rf: Future) -> Future:
        # device dispatch; future is eager — the DAG drives XLA's async
        # queue, dependencies are enforced by the arrays themselves
        return ex.async_execute_raw(
            compiled, lf.get()[-1:], mf.get(), rf.get()[:1], coef)

    for _t in range(p.nt):
        # node returns a Future; dataflow's shared state unwraps it, so
        # u stays a flat vector of futures of partition arrays. sync
        # policy: the "task body" is just an async device dispatch, no
        # host pool hop needed.
        u = [
            dataflow(node, u[(i - 1) % p.np_], u[i], u[(i + 1) % p.np_],
                     policy=Launch.sync)
            for i in range(p.np_)
        ]
    return u


def gather_dataflow_result(u: List[Future]) -> jax.Array:
    return jnp.concatenate([f.get() for f in u])


# -- fused (TPU-first) ------------------------------------------------------

def stencil_fused(p: StencilParams, u0: Optional[jax.Array] = None,
                  steps_per_dispatch: int = 50,
                  use_pallas: Optional[bool] = None) -> jax.Array:
    u = init_domain(p) if u0 is None else u0
    coef = jnp.float32(p.coef)
    done = 0
    while done < p.nt:
        s = min(steps_per_dispatch, p.nt - done)
        u = multistep(u, coef, s, use_pallas)
        done += s
    return u


# -- reporting (print_time_results analog) ----------------------------------

def print_time_results(variant: str, elapsed_s: float, p: StencilParams,
                       file=None) -> float:
    """Prints the reference-style results row; returns Mcells/s."""
    import sys
    cells = p.total * p.nt
    mcps = cells / elapsed_s / 1e6
    print(f"{variant:>18s}: {p.np_:>6d} partitions, {p.nx:>8d} points each, "
          f"{p.nt:>6d} steps, {elapsed_s:8.4f} s, {mcps:12.1f} Mcells/s",
          file=file or sys.stdout)
    return mcps
