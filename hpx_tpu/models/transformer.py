"""Decoder-only transformer with a fully sharded training step.

The reference (HPX) ships no ML models; this is the model family the
driver mandates for the TPU rebuild, built on the framework's own
substrate: ring attention (ops/attention.py — the halo-exchange ring of
SURVEY.md §5.7) for sequence parallelism, XLA collectives over ICI for
tensor/data parallelism.

Parallelism layout over a Mesh(("dp","sp","tp")):
  dp — batch sharded; grads psum over dp (+sp for the sequence split)
  sp — sequence sharded; attention walks the ring (ring_attention_
       sharded), everything else is token-local
  tp — Megatron-style: attention heads and MLP hidden dim sharded;
       wo/w2 contractions end in a psum over tp
  (collective axis names inside shard_map bodies are machine-checked
  against the mesh declaration by hpxlint HPX021)

Everything (forward, loss, backward through the ring, optimizer) runs
inside ONE shard_map-jitted program — the whole training step is a
single XLA executable per device.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from ..utils.jaxcompat import shard_map
from jax.sharding import PartitionSpec as P

from ..ops.attention import auto_attention, ring_attention_sharded

__all__ = ["TransformerConfig", "init_params", "make_train_step",
           "make_mesh_3d", "shard_params", "shard_batch", "sample_batch",
           "make_opt_state", "generate", "make_pipelined_train_step",
           "stack_pipeline_params", "shard_pipeline_params",
           "pipelined_param_specs", "interleave_pipeline_params",
           "speculative_generate", "speculative_sample",
           "deinterleave_pipeline_params", "prepare_pipeline_params",
           "beam_search"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    head_dim: int = 16
    n_layers: int = 2
    d_ff: int = 128
    dtype: Any = jnp.float32
    lr: float = 1e-2
    # mixture-of-experts: n_experts > 0 replaces every block's MLP with
    # a MoE FFN (models/moe.py); experts shard over the dp axis —
    # tokens are batch-sharded there, so the MoE all_to_all exchanges
    # tokens within data-parallel groups (the GShard layout) — giving
    # the dp x sp x tp x EP parallelism combination in one train step
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity: float = 2.0
    moe_aux_weight: float = 0.01
    # grouped-query attention: 0 < n_kv_heads < n_heads shares each
    # K/V head across a group of n_heads/n_kv_heads query heads
    # (GQA; n_kv_heads=1 is MQA). 0 means n_heads (standard MHA).
    # The KV cache — the serving memory bill — shrinks by the same
    # factor; the flash kernels read shared tiles via BlockSpec index
    # remaps, never a materialized repeat.
    n_kv_heads: int = 0
    # rematerialize each block in the backward pass (jax.checkpoint):
    # activation memory drops from O(n_layers * S * D) residuals to one
    # block's, for one extra forward — the standard long-context trade
    remat: bool = False
    # rotary position embeddings (RoPE, GPT-NeoX rotate-half form)
    # applied to q/k before attention. Off by default (the original
    # position-free model stays the baseline); under sequence
    # parallelism each shard rotates by its GLOBAL positions
    # (axis_index * S_local offset), so the ring sees one coherent
    # position space.
    rope: bool = False
    rope_theta: float = 10000.0
    # striped sequence parallelism (Striped Attention): shard r of the
    # sp ring holds tokens r, r+sp, ... instead of a contiguous chunk,
    # so causal ring steps do balanced half-work (~2x wall clock on
    # causal rings; see ops/attention.stripe_sequence). make_train_step
    # stripes the batch itself (one all_to_all each way per step);
    # positions stay GLOBAL so weights are layout-independent — decode
    # and checkpoints are unaffected.
    striped_ring: bool = False

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def make_mesh_3d(n_devices: int, devices=None):
    """Factor n into (dp, sp, tp) — prefer sp and tp first (they
    exercise the interesting collectives), then dp."""
    import numpy as np
    import jax as _j
    devs = list(devices) if devices is not None else _j.devices()
    devs = devs[:n_devices]

    def take(n, want):
        f = math.gcd(n, want)
        while f < want and n % (f * 2) == 0 and f * 2 <= want:
            f *= 2
        return (f if n % f == 0 else 1)

    tp = 2 if n_devices % 2 == 0 else 1
    rest = n_devices // tp
    sp = 2 if rest % 2 == 0 else 1
    dp = rest // sp
    from jax.sharding import Mesh
    return Mesh(np.array(devs).reshape(dp, sp, tp), ("dp", "sp", "tp"))


def _moe_cfg(cfg: TransformerConfig):
    from .moe import MoeConfig
    return MoeConfig(n_experts=cfg.n_experts, top_k=cfg.moe_top_k,
                     capacity_factor=cfg.moe_capacity,
                     d_model=cfg.d_model, d_ff=cfg.d_ff,
                     dtype=cfg.dtype)


def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Weight pytree. tp-sharded leaves carry their FULL logical shape
    here; shard_params() places them."""
    d, nh, hd, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    keys = jax.random.split(key, 2 + cfg.n_layers)
    s = 1.0 / math.sqrt(d)

    def layer(k):
        k1, k2, k3, k4 = jax.random.split(k, 4)
        nkv = cfg.kv_heads
        if nh % nkv:
            raise ValueError(f"n_heads={nh} not a multiple of "
                             f"n_kv_heads={nkv}")
        if nkv == nh:
            qkv = {"wqkv": (jax.random.normal(k1, (3, d, nh, hd)) * s
                            ).astype(cfg.dtype)}
        else:
            kq, kkv = jax.random.split(k1)
            qkv = {"wq": (jax.random.normal(kq, (d, nh, hd)) * s
                          ).astype(cfg.dtype),
                   "wkv": (jax.random.normal(kkv, (2, d, nkv, hd)) * s
                           ).astype(cfg.dtype)}
        out = {
            "ln1": jnp.ones((d,), cfg.dtype),
            **qkv,
            "wo": (jax.random.normal(k2, (nh, hd, d)) * s
                   ).astype(cfg.dtype),
            "ln2": jnp.ones((d,), cfg.dtype),
        }
        if cfg.n_experts > 0:
            from .moe import init_moe_params
            out["moe"] = init_moe_params(_moe_cfg(cfg), k3)
        else:
            out.update({
                "w1": (jax.random.normal(k3, (d, f)) * s
                       ).astype(cfg.dtype),
                "b1": jnp.zeros((f,), cfg.dtype),
                "w2": (jax.random.normal(k4, (f, d)) / math.sqrt(f)
                       ).astype(cfg.dtype),
            })
        return out

    return {
        "emb": (jax.random.normal(keys[0], (cfg.vocab, d)) * s
                ).astype(cfg.dtype),
        "ln_f": jnp.ones((d,), cfg.dtype),
        "layers": [layer(keys[2 + i]) for i in range(cfg.n_layers)],
    }


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs: heads/ffn over tp; MoE experts over dp (the ep
    layout — see TransformerConfig); everything else replicated."""
    if cfg.kv_heads == cfg.n_heads:
        qkv = {"wqkv": P(None, None, "tp", None)}
    else:
        qkv = {"wq": P(None, "tp", None),
               "wkv": P(None, None, "tp", None)}
    layer = {
        "ln1": P(), **qkv,
        "wo": P("tp", None, None), "ln2": P(),
    }
    if cfg.n_experts > 0:
        from .moe import moe_param_specs
        # experts over dp (ep layout) AND each expert's d_ff over tp —
        # the MoE output closes with a tp psum like the dense MLP
        layer["moe"] = moe_param_specs("dp", tp_axis="tp")
    else:
        layer.update({"w1": P(None, "tp"), "b1": P("tp"),
                      "w2": P("tp", None)})
    return {"emb": P(), "ln_f": P(),
            "layers": [dict(layer) for _ in range(cfg.n_layers)]}


def _place(tree, specs, mesh):
    from jax.sharding import NamedSharding
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, specs)


def shard_params(params, cfg: TransformerConfig, mesh):
    return _place(params, param_specs(cfg), mesh)


def shard_batch(tokens, targets, mesh):
    from jax.sharding import NamedSharding
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(tokens, sh), jax.device_put(targets, sh)


def sample_batch(cfg: TransformerConfig, batch: int, seq: int,
                 key: jax.Array):
    toks = jax.random.randint(key, (batch, seq + 1), 0, cfg.vocab)
    return toks[:, :-1], toks[:, 1:]


# ---------------------------------------------------------------------------
# per-shard forward / loss
# ---------------------------------------------------------------------------

def _ln(x, scale):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * scale


def _dq(w, like):
    """Dequantize int8 serving weights at use (models/quant.QTensor);
    dense weights pass through untouched."""
    from .quant import dequant
    return dequant(w, like.dtype)


def _qkv_proj(h, lp):
    """Project to (q, k, v); GQA layouts ("wq"+"wkv") give k/v their
    smaller head count."""
    if "wqkv" in lp:
        q, k, v = jnp.einsum("bsd,cdnh->cbsnh", h, _dq(lp["wqkv"], h))
        return q, k, v
    q = jnp.einsum("bsd,dnh->bsnh", h, _dq(lp["wq"], h))
    k, v = jnp.einsum("bsd,cdnh->cbsnh", h, _dq(lp["wkv"], h))
    return q, k, v


def _rope(x, pos, cfg: TransformerConfig):
    """Rotate q/k by position (GPT-NeoX rotate-half). x: [B, S, N, H]
    (or S=1 decode); pos: [S] int positions (global under sp)."""
    hd = x.shape[-1]
    if hd % 2:
        raise ValueError(f"rope needs an even head_dim; got {hd}")
    half = hd // 2
    freq = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32)
                              / half)
    ang = pos.astype(jnp.float32)[:, None] * freq[None, :]   # [S, half]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _block(x, lp, cfg: TransformerConfig, sp_size: int, dp_size: int):
    """One decoder block on a [B/dp, S/sp, D] shard; heads already
    tp-local. The Megatron f/g conjugate pair is implicit: with vma
    tracking on, jax transposes the closing psums and reduces the
    mixed replicated/partial cotangents itself. Returns (x, moe_aux)."""
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    if cfg.rope:
        # GLOBAL positions from THE layout definition the ring uses
        from ..ops.attention import ring_positions
        pos = ring_positions(jax.lax.axis_index("sp"), sp_size,
                             q.shape[1], cfg.striped_ring)
        q, k = _rope(q, pos, cfg), _rope(k, pos, cfg)
    # GQA layouts pass straight through: ring_attention_sharded
    # broadcasts grouped K/V itself on the paths that need it
    att = ring_attention_sharded(q, k, v, "sp", sp_size, causal=True,
                                 striped=cfg.striped_ring)
    o = jnp.einsum("bsnh,nhd->bsd", att, lp["wo"])
    o = jax.lax.psum(o, "tp")              # Megatron row-parallel close
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        from .moe import moe_ffn
        b, s, d = x.shape
        h, aux = moe_ffn(h.reshape(b * s, d), lp["moe"], _moe_cfg(cfg),
                         axis="dp", axis_size=dp_size)
        h = jax.lax.psum(h, "tp")      # experts' d_ff is tp-sharded
        return x + h.reshape(b, s, d), aux
    h = jax.nn.gelu(h @ lp["w1"] + lp["b1"])
    h = h @ lp["w2"]
    h = jax.lax.psum(h, "tp")
    return x + h, jnp.float32(0.0)


def _nll_head(params, x, targets):
    """ln_f + tied-embedding loss head on a [B, S, D] shard; returns
    (nll_sum, count).

    -log p[target] = logsumexp(row) - logits[target]. The target
    logit is recomputed as a row-wise dot against the gathered
    embedding instead of take_along_axis over the [B,S,V] tensor —
    the full-vocab array feeds ONLY the logsumexp reduction (which
    XLA fuses into the matmul consumer), saving a GB-scale gather
    read per step at V=32k. The dot runs in the logits' dtype so both
    terms see the same rounding (a f32 recompute against bf16 logits
    would make near-deterministic tokens go slightly negative)."""
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.einsum("bsd,bsd->bs", x, params["emb"][targets]
                     ).astype(jnp.float32)
    nll = lse - tgt
    return nll.sum(), nll.size


def _local_loss(params, tokens, targets, cfg: TransformerConfig,
                sp_size: int, dp_size: int = 1):
    """Shard-local token loss SUM, count, and MoE aux sum (psum'd by
    the caller)."""
    x = params["emb"][tokens]              # [B/dp, S/sp, D]
    aux = jnp.float32(0.0)
    block = functools.partial(_block, cfg=cfg, sp_size=sp_size,
                              dp_size=dp_size)
    if cfg.remat:
        block = jax.checkpoint(block)
    for lp in params["layers"]:
        x, a = block(x, lp)
        aux = aux + a
    s, n = _nll_head(params, x, targets)
    return s, n, aux


# ---------------------------------------------------------------------------
# the training step (one sharded XLA program)
# ---------------------------------------------------------------------------

def make_train_step(cfg: TransformerConfig, mesh, optimizer: Any = None):
    """Returns a jitted train step over the (dp, sp, tp) mesh.

    optimizer=None: plain SGD — step(params, tokens, targets) ->
    (params, loss).

    optimizer=<optax GradientTransformation>: step(params, opt_state,
    tokens, targets) -> (params, opt_state, loss); the opt state is
    sharded LIKE the params (tp-sharded moments for tp-sharded weights),
    initialize it with `optimizer.init` on the sharded params OUTSIDE
    the step (its sharding follows the params') — see
    make_opt_state().
    """
    sp_size = mesh.shape["sp"]
    dp_size = mesh.shape["dp"]
    tp_size = mesh.shape["tp"]
    if cfg.n_heads % tp_size or cfg.kv_heads % tp_size:
        raise ValueError(
            f"heads (q={cfg.n_heads}, kv={cfg.kv_heads}) must divide by "
            f"tp={tp_size} (MQA under tp needs n_kv_heads >= tp)")
    pspecs = param_specs(cfg)
    data_spec = P("dp", "sp")

    def loss_of(params, tokens, targets):
        s, n, aux = _local_loss(params, tokens, targets, cfg, sp_size,
                                dp_size)
        total = jax.lax.psum(s, ("dp", "sp"))
        count = jax.lax.psum(jnp.float32(n), ("dp", "sp"))
        loss = total / count
        if cfg.n_experts > 0:
            # mean the router load-balance term the same way as the nll
            aux_m = jax.lax.psum(aux, ("dp", "sp")) / (
                dp_size * sp_size * cfg.n_layers)
            loss = loss + cfg.moe_aux_weight * aux_m
        return loss

    # vma (varying-manual-axes) tracking is ON: jax's AD knows each
    # param enters invariant (replicated) over the axes its spec omits,
    # and automatically psums cotangents over exactly the axes they
    # vary on — dp/sp data partials AND the Megatron tp mixed-
    # replication case (residual replicated, attention/MLP partial)
    # come out correctly reduced with no manual psums.
    if optimizer is None:
        def step(params, tokens, targets):
            loss, grads = jax.value_and_grad(loss_of)(
                params, tokens, targets)
            new_params = jax.tree.map(
                lambda p, g: p - cfg.lr * g.astype(p.dtype),
                params, grads)
            return new_params, loss

        prog = shard_map(step, mesh=mesh,
                         in_specs=(pspecs, data_spec, data_spec),
                         out_specs=(pspecs, P()))
        return _jit_maybe_striped(prog, cfg, sp_size)

    ospecs = _opt_state_specs(cfg, optimizer)

    def step_opt(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_of)(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = jax.tree.map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return new_params, opt_state, loss

    prog_opt = shard_map(
        step_opt, mesh=mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P()))
    return _jit_maybe_striped(prog_opt, cfg, sp_size)


def _jit_maybe_striped(prog, cfg: TransformerConfig, sp_size: int):
    """jit `prog`, striping the LAST TWO args (tokens, targets) over
    the sp ring first when cfg.striped_ring — one wrapper for the SGD
    and optimizer step shapes so the two paths cannot diverge."""
    if not (cfg.striped_ring and sp_size > 1):
        return jax.jit(prog)
    from ..ops.attention import stripe_sequence

    def outer(*args):
        head, (tokens, targets) = args[:-2], args[-2:]
        return prog(*head, stripe_sequence(tokens, sp_size),
                    stripe_sequence(targets, sp_size))

    return jax.jit(outer)


def _opt_state_specs(cfg: TransformerConfig, optimizer: Any):
    """PartitionSpecs for an optax state: param-shaped subtrees
    (momentum/second moment) take the param's spec; scalar bookkeeping
    (step counts) is replicated. optax.tree_map_params knows which
    state leaves are param-like — shape matching would be ambiguous
    (e.g. w1/w2 share a shape when d_model == d_ff but have transposed
    tp specs)."""
    import optax
    params = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0)))
    state_shape = jax.eval_shape(lambda p: optimizer.init(p), params)
    pspecs = param_specs(cfg)
    return optax.tree_map_params(
        optimizer, lambda _leaf, spec: spec, state_shape, pspecs,
        transform_non_params=lambda _leaf: P())


# ---------------------------------------------------------------------------
# pipeline-parallel training step (the pp axis, in one sharded program)
# ---------------------------------------------------------------------------

def stack_pipeline_params(params) -> Dict[str, Any]:
    """Restack the per-layer param list into leading-axis arrays so the
    layer dimension can shard over the "pp" mesh axis (each stage holds
    n_layers/pp layers and scans over them locally)."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    return {"emb": params["emb"], "ln_f": params["ln_f"],
            "layers": stacked}


def _interleave_order(n_layers: int, pp: int, v: int):
    """Layer permutation for the interleaved schedule: device d's
    contiguous pp-slab holds its round-robin stage chunks
    [d, d+pp, d+2*pp, ...] (stage s = chunk*pp + d, chunk-major within
    the slab)."""
    if v < 1 or n_layers % (pp * v):
        raise ValueError(
            f"n_layers={n_layers} not divisible by pp*interleave="
            f"{pp}*{v}")
    ls = n_layers // (pp * v)
    order = []
    for d in range(pp):
        for chunk in range(v):
            s = chunk * pp + d
            order.extend(range(s * ls, (s + 1) * ls))
    return order


def interleave_pipeline_params(stacked, pp: int, v: int):
    """Reorder the stacked layer axis for make_pipelined_train_step's
    interleave=v schedule (identity when v == 1)."""
    if v == 1:
        return stacked
    order = jnp.asarray(_interleave_order(
        jax.tree.leaves(stacked["layers"])[0].shape[0], pp, v))
    return {**stacked,
            "layers": jax.tree.map(lambda a: a[order],
                                   stacked["layers"])}


def deinterleave_pipeline_params(stacked, pp: int, v: int):
    """Inverse of interleave_pipeline_params (back to layer order)."""
    if v == 1:
        return stacked
    n = jax.tree.leaves(stacked["layers"])[0].shape[0]
    order = _interleave_order(n, pp, v)
    inv = [0] * n
    for i, o in enumerate(order):
        inv[o] = i
    inv = jnp.asarray(inv)
    return {**stacked,
            "layers": jax.tree.map(lambda a: a[inv], stacked["layers"])}


def pipelined_param_specs(tp_axis: Optional[str] = None, *,
                          gqa: bool = False) -> Dict[str, Any]:
    """Specs for stacked params: layer axis over "pp", heads/ffn over
    tp (when present), embedding/final-norm replicated. (Dense blocks
    only — make_pipelined_train_step rejects MoE configs.)"""
    t = tp_axis
    if gqa:
        qkv = {"wq": P("pp", None, t, None),
               "wkv": P("pp", None, None, t, None)}
    else:
        qkv = {"wqkv": P("pp", None, None, t, None)}
    layer = {
        "ln1": P("pp", None),
        **qkv,
        "wo": P("pp", t, None, None),
        "ln2": P("pp", None),
        "w1": P("pp", None, t),
        "b1": P("pp", t),
        "w2": P("pp", t, None),
    }
    return {"emb": P(), "ln_f": P(), "layers": layer}


def shard_pipeline_params(stacked, mesh):
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    gqa = "wq" in stacked["layers"]
    return _place(stacked, pipelined_param_specs(tp_axis, gqa=gqa), mesh)


def prepare_pipeline_params(params, mesh, interleave: int = 1):
    """One-stop: stack the per-layer list, apply the interleaved layer
    permutation when interleave > 1, and place on the mesh. Use this
    with make_pipelined_train_step(..., interleave=V) — the layer
    LAYOUT must match the step's interleave or training silently runs
    a layer-permuted network (nothing in the arrays records the
    layout, so the pairing is the API's job; this helper makes the
    pairing a single argument)."""
    pp = mesh.shape["pp"]
    stacked = interleave_pipeline_params(
        stack_pipeline_params(params), pp, interleave)
    return shard_pipeline_params(stacked, mesh)


def _pp_block(x, lp, cfg: TransformerConfig, tp_axis: Optional[str]):
    """One decoder block on a [mb, S, D] microbatch shard inside the
    pipeline: attention is sequence-LOCAL (auto_attention — flash on
    TPU; the sp ring belongs to the dp x sp x tp step), heads/ffn
    tp-sharded when a tp axis exists."""
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    if cfg.rope:
        pos = jnp.arange(q.shape[1])    # sequence is pp-local in full
        q, k = _rope(q, pos, cfg), _rope(k, pos, cfg)
    att = auto_attention(q, k, v, causal=True)
    o = jnp.einsum("bsnh,nhd->bsd", att, lp["wo"])
    if tp_axis:
        o = jax.lax.psum(o, tp_axis)
    x = x + o
    h = _ln(x, lp["ln2"])
    h = jax.nn.gelu(h @ lp["w1"] + lp["b1"])
    h = h @ lp["w2"]
    if tp_axis:
        h = jax.lax.psum(h, tp_axis)
    return x + h


def _pipelined_opt_state_specs(cfg: TransformerConfig, optimizer: Any,
                               tp_axis: Optional[str]):
    """Opt-state specs for the STACKED layout (mirrors
    _opt_state_specs: param-shaped moments take the param's spec)."""
    import optax
    stacked = jax.eval_shape(
        lambda: stack_pipeline_params(
            init_params(cfg, jax.random.PRNGKey(0))))
    state_shape = jax.eval_shape(lambda p: optimizer.init(p), stacked)
    pspecs = pipelined_param_specs(
        tp_axis, gqa=cfg.kv_heads != cfg.n_heads)
    return optax.tree_map_params(
        optimizer, lambda _leaf, spec: spec, state_shape, pspecs,
        transform_non_params=lambda _leaf: P())


def make_pipelined_opt_state(stacked, cfg: TransformerConfig, mesh,
                             optimizer: Any):
    """optimizer.init under jit with shardings matching the stacked
    layout (moments pp/tp-sharded like their weights)."""
    from jax.sharding import NamedSharding
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    ospecs = _pipelined_opt_state_specs(cfg, optimizer, tp_axis)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, P))
    return jax.jit(optimizer.init, out_shardings=shardings)(stacked)


def make_pipelined_train_step(cfg: TransformerConfig, mesh,
                              n_microbatches: int,
                              optimizer: Any = None,
                              interleave: int = 1):
    """Train step with pipeline parallelism INSIDE the jitted program:
    layers shard over the mesh's "pp" axis (stacked leading dim),
    microbatches hand off stage-to-stage via one lax.ppermute hop per
    scan step (parallel/pipeline_spmd.pipeline_run), batch shards over
    "dp", heads/ffn over "tp" when present. AD through the scan IS the
    backward pipeline (ppermute transposes to the inverse rotation).

    Params must be in the STACKED layout (stack_pipeline_params +
    shard_pipeline_params). step(params, tokens, targets) ->
    (params, loss) with plain-SGD update, matching make_train_step's
    optimizer=None contract.

    striped_ring is not wired here (no sp axis to stripe) and raises.

    The schedule stashes final-stage outputs into an [M, ...] buffer
    and runs the loss head ONCE per device after the scan; the only
    dead head work is that single post-scan pass on the pp-1 non-last
    devices (their buffers are zeros, masked out of the psum). MoE
    configs take the dp/ep step instead (expert all_to_all inside a
    pipeline stage would deadlock against the pp ppermute schedule if
    capacity buffers ever shard over dp x pp jointly).

    interleave=V > 1 runs the INTERLEAVED schedule (virtual stages,
    pipeline_run_interleaved): pp*V stages round-robin over devices,
    each scan step computing one 1/(pp*V) layer chunk — bubble
    (pp-1)/(M*V + pp-1) instead of (pp-1)/(M + pp-1); M must divide by
    pp. Params must be in the MATCHING interleaved layout — build them
    with prepare_pipeline_params(params, mesh, interleave=V) (updates
    come back in that layout; invert with
    deinterleave_pipeline_params).
    """
    if cfg.striped_ring:
        raise NotImplementedError(
            "striped_ring is wired for make_train_step's sp ring; the "
            "pipelined step has no sp axis to stripe")
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "pipeline-parallel MoE is not supported; use make_train_step "
            "with the dp/ep layout")
    from ..parallel.pipeline_spmd import (pipeline_run,
                                          pipeline_run_interleaved)
    from ..ops.attention import _pvary

    axes = mesh.axis_names
    if "pp" not in axes or "dp" not in axes:
        raise ValueError(f"mesh must carry ('dp', 'pp'); has {axes}")
    tp_axis = "tp" if "tp" in axes else None
    pp, dp = mesh.shape["pp"], mesh.shape["dp"]
    V = interleave
    if V < 1 or cfg.n_layers % (pp * V):
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"pp*interleave={pp}*{V}")
    if tp_axis:
        tp_size = mesh.shape["tp"]
        if cfg.n_heads % tp_size or cfg.kv_heads % tp_size:
            raise ValueError(
                f"heads (q={cfg.n_heads}, kv={cfg.kv_heads}) must "
                f"divide by tp={tp_size}")
    M = n_microbatches
    pspecs = pipelined_param_specs(
        tp_axis, gqa=cfg.kv_heads != cfg.n_heads)
    data_spec = P("dp", None)

    def loss_of(params, tokens, targets):
        bl, s = tokens.shape
        if bl % M:
            raise ValueError(f"per-dp-shard batch {bl} not divisible "
                             f"by n_microbatches={M}")
        mb = bl // M
        toks = tokens.reshape(M, mb, s)
        tgts = targets.reshape(M, mb, s)

        block = jax.checkpoint(
            lambda x, lp: _pp_block(x, lp, cfg, tp_axis))

        def chunk_apply(lg, x):
            x, _ = jax.lax.scan(
                lambda x, lp: (block(x, lp), None), x, lg)
            return x

        def feed(t):
            return params["emb"][toks[t]].astype(cfg.dtype)

        # collect STASHES the final-stage outputs into an [M, ...]
        # buffer; the loss head (a full [*, vocab] matmul + logsumexp)
        # then runs ONCE per device after the scan instead of at every
        # schedule step — in-scan heads would multiply dead masked
        # work by the step count (x V^2 relative to useful compute on
        # the interleaved schedule)
        def collect(buf, y, t_out, valid):
            upd = jax.lax.dynamic_update_index_in_dim(
                buf, y.astype(buf.dtype), t_out, 0)
            return jnp.where(valid, upd, buf)

        vary = ("dp", "pp")
        buf0 = _pvary(jnp.zeros((M, mb, s, cfg.d_model), cfg.dtype),
                      vary)
        if V == 1:
            x0 = _pvary(jnp.zeros((mb, s, cfg.d_model), cfg.dtype), vary)
            buf = pipeline_run(
                "pp", pp, M, lambda x: chunk_apply(params["layers"], x),
                feed, collect, buf0, x0)
        else:
            ls_per = cfg.n_layers // (pp * V)
            lgroups = jax.tree.map(
                lambda a: a.reshape((V, ls_per) + a.shape[1:]),
                params["layers"])

            def stage_fn(v, x):
                # v is a traced per-device chunk index: dynamic_index
                # (not lax.switch — SPMD would run all V branches)
                lg = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, v, 0, keepdims=False), lgroups)
                return chunk_apply(lg, x)

            x0 = _pvary(jnp.zeros((V, mb, s, cfg.d_model), cfg.dtype),
                        vary)
            buf = pipeline_run_interleaved(
                "pp", pp, V, M, stage_fn, feed, collect, buf0, x0)
        ssum, n = _nll_head(params, buf.reshape(M * mb, s, cfg.d_model),
                            tgts.reshape(M * mb, s))
        w = (jax.lax.axis_index("pp") == pp - 1).astype(jnp.float32)
        # n is a static size: w*n varies over pp only — add the missing
        # dp variance before the joint psum (w*ssum already has both:
        # ssum derives from the dp-sharded targets)
        cnt = _pvary(w * jnp.float32(n), ("dp",))
        return jax.lax.psum(w * ssum, ("dp", "pp")) \
            / jax.lax.psum(cnt, ("dp", "pp"))

    if optimizer is None:
        def step(params, tokens, targets):
            loss, grads = jax.value_and_grad(loss_of)(
                params, tokens, targets)
            new_params = jax.tree.map(
                lambda p, g: p - cfg.lr * g.astype(p.dtype), params, grads)
            return new_params, loss

        return jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=(pspecs, data_spec, data_spec),
            out_specs=(pspecs, P())))

    ospecs = _pipelined_opt_state_specs(cfg, optimizer, tp_axis)

    def step_opt(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_of)(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = jax.tree.map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return new_params, opt_state, loss

    return jax.jit(shard_map(
        step_opt, mesh=mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P())))


def _block_decode(x, lp, kv, write_at, cfg: TransformerConfig,
                  tp_axis: Optional[str] = None,
                  ep_axis: Optional[str] = None, ep_size: int = 1):
    """One decoder block for a single new token position with a KV
    cache. x: [B, 1, D]; kv: (k_cache, v_cache) each [B, Smax, N, H]
    (N = the tp-LOCAL head count under sharded decode); write_at:
    scalar index. With tp_axis set, the wo/w2 contractions close with
    a psum — the same Megatron split the train step uses, so the KV
    cache shards over heads and never replicates. GQA: the cache holds
    only the kv heads ([B, Smax, Nkv, H] — the n_heads/n_kv_heads
    serving-memory saving); q heads attend grouped."""
    kc, vc = kv
    h = _ln(x, lp["ln1"])
    q, k, v = _qkv_proj(h, lp)
    sq = x.shape[1]
    if cfg.rope:
        # rotate at the write positions; the cache stores POST-rope k,
        # so cached entries never need re-rotation. sq > 1 is the
        # WINDOW decode (speculative verification / chunked prefill):
        # token i of the window sits at write_at + i.
        pos = jnp.asarray(write_at) + jnp.arange(sq)
        q, k = _rope(q, pos, cfg), _rope(k, pos, cfg)
    kc = jax.lax.dynamic_update_slice_in_dim(kc, k, write_at, axis=1)
    vc = jax.lax.dynamic_update_slice_in_dim(vc, v, write_at, axis=1)
    b, sq, nq, hd = q.shape
    nkv = kc.shape[2]
    g = nq // nkv
    qg = q.reshape(b, sq, nkv, g, hd)
    s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
    pos = jnp.arange(kc.shape[1])
    # per-query causal horizon: window token i attends cache positions
    # <= write_at + i (collapses to the old scalar mask at sq == 1)
    qpos = jnp.asarray(write_at) + jnp.arange(sq)
    s = jnp.where(pos[None, None, None, None, :]
                  <= qpos[None, None, None, :, None], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(b, sq, nq, hd)
    o = jnp.einsum("bsnh,nhd->bsd", att, _dq(lp["wo"], att))
    if tp_axis:
        o = jax.lax.psum(o, tp_axis)
    x = x + o
    h = _ln(x, lp["ln2"])
    if "moe" in lp:
        from .moe import moe_ffn, moe_ffn_decode
        b, s, d = h.shape
        # decode routes DROP-FREE (capacity_factor = n_experts makes
        # C >= every possible claim): with no drops, each token's output
        # is independent of the rest of the batch — generating a prompt
        # alone or inside a batch yields identical tokens, and the
        # serving path never silently zeroes a token the way
        # capacity-limited training legitimately does
        mcfg = dataclasses.replace(_moe_cfg(cfg),
                                   capacity_factor=float(cfg.n_experts))
        if ep_axis is not None:
            # expert-parallel decode: experts shard over ep_axis; the
            # replicated token block splits across it and the outputs
            # close with a psum (moe_ffn_decode) — the expert-axis
            # analogue of the dense branch's row-parallel tp psum
            out, _aux, _stats = moe_ffn_decode(
                h.reshape(b * s, d), lp["moe"], mcfg, ep_axis, ep_size)
        else:
            out, _aux = moe_ffn(h.reshape(b * s, d), lp["moe"], mcfg)
        return x + out.reshape(b, s, d), (kc, vc)
    h = jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) @ _dq(lp["w2"], h)
    if tp_axis:
        h = jax.lax.psum(h, tp_axis)
    return x + h, (kc, vc)


def _decode_forward(params, caches, tok, pos, cfg, tp_axis=None,
                    ep_axis=None, ep_size=1):
    """One decode token through every block: the W == 1 case of
    _decode_window, so there is exactly ONE copy of the cached forward
    — any change to it lands in generate(), beam_search(), and both
    phases of speculative_generate(). Returns (caches, f32 logits
    [B, V])."""
    caches, logits = _decode_window(params, caches, tok[:, None], pos,
                                    cfg, tp_axis=tp_axis,
                                    ep_axis=ep_axis, ep_size=ep_size)
    return caches, logits[:, 0, :]


def _decode_window(params, caches, toks, pos0, cfg, tp_axis=None,
                   ep_axis=None, ep_size=1, need_logits=True):
    """A WINDOW of new tokens through the cached blocks in one pass:
    toks [B, W] at positions pos0..pos0+W-1. Returns (caches, f32
    logits [B, W, V]). One MXU-batched forward where a scan would run
    W sequential steps — the speculative-verification / chunked-prefill
    fast path (every weight is read once per window instead of once per
    token, which is the whole memory-bandwidth case for speculative
    decoding). need_logits=False is the cache-only prefill: skips the
    final ln + [B, W, V] unembedding when the caller only wants the KV
    side effects (returns (caches, None))."""
    x = params["emb"][toks]
    new_caches = []
    for lp, kv in zip(params["layers"], caches):
        x, kv = _block_decode(x, lp, kv, pos0, cfg, tp_axis=tp_axis,
                              ep_axis=ep_axis, ep_size=ep_size)
        new_caches.append(kv)
    if not need_logits:
        return new_caches, None
    x = _ln(x, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["emb"])
    return new_caches, logits.astype(jnp.float32)


# CHUNK tokens per prefill window: large enough that every weight read
# amortizes over a full MXU tile of tokens, small enough that the
# transient per-chunk [B, CHUNK, V] logits (last chunk only) and [B,
# CHUNK, S] attention scores stay modest at long prompts
_PREFILL_CHUNK = 128


def _prefill_window(params, cfg, caches, prompt, tp_axis=None,
                    ep_axis=None, ep_size=1,
                    chunk: int = _PREFILL_CHUNK, need_logits=True,
                    logits0=None):
    """Feed the prompt into the caches in windowed one-pass chunks
    (chunked prefill): each chunk of up to `chunk` tokens is ONE
    _decode_window forward — every weight is read once per chunk
    instead of once per token, the classic prefill-vs-decode
    distinction. Returns (caches, logits after the LAST prompt token);
    intermediate chunks run cache-only, as does everything when
    need_logits=False (a draft model's prefill never reads logits).
    `logits0` is the empty-prompt fallback result (callers build it
    with the right sharding/vma). Shared by generate(), beam_search(),
    and speculative_generate()."""
    plen = prompt.shape[1]
    last = logits0[:, None] if logits0 is not None else None
    for s in range(0, plen, chunk):
        e = min(plen, s + chunk)
        caches, lg = _decode_window(params, caches, prompt[:, s:e], s,
                                    cfg, tp_axis=tp_axis,
                                    ep_axis=ep_axis, ep_size=ep_size,
                                    need_logits=need_logits
                                    and e == plen)
        if lg is not None:
            last = lg
    return caches, (last[:, -1] if need_logits else None)


def generate(params, cfg: TransformerConfig, prompt: jax.Array,
             max_new: int = 32, mesh=None, temperature: float = 0.0,
             top_k: int = 0, eos_id: Optional[int] = None,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Decode: prefill the prompt token-by-token into KV caches, then
    emit max_new tokens. Static shapes throughout (lax.scan over cache
    positions) — one compile per (prompt_len, max_new).

    temperature=0 (default): greedy argmax. temperature>0: sample from
    softmax(logits/temperature), truncated to the top_k logits when
    top_k>0 (pass `key`). Sampling keys fold in the GLOBAL batch row
    and position, so sharded and single-device runs draw identical
    tokens. eos_id: rows that emit it keep emitting it (done rows
    still compute — static shapes — but their output is pinned).

    mesh=None: single device. Otherwise a Mesh with axes ("dp", "tp")
    (either size may be 1) runs SHARDED serving as one program: batch
    over dp, attention heads + ffn + KV caches over tp (Megatron decode
    — caches never replicate), params placed by shard_params, prompt
    sharded [dp, None]. MoE models decode EXPERT-PARALLEL: experts
    shard over tp (or a dedicated "ep" mesh axis), routing drop-free
    through moe_ffn_decode's all_to_all exchange — token-identical to
    the single-device MoE path."""
    if temperature > 0.0 and key is None:
        raise ValueError("temperature > 0 needs a PRNG key")
    if temperature <= 0.0 and (top_k > 0 or key is not None):
        raise ValueError(
            "top_k/key have no effect at temperature=0 (greedy); pass "
            "temperature > 0 to sample")
    from ..ops.attention import _pvary

    b, plen = prompt.shape
    smax = plen + max_new
    nh, hd = cfg.n_heads, cfg.head_dim
    tp = dp = 1
    tp_axis = None
    ep_axis, ep_size = None, 1
    if mesh is not None:
        dp, tp = _decode_mesh_check(cfg, mesh, b)
        tp_axis = "tp"       # size-1 tp: the psums are no-ops
        ep_axis, ep_size = _decode_ep(cfg, mesh)

    def fresh_cache(b_local, nh_local):
        caches = [(jnp.zeros((b_local, smax, nh_local, hd), cfg.dtype),
                   jnp.zeros((b_local, smax, nh_local, hd), cfg.dtype))
                  for _ in range(cfg.n_layers)]
        if mesh is not None:
            # zeros are axis-invariant; the scanned k/v updates vary
            # over dp (batch) and tp (heads) — match the carry's vma
            caches = jax.tree.map(lambda z: _pvary(z, ("dp", "tp")),
                                  caches)
        return caches

    def select(logits, pos, b_local, karg):
        """Next token from [B_local, V] logits at position `pos`.
        `karg` is the PRNG key as a TRACED argument — baking it into
        the closure would force a recompile per key."""
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        raw = logits.astype(jnp.float32)
        if top_k > 0:
            # top-k set is scale-invariant: mask the raw logits, the
            # shared sampler scales after
            thr = jax.lax.top_k(raw, top_k)[0][..., -1:]
            raw = jnp.where(raw < thr, -jnp.inf, raw)
        # keys fold in (position, GLOBAL row): sharded == single-device
        base = (jax.lax.axis_index("dp") * b_local if mesh is not None
                else 0)
        return jax.vmap(
            lambda row_logits, r: _sample_row(row_logits, temperature,
                                              karg, pos, r))(
            raw, base + jnp.arange(b_local))

    def forward_token(params, caches, tok, pos):
        return _decode_forward(params, caches, tok, pos, cfg,
                               tp_axis=tp_axis, ep_axis=ep_axis,
                               ep_size=ep_size)

    def step_token(params, karg, carry, inp):
        caches, _prev = carry
        tok, pos = inp
        caches, logits = forward_token(params, caches, tok, pos)
        nxt = select(logits, pos, tok.shape[0], karg)
        return (caches, nxt), nxt

    def run(params, prompt, karg):
        b_local = prompt.shape[0]
        caches = fresh_cache(b_local, cfg.kv_heads // tp)
        # chunked prefill: windowed one-pass forwards at positions
        # 0..plen-1; selection happens once afterwards on the last
        # position's logits. logits0 covers the empty-prompt edge
        # (unconditional generation: argmax/sample over zeros).
        logits0 = jnp.zeros((b_local, cfg.vocab), jnp.float32)
        if mesh is not None:
            logits0 = _pvary(logits0, ("dp",))
        caches, last_logits = _prefill_window(params, cfg, caches,
                                              prompt, tp_axis=tp_axis,
                                              ep_axis=ep_axis,
                                              ep_size=ep_size,
                                              logits0=logits0)
        # t0 = the prediction following the last prompt token, drawn at
        # position plen-1 (same key fold the in-scan path would use)
        tok0 = select(last_logits, plen - 1, b_local, karg)
        step = functools.partial(step_token, params, karg)
        # decode: feed back the selected token; each step emits the
        # token it FEEDS — emitting the step's own prediction instead
        # would drop t0 and shift the whole output by one.
        done0 = jnp.zeros((b_local,), jnp.bool_)
        if mesh is not None:
            done0 = _pvary(done0, ("dp",))

        def gen(carry, pos):
            caches, tok, done = carry
            if eos_id is not None:
                tok = jnp.where(done, jnp.int32(eos_id),
                                tok.astype(jnp.int32))
            (caches, nxt), _ = step((caches, tok), (tok, pos))
            if eos_id is not None:
                done = jnp.logical_or(done, tok == eos_id)
            return (caches, nxt, done), tok

        _carry, toks = jax.lax.scan(
            gen, (caches, tok0, done0), jnp.arange(plen, smax))
        return toks.T                                  # [B_local, max_new]

    karg = key if key is not None else jax.random.PRNGKey(0)
    ck = ("generate", cfg, b, plen, max_new, temperature, top_k,
          eos_id, mesh, _tree_key(params))
    if mesh is None:
        prog = _cached_program(ck, lambda: jax.jit(run))
        return prog(params, prompt, karg)

    from jax.sharding import NamedSharding
    data_spec = P("dp", None)

    def build():
        # scales follow channels; experts take the decode layout
        pspecs = _decode_pspecs(params, cfg, mesh)
        return jax.jit(shard_map(
            run, mesh=mesh,
            in_specs=(pspecs, data_spec, P()),
            out_specs=data_spec))

    prog = _cached_program(ck, build)
    prompt = jax.device_put(prompt, NamedSharding(mesh, data_spec))
    return prog(params, prompt, karg)


# Compiled serving programs, keyed by everything the traced closures
# BAKE IN (config, shapes, decode options, mesh, param-tree structure).
# Without this, every generate()/beam_search()/speculative_* call
# builds a fresh closure and jit RETRACES — repeated serving calls pay
# a full compile each time. jit still retraces internally if the traced
# ARG shapes change under one cache key, so the key only needs the
# closure constants.
_PROGRAMS: Dict[Any, Any] = {}


def _cached_program(key_, build):
    from ..core.programs import cached_program
    return cached_program(_PROGRAMS, key_, build)


def _tree_key(tree) -> Any:
    return jax.tree_util.tree_structure(tree)



def _sample_row(logits_row, temperature, key, pos, row):
    """THE per-row sampling contract every decoder shares (generate's
    select, the continuous-batching server's step and admission):
    temperature-scale, fold (position, row) into the key, categorical.
    Keeping one copy is what makes 'batched == solo' token equality a
    theorem rather than a hope."""
    k = jax.random.fold_in(jax.random.fold_in(key, pos), row)
    return jax.random.categorical(
        k, logits_row.astype(jnp.float32) / temperature)


def _pick_row(logits_row, key, temperature, pos):
    """Greedy-or-sampled next token for ONE batch row — the serving
    wrapper of the `_sample_row` contract: argmax at temperature 0,
    the shared categorical draw otherwise (row index pinned to 0: the
    server keys are folded per slot, so the batch row carries no
    entropy). The speculative-verify window picks its targets with
    this exact function at each window position, which is what makes
    acceptance collapse to exact token match: the window's position-p
    pick IS the token the sequential step program would have emitted
    at p."""
    sampled = _sample_row(logits_row, jnp.maximum(temperature, 1e-6),
                          key, pos, 0)
    return jnp.where(temperature > 0, sampled,
                     jnp.argmax(logits_row))


def _decode_ep(cfg: TransformerConfig, mesh):
    """Expert axis for sharded MoE decode: the dedicated "ep" mesh
    axis when the mesh declares one, otherwise experts ride "tp".
    Returns (axis_name, axis_size); (None, 1) for dense models or no
    mesh."""
    if mesh is None or cfg.n_experts <= 0:
        return None, 1
    name = "ep" if "ep" in mesh.axis_names else "tp"
    return name, mesh.shape[name]


def _decode_mesh_check(cfg: TransformerConfig, mesh, batch: int):
    """Shared decode-mesh contract for generate()/
    speculative_generate, and for ContinuousServer — dense AND paged
    (slots play the batch role there): ("dp","tp") axes, heads/batch
    divisible. MoE models decode EXPERT-PARALLEL: experts shard over
    "tp" (or a dedicated "ep" axis when the mesh declares one), token
    routing rides moe_ffn's tiled all_to_all, and n_experts must
    divide the expert axis. Returns (dp, tp)."""
    names = mesh.axis_names
    if "dp" not in names or "tp" not in names:
        raise ValueError(f"decode mesh needs ('dp','tp'); has {names}")
    dp, tp = mesh.shape["dp"], mesh.shape["tp"]
    if cfg.n_heads % tp or cfg.kv_heads % tp:
        raise ValueError(
            f"heads (q={cfg.n_heads}, kv={cfg.kv_heads}) not divisible "
            f"by tp={tp}")
    if batch % dp:
        raise ValueError(f"batch {batch} not divisible by dp={dp}")
    if cfg.n_experts > 0:
        ep_axis, ep = _decode_ep(cfg, mesh)
        if cfg.n_experts % ep:
            raise ValueError(
                f"n_experts ({cfg.n_experts}) not divisible by "
                f"{ep_axis}={ep}; shrink {ep_axis} to a divisor of "
                f"n_experts, or declare a dedicated 'ep' mesh axis "
                f"that divides it")
    return dp, tp


def _decode_pspecs(params, cfg: TransformerConfig, mesh=None):
    """Param specs for sharded decode; quantized targets (int8 or
    packed int4) place scales with their channels. MoE experts take
    the DECODE layout — experts over the expert axis (_decode_ep),
    each expert's d_ff UNSHARDED: the training layout's tp split of
    d_ff can't compose with experts occupying tp, and the decode close
    is already the psum over the expert axis."""
    from .quant import QTensor, QTensor4, quantized_bits
    quant = any(isinstance(x, (QTensor, QTensor4))
                for x in jax.tree.leaves(
                    params,
                    is_leaf=lambda x: isinstance(x, (QTensor,
                                                     QTensor4))))
    if quant:
        from .quant import quantized_param_specs
        bits = quantized_bits(params)
        specs = quantized_param_specs(cfg, bits)
    else:
        specs = param_specs(cfg)
    if cfg.n_experts > 0:
        from .moe import moe_param_specs
        ep_axis = _decode_ep(cfg, mesh)[0] or "tp"
        m = moe_param_specs(ep_axis, tp_axis=None)
        if quant:
            # scales keep size-1 contract axes (already unsharded in
            # the decode layout), so their spec matches the weight's
            from .quant import _MOE_CONTRACT_AXES, _MOE_PACK_AXES
            for mn in _MOE_CONTRACT_AXES:
                m[mn] = (QTensor4(m[mn], m[mn], _MOE_PACK_AXES[mn])
                         if bits == 4 else QTensor(q=m[mn], s=m[mn]))
        for lp in specs["layers"]:
            lp["moe"] = dict(m)
    return specs




def _pin_after_eos(out, eos_id):
    """Pin every position AFTER a row's first eos to eos — the same
    observable behavior as generate()'s done-row pinning (a finished
    row keeps emitting eos), applied as a post-pass so the speculative
    loops stay eos-free inside."""
    hit = (out == eos_id)
    after = jnp.cumsum(hit.astype(jnp.int32), axis=1) >= 1
    prev = jnp.concatenate(
        [jnp.zeros_like(after[:, :1]), after[:, :-1]], axis=1)
    return jnp.where(prev, jnp.int32(eos_id), out)


def _accept_scatter(out, m, a, emis, k, max_new):
    """Shared accept-and-emit step for both speculative decoders: write
    emissions 0..a at columns m..m+a of `out` (the max_new sentinel
    index + mode='drop' is the out-of-bounds clamp), return the new
    cursor token and advanced count. emis: [B, k+1]."""
    idx = m + jnp.arange(k + 1)
    valid = (jnp.arange(k + 1) <= a) & (idx < max_new)
    idx_safe = jnp.where(valid, idx, max_new)      # max_new: dropped
    out = out.at[:, idx_safe].set(
        jnp.where(valid[None, :], emis, 0), mode="drop")
    cur = jnp.take(emis, a, axis=1)
    return out, cur, jnp.minimum(m + a + 1, max_new)


def speculative_generate(params, cfg: TransformerConfig,
                         draft_params, draft_cfg: TransformerConfig,
                         prompt: jax.Array, max_new: int = 32,
                         k: int = 4, mesh=None,
                         eos_id: Optional[int] = None,
                         return_stats: bool = False) -> jax.Array:
    """Greedy speculative decoding (Leviathan et al. shape, greedy
    acceptance): a small DRAFT model proposes k tokens autoregressively,
    the target model scores all k+1 positions in ONE window forward
    (_decode_window — each target weight is read once per window instead
    of once per token, which is the whole memory-bandwidth win), and the
    longest agreeing prefix is accepted plus the target's own token at
    the first disagreement. Every emitted token comes from the TARGET's
    argmax, so the output matches generate(temperature=0) up to
    floating-point argmax ties: the window and sequential forwards
    reassociate sums (~1e-4 logit difference), so a position whose
    top-2 target logits are closer than that can resolve either way —
    the draft still never changes which DISTRIBUTION tokens come from.

    Batches accept the MINIMUM agreement count across rows each round
    (per-row counts would need per-row cache positions): correct for
    every row — tokens below the minimum agree everywhere, and the
    bonus token equals the draft token on rows that agreed further —
    at reduced speedup for large batches. Greedy only; models must
    share the vocab (sizes may differ otherwise).

    mesh=None runs single-device. A Mesh(("dp","tp")) runs the same
    sharded-serving layout as generate() (MoE targets run
    expert-parallel over tp/ep; the draft is replicated); the
    row-agreement minimum is then PER dp SHARD, and
    each shard's decode loop runs its own trip count — with
    return_stats the per-row rounds report their shard's count.

    Cache staleness note: rejected draft entries stay in the caches
    PAST the accepted position; they are harmless because the next
    round rewrites positions sequentially from the rewound cursor and
    the causal mask never lets a query see beyond its own position."""
    if k < 1:
        raise ValueError(f"speculative_generate: k must be >= 1, got {k}")
    if draft_cfg.vocab != cfg.vocab:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab} != target vocab {cfg.vocab}")
    if max_new <= 0:
        empty = prompt[:, :0].astype(jnp.int32)
        return (empty, 0) if return_stats else empty

    from ..ops.attention import _pvary

    b, plen = prompt.shape
    # target windows start at plen+m-1 (m <= max_new-1) and span k+1
    smax = plen + max_new + k

    tp_size = 1
    tp_axis = None
    ep_axis, ep_size = None, 1
    if mesh is not None:
        # same mesh contract as generate() (dp x tp; MoE targets run
        # expert-parallel over tp or a dedicated ep axis). The DRAFT
        # is replicated (small by construction; each tp rank drafts
        # redundantly and identically). Acceptance is per-dp-shard
        # local, so the while_loop trip counts legitimately DIVERGE
        # across dp shards — no collective crosses dp inside the loop,
        # and tp groups stay in lockstep because their logits are
        # psum-complete (expert psums included).
        _dp_size, tp_size = _decode_mesh_check(cfg, mesh, b)
        tp_axis = "tp"
        ep_axis, ep_size = _decode_ep(cfg, mesh)

    def fresh(c: TransformerConfig, b_local, nh_local, axes):
        caches = [(jnp.zeros((b_local, smax, nh_local, c.head_dim),
                             c.dtype),
                   jnp.zeros((b_local, smax, nh_local, c.head_dim),
                             c.dtype))
                  for _ in range(c.n_layers)]
        if mesh is not None:
            caches = jax.tree.map(lambda z: _pvary(z, axes), caches)
        return caches

    def run(tgt, dft, prompt):
        b_local = prompt.shape[0]
        logits0 = jnp.zeros((b_local, cfg.vocab), jnp.float32)
        if mesh is not None:
            logits0 = _pvary(logits0, ("dp",))
        t_caches = fresh(cfg, b_local, cfg.kv_heads // tp_size,
                         ("dp", "tp"))
        d_caches = fresh(draft_cfg, b_local, draft_cfg.kv_heads,
                         ("dp",))
        t_caches, t_last = _prefill_window(tgt, cfg, t_caches, prompt,
                                           tp_axis=tp_axis,
                                           ep_axis=ep_axis,
                                           ep_size=ep_size,
                                           logits0=logits0)
        # draft prefill is cache-only: its prompt logits are never read
        d_caches, _ = _prefill_window(dft, draft_cfg, d_caches,
                                      prompt, need_logits=False)
        tok0 = jnp.argmax(t_last, axis=-1).astype(jnp.int32)
        out = jnp.zeros((b_local, max_new),
                        jnp.int32).at[:, 0].set(tok0)

        def cond(carry):
            return carry[0] < max_new

        def body(carry):
            m, cur, out, t_caches, d_caches, rounds = carry
            pos0 = plen + m - 1          # cur's sequence position

            def dstep(c, j):
                dc, tok = c
                dc, lg = _decode_forward(dft, dc, tok, pos0 + j,
                                         draft_cfg)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (dc, nxt), nxt

            # k+1 steps, not k: the extra step feeds d_{k-1} so ITS KV
            # lands at pos0+k — on a fully-accepted round the next
            # round resumes past that slot, and a skipped write would
            # leave a permanent zero-KV hole every later draft query
            # attends (silently collapsing acceptance rates; outputs
            # would stay correct, which is why only this comment and
            # the hole test notice). The k+1-th PROPOSAL is discarded.
            (d_caches, _), d = jax.lax.scan(
                dstep, (d_caches, cur), jnp.arange(k + 1))
            d = d.T[:, :k]                             # [B, k]
            window = jnp.concatenate([cur[:, None], d], axis=1)
            t_caches, lg = _decode_window(tgt, t_caches, window, pos0,
                                          cfg, tp_axis=tp_axis,
                                          ep_axis=ep_axis,
                                          ep_size=ep_size)
            t = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # [B, k+1]
            # longest all-rows-agree prefix; +1 bonus from the target.
            # Every EMITTED token is t[:, j]: for j < a the draft
            # agreed (d == t there by definition of a), at j == a it is
            # the target's correction — so the scatter writes t itself.
            matches = (d == t[:, :k]).astype(jnp.int32)
            a = jnp.cumprod(matches, axis=1).sum(axis=1).min()
            out, cur, m = _accept_scatter(out, m, a, t, k, max_new)
            return (m, cur, out, t_caches, d_caches, rounds + 1)

        m0, r0 = jnp.asarray(1), jnp.asarray(0)
        if mesh is not None:
            # per-dp-shard loop state (trip counts may diverge)
            m0, r0 = _pvary(m0, ("dp",)), _pvary(r0, ("dp",))
        carry = (m0, tok0, out, t_caches, d_caches, r0)
        fin = jax.lax.while_loop(cond, body, carry)
        toks = fin[2] if eos_id is None else _pin_after_eos(fin[2],
                                                            eos_id)
        # rounds = target window forwards run: the efficiency metric —
        # a healthy draft takes ~ceil((max_new-1)/(k+1)), a degraded
        # one (e.g. a KV hole) collapses toward max_new-1. Sharded:
        # reported per ROW (each row carries its dp shard's count).
        if not return_stats:
            return toks
        rounds = fin[5]
        if mesh is not None:
            rounds = jnp.broadcast_to(rounds, (b_local,))
        return toks, rounds

    ck = ("spec_gen", cfg, draft_cfg, b, plen, max_new, k, mesh,
          eos_id, return_stats, _tree_key(params),
          _tree_key(draft_params))
    if mesh is None:
        prog = _cached_program(ck, lambda: jax.jit(run))
        return prog(params, draft_params, prompt)

    from jax.sharding import NamedSharding
    data_spec = P("dp", None)

    def build():
        pspecs = _decode_pspecs(params, cfg, mesh)
        dspecs = jax.tree.map(lambda _: P(), draft_params)
        out_spec = (data_spec, P("dp")) if return_stats else data_spec
        return jax.jit(shard_map(
            run, mesh=mesh,
            in_specs=(pspecs, dspecs, data_spec),
            out_specs=out_spec))

    prog = _cached_program(ck, build)
    prompt = jax.device_put(prompt, NamedSharding(mesh, data_spec))
    return prog(params, draft_params, prompt)



def speculative_sample(params, cfg: TransformerConfig,
                       draft_params, draft_cfg: TransformerConfig,
                       prompt: jax.Array, max_new: int = 32,
                       k: int = 4, temperature: float = 1.0,
                       key: Optional[jax.Array] = None,
                       eos_id: Optional[int] = None,
                       return_stats: bool = False) -> jax.Array:
    """SAMPLED speculative decoding — the exact acceptance-rejection
    algorithm (speculative sampling): draft j proposes d_j ~ q_j, the
    target scores the window in one forward, d_j is accepted with
    probability min(1, p_j(d_j)/q_j(d_j)), and the first rejection
    resamples from norm(relu(p_a - q_a)). The emitted sequence is
    distributed EXACTLY as sampling the target alone (the residual
    construction cancels the draft's bias; with q padded to zero past
    the proposals, the all-accepted bonus draw from p_k is the same
    formula). Each round folds its round index into the PRNG key, so a
    position redrafted after a rejection gets FRESH randomness — key
    reuse across rounds would correlate draws and break exactness.

    Single device, batch == 1 (the latency-sensitive single-stream
    case: per-row acceptance counts would need per-row cache
    positions). Greedy/batched/sharded speculation: see
    speculative_generate."""
    if key is None:
        raise ValueError("speculative_sample needs a PRNG key")
    if temperature <= 0.0:
        raise ValueError(
            "speculative_sample is the sampled algorithm; temperature "
            "must be > 0 (greedy: speculative_generate)")
    if prompt.shape[0] != 1:
        raise ValueError(
            f"speculative_sample is single-stream (batch == 1); got "
            f"batch {prompt.shape[0]}")
    if k < 1:
        raise ValueError(f"speculative_sample: k must be >= 1, got {k}")
    if draft_cfg.vocab != cfg.vocab:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab} != target vocab {cfg.vocab}")
    if max_new <= 0:
        empty = prompt[:, :0].astype(jnp.int32)
        return (empty, 0) if return_stats else empty

    plen = prompt.shape[1]
    smax = plen + max_new + k
    V = cfg.vocab

    def fresh(c: TransformerConfig):
        return [(jnp.zeros((1, smax, c.kv_heads, c.head_dim), c.dtype),
                 jnp.zeros((1, smax, c.kv_heads, c.head_dim), c.dtype))
                for _ in range(c.n_layers)]

    def probs(logits):
        return jax.nn.softmax(logits.astype(jnp.float32) / temperature,
                              axis=-1)

    def run(tgt, dft, prompt, karg):
        t_caches, t_last = _prefill_window(
            tgt, cfg, fresh(cfg), prompt,
            logits0=jnp.zeros((1, V), jnp.float32))
        d_caches, _ = _prefill_window(dft, draft_cfg, fresh(draft_cfg),
                                      prompt, need_logits=False)
        tok0 = jax.random.categorical(
            jax.random.fold_in(karg, 0),
            t_last[0] / temperature).astype(jnp.int32)[None]
        out = jnp.zeros((1, max_new), jnp.int32).at[:, 0].set(tok0)

        def cond(carry):
            return carry[0] < max_new

        def body(carry):
            m, cur, out, t_caches, d_caches, rounds = carry
            pos0 = plen + m - 1
            kr = jax.random.fold_in(karg, rounds + 1)  # fresh per round

            def dstep(c, j):
                dc, tok = c
                dc, lg = _decode_forward(dft, dc, tok, pos0 + j,
                                         draft_cfg)
                nxt = jax.random.categorical(
                    jax.random.fold_in(jax.random.fold_in(kr, 1), j),
                    lg[0] / temperature).astype(jnp.int32)[None]
                return (dc, nxt), (nxt, lg)

            # k+1 steps: the extra one lands d_{k-1}'s KV (see
            # speculative_generate's KV-hole note); its proposal and
            # logits are discarded
            (d_caches, _), (dtoks, dlogits) = jax.lax.scan(
                dstep, (d_caches, cur), jnp.arange(k + 1))
            d = dtoks[:k, 0]                           # [k]
            q = probs(dlogits[:k, 0])                  # [k, V]

            window = jnp.concatenate([cur[:, None], d[None, :]], axis=1)
            t_caches, lg = _decode_window(tgt, t_caches, window, pos0,
                                          cfg)
            p = probs(lg[0])                           # [k+1, V]

            pd = p[jnp.arange(k), d]
            qd = q[jnp.arange(k), d]
            u = jax.random.uniform(jax.random.fold_in(kr, 2), (k,))
            accept = u < jnp.minimum(1.0, pd / qd)
            a = jnp.where(accept.all(), k,
                          jnp.argmin(accept))         # first rejection
            # rejection resample from norm(relu(p_a - q_a)); with q
            # padded to a zero row at k, a == k (all accepted) makes
            # the SAME formula the bonus draw from p_k
            q_pad = jnp.concatenate([q, jnp.zeros((1, V))], axis=0)
            resid = jnp.maximum(p[a] - q_pad[a], 0.0)
            z = resid.sum()
            dist = jnp.where(z > 0, resid / jnp.maximum(z, 1e-30), p[a])
            e_a = jax.random.categorical(
                jax.random.fold_in(kr, 3),
                jnp.log(dist)).astype(jnp.int32)
            d_pad = jnp.concatenate([d, jnp.zeros(1, jnp.int32)])
            emis = jnp.where(jnp.arange(k + 1) < a, d_pad, e_a)
            out, cur, m = _accept_scatter(out, m, a, emis[None, :], k,
                                          max_new)
            return (m, cur, out, t_caches, d_caches, rounds + 1)

        carry = (jnp.asarray(1), tok0, out, t_caches, d_caches,
                 jnp.asarray(0))
        fin = jax.lax.while_loop(cond, body, carry)
        toks = fin[2] if eos_id is None else _pin_after_eos(fin[2],
                                                            eos_id)
        return (toks, fin[5]) if return_stats else toks

    ck = ("spec_sample", cfg, draft_cfg, plen, max_new, k, temperature,
          eos_id, return_stats, _tree_key(params),
          _tree_key(draft_params))
    prog = _cached_program(ck, lambda: jax.jit(run))
    return prog(params, draft_params, prompt, key)


def beam_search(params, cfg: TransformerConfig, prompt: jax.Array,
                max_new: int = 32, beam_width: int = 4,
                return_all: bool = False):
    """Beam-search decode (single device): keep the beam_width highest
    total-log-probability continuations per row. Static shapes: the
    prompt prefills once at batch B, then beams run flat at B*W with
    per-step cache reordering (gather by surviving parent). Returns
    the best [B, max_new] sequences, or (tokens [B, W, max_new],
    scores [B, W]) sorted best-first when return_all.

    beam_width=1 reproduces greedy decode exactly. No eos handling —
    beams run to max_new (finished-hypothesis freezing composes with
    this scheme but is not wired)."""
    if beam_width < 1:
        raise ValueError("beam_width >= 1")
    b, plen = prompt.shape
    w = beam_width
    smax = plen + max_new
    hd = cfg.head_dim

    def run(params, prompt):
        nkv = cfg.kv_heads
        caches = [(jnp.zeros((b, smax, nkv, hd), cfg.dtype),
                   jnp.zeros((b, smax, nkv, hd), cfg.dtype))
                  for _ in range(cfg.n_layers)]

        caches, logits = _prefill_window(
            params, cfg, caches, prompt,
            logits0=jnp.zeros((b, cfg.vocab), jnp.float32))

        # tile beams: all start identical; only beam 0 is live so the
        # duplicates can't multiply into the topk
        caches = jax.tree.map(lambda a: jnp.repeat(a, w, axis=0), caches)
        scores = jnp.full((b, w), -jnp.inf).at[:, 0].set(0.0)
        logits = jnp.repeat(logits, w, axis=0)          # [B*W, V]
        hist = jnp.zeros((b, w, max_new), jnp.int32)

        def step(carry, t):
            caches, scores, hist, logits = carry
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32)).reshape(b, w, cfg.vocab)
            cand = scores[:, :, None] + logp            # [B, W, V]
            top, idx = jax.lax.top_k(cand.reshape(b, -1), w)
            parent = idx // cfg.vocab                   # [B, W]
            tok = (idx % cfg.vocab).astype(jnp.int32)
            flat_parent = (jnp.arange(b)[:, None] * w + parent
                           ).reshape(-1)
            caches = jax.tree.map(lambda a: a[flat_parent], caches)
            hist = jnp.take_along_axis(hist, parent[..., None], axis=1)
            hist = jax.lax.dynamic_update_index_in_dim(
                hist, tok, t, axis=2)
            caches, logits = _decode_forward(
                params, caches, tok.reshape(-1), plen + t, cfg)
            return (caches, top, hist, logits), None

        (caches, scores, hist, _), _ = jax.lax.scan(
            step, (caches, scores, hist, logits), jnp.arange(max_new))
        order = jnp.argsort(-scores, axis=1)
        hist = jnp.take_along_axis(hist, order[..., None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
        return hist, scores

    ck = ("beam", cfg, b, plen, max_new, w, _tree_key(params))
    prog = _cached_program(ck, lambda: jax.jit(run))
    hist, scores = prog(params, prompt)
    if return_all:
        return hist, scores
    return hist[:, 0, :]


def make_opt_state(params, cfg: TransformerConfig, mesh, optimizer: Any):
    """optimizer.init under jit with sharded outputs matching
    _opt_state_specs (so moments are tp-sharded like their weights)."""
    from jax.sharding import NamedSharding
    ospecs = _opt_state_specs(cfg, optimizer)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, P))
    return jax.jit(optimizer.init, out_shardings=shardings)(params)
