from .fnkey import fn_cache_key  # noqa: F401
