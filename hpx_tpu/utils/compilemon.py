"""Count XLA backend compiles via jax.monitoring.

The serving program-cache work (bucketed prefill) is ultimately about
COMPILES, not dict hits — so tests and benchmarks measure the real
thing: jax emits a ``/jax/core/compile/backend_compile_duration``
event for every backend compilation, and `count_compiles` tallies
them over a region.

One process-wide listener is registered on first use and never
removed (jax.monitoring has no unregister API); it fans out to a
stack of active counters, so nested regions each see their own
tally. Note the event fires for EVERY backend compile in the
process — including first-touch eager ops and other threads — so
assertions over a region should either warm unrelated paths first or
allow a small constant slack.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, List

__all__ = ["count_compiles"]

_lock = threading.Lock()
_installed = False
_active: List["_Tally"] = []


class _Tally:
    """Mutable compile counter handed to the caller; reads as int."""

    def __init__(self) -> None:
        self.count = 0

    def __int__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return f"_Tally(count={self.count})"


def _listener(event: str, duration: float, **kwargs) -> None:
    if "backend_compile" not in event:
        return
    with _lock:
        for t in _active:
            t.count += 1


def _install() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax
    jax.monitoring.register_event_duration_secs_listener(_listener)


@contextlib.contextmanager
def count_compiles() -> Iterator[_Tally]:
    """``with count_compiles() as c: ...; int(c)`` — backend compiles
    that happened inside the region (process-wide)."""
    _install()
    tally = _Tally()
    with _lock:
        _active.append(tally)
    try:
        yield tally
    finally:
        with _lock:
            _active.remove(tally)
