"""Version-bridging imports for the jax API surface the runtime uses.

The library targets current jax (`jax.shard_map` is public API since
0.6), but CI sandboxes and TPU pods pin older wheels where the same
function lives at `jax.experimental.shard_map.shard_map`. Importing
through this module keeps every subsystem collectable on both — an
ImportError at module scope would otherwise take out the whole
models/ops import chain (and with it every test in those files) on an
older pin. Semantics-level differences (e.g. `jax.lax.pvary` not
existing before varying-manual-axes tracking) stay guarded at the call
sites with hasattr, as `ops.attention._pvary` does.
"""

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:            # older pins keep it in experimental
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, **kwargs):
        # The experimental version's check_rep pass infers replication
        # statically and REJECTS programs whose replicated out_specs it
        # cannot prove (e.g. psum-closed grads inside a scanned train
        # step). Modern jax tracks varying axes through the program
        # instead and accepts them, and every caller here was written
        # against that behavior — so default the legacy check off
        # rather than fail closed on valid programs.
        kwargs.setdefault("check_rep", False)
        return _shard_map(f, **kwargs)

__all__ = ["shard_map"]
