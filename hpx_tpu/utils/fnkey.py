"""Structural cache keys for user callables.

Why: algorithm call sites pass fresh lambda objects each call
(`hpx.transform(pol, x, lambda v: a*v+b, y)` in a loop). Keying the jit
cache on object identity would recompile the XLA program every iteration —
the difference between ~0.5 s and ~0.5 ms per call. This key treats two
functions as equal when they have the same code object, the same
(hashable) closure-cell values and defaults, recursing into captured
functions.

Caching semantics match jax.jit's: changes to *globals* read inside the
function are not part of the key (jit has the same behavior — the trace
is cached). Unhashable or exotic captures fall back to identity keying,
which is always correct, merely slower.
"""

from __future__ import annotations

import types
from typing import Any, Hashable

_SCALARS = (int, float, complex, bool, str, bytes, type(None))


def fn_cache_key(f: Any, _depth: int = 0) -> Hashable:
    if _depth > 4 or not isinstance(f, types.FunctionType):
        return f
    vals = []
    for cell in f.__closure__ or ():
        try:
            v = cell.cell_contents
        except ValueError:  # empty cell
            return f
        if isinstance(v, _SCALARS):
            vals.append((type(v).__name__, v))
        elif isinstance(v, (types.BuiltinFunctionType, type)):
            vals.append(v)  # builtins (operator.add, ...) and classes are
            # stable singletons — hashable by identity
        elif isinstance(v, types.FunctionType):
            k = fn_cache_key(v, _depth + 1)
            if k is v:
                return f  # captured fn not structurally keyable
            vals.append(k)
        elif isinstance(v, types.ModuleType):
            vals.append(("module", v.__name__))
        elif isinstance(v, tuple) and all(isinstance(x, _SCALARS) for x in v):
            vals.append(("tuple", v))
        else:
            return f  # mutable/unhashable capture: identity key
    defaults = f.__defaults__
    if defaults is not None and not all(
            isinstance(d, _SCALARS) for d in defaults):
        return f
    kwdefaults = f.__kwdefaults__
    if kwdefaults is not None:
        if not all(isinstance(d, _SCALARS) for d in kwdefaults.values()):
            return f
        kwdefaults = tuple(sorted(kwdefaults.items()))
    return (f.__code__, tuple(vals), defaults, kwdefaults)
