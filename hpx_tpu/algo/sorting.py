"""Sorting and order ops: sort, stable_sort, is_sorted, merge, rotate,
reverse, unique, partition.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{sort,is_sorted,merge,rotate,reverse,unique,partition}.hpp (parallel
quicksort/merge). Device lowering: XLA's sort (bitonic-style network) via
jnp.sort/argsort — the compiler's sort IS the parallel sort.
"""

from __future__ import annotations

import functools
import operator
from typing import Any, Callable, Optional

from ..exec.policies import ExecutionPolicy
from ._core import (
    device_executor,
    finish,
    is_device_policy,
    to_numpy_view,
)


_SHARDED_SORT_PROGRAMS: dict = {}

# jitted per-element key programs, weakly keyed by the user's key
# function so repeated sorts with the same (named) key reuse one
# executable; inline lambdas are new objects per call and simply miss
import weakref

_KEY_PROGRAMS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _sharded_axis(a) -> Optional[tuple]:
    """(mesh, axis) when `a` is a jax.Array sharded in contiguous
    chunks over one axis of a 1-D mesh; else None."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec
        sh = getattr(a, "sharding", None)
        if not isinstance(sh, NamedSharding):
            return None
        mesh = sh.mesh
        if len(mesh.axis_names) != 1 or mesh.size <= 1:
            return None
        axis = mesh.axis_names[0]
        if sh.spec != PartitionSpec(axis) or a.ndim != 1:
            return None
        if a.shape[0] % mesh.size:
            return None
        return mesh, axis
    except Exception:  # noqa: BLE001
        return None


def _build_odd_even(mesh, axis: str):
    """Odd-even transposition on blocks: p rounds of pairwise ppermute
    exchange + merge-split (lower-index partner keeps the low half) —
    the classic result that p merge-split phases over p locally sorted
    blocks sort globally. O(p) collective rounds: right shape at small
    p (cheap rounds, no capacity padding), wrong shape at pod scale."""
    import jax
    import jax.numpy as jnp
    from ..utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    p = mesh.shape[axis]

    def body(chunk):
        local = jnp.sort(chunk)
        idx = jax.lax.axis_index(axis)
        for r in range(p):
            # round parity picks the pairing: (0,1)(2,3)… then
            # (1,2)(3,4)…; partner = idx±1 by idx parity
            if r % 2 == 0:
                pairs = [(i, i + 1) for i in range(0, p - 1, 2)]
            else:
                pairs = [(i, i + 1) for i in range(1, p - 1, 2)]
            perm = [(a, b) for a, b in pairs] + \
                   [(b, a) for a, b in pairs]
            paired = jnp.zeros((), jnp.bool_)
            lower = jnp.zeros((), jnp.bool_)
            for a, b in pairs:
                paired = paired | (idx == a) | (idx == b)
                lower = lower | (idx == a)
            recv = jax.lax.ppermute(local, axis, perm)
            both = jnp.sort(jnp.concatenate([local, recv]))
            m = local.shape[0]
            keep = jnp.where(lower, both[:m], both[m:])
            local = jnp.where(paired, keep, local)
        return local

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis)))


def _sort_key_fns(dt):
    """(to_key, from_key, key_dtype): a TOTAL-ORDER integer key per
    value dtype, so the sample sort's comparisons/padding never meet
    IEEE partial order. Floats use the classic sign-flip bitcast
    (negatives bit-inverted, positives sign-bit-set → unsigned order
    == numeric order), with every NaN forced to the key-space max so
    NaNs sort last exactly like jnp.sort/np.sort (payloads collapse to
    one canonical NaN on the way back). Ints/bools are their own key."""
    import jax
    import jax.numpy as jnp

    if jnp.issubdtype(dt, jnp.integer):
        return (lambda v: v), (lambda k: k), dt
    if dt == jnp.bool_:
        return (lambda v: v.astype(jnp.uint8)), \
               (lambda k: k.astype(jnp.bool_)), jnp.dtype(jnp.uint8)
    if not jnp.issubdtype(dt, jnp.floating):
        raise TypeError(f"sort_sharded: unsupported dtype {dt}")
    nbits = jnp.dtype(dt).itemsize * 8
    ui = jnp.dtype(f"uint{nbits}")
    sign = ui.type(1 << (nbits - 1))
    allbits = ui.type((1 << nbits) - 1)

    def to_key(v):
        u = jax.lax.bitcast_convert_type(v, ui)
        k = jnp.where((u & sign) != 0, ~u, u | sign)
        return jnp.where(jnp.isnan(v), allbits, k)

    def from_key(k):
        u = jnp.where((k & sign) != 0, k ^ sign, ~k)
        return jax.lax.bitcast_convert_type(u.astype(ui), dt)

    return to_key, from_key, ui


def _transport_fns(dt):
    """(encode, decode, wire_dtype): lossless BIT transport of any
    fixed-width dtype as unsigned ints (the by-key payload path — the
    payload is moved, never compared; integer wire format keeps the
    final zero-identity sum-scatter exact)."""
    import jax
    import jax.numpy as jnp
    if dt == jnp.bool_:
        return (lambda x: x.astype(jnp.uint8)), \
               (lambda u: u.astype(jnp.bool_)), jnp.dtype(jnp.uint8)
    nbits = jnp.dtype(dt).itemsize * 8
    ui = jnp.dtype(f"uint{nbits}")
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return (lambda x: x), (lambda u: u), jnp.dtype(dt)
    return (lambda x: jax.lax.bitcast_convert_type(x, ui)), \
           (lambda u: jax.lax.bitcast_convert_type(u, dt)), ui


def _build_sample_sort(mesh, axis: str, with_payload: bool = False):
    """One-shot sample sort (PSRS — parallel sorting by regular
    sampling): local sort → rank-stripe all_to_all → regular-sample
    splitters via all_gather → ONE bucket all_to_all → local merge →
    exact-rank rebalance all_to_all. O(1) collective steps regardless
    of p (vs odd-even's p rounds) — the pod-scale shape.

    Correctness under duplicates and static shapes, the two things XLA
    makes hard:

    * Every element carries a lexicographic key (value, global_id), so
      keys are DISTINCT and the PSRS bucket bound B_j < 2M (M = padded
      chunk length) is a theorem, not a hope — all-equal inputs
      bucket by id and stay balanced.
    * The rank-stripe pre-exchange (element of local sorted rank r
      moves to device r mod p) makes each device's chunk a union of
      p regular subsamples of sorted chunks. A bucket is a contiguous
      key interval, and a stride-p subsample of a contiguous run of
      length L contains at most L/p + 1 elements, so the per-pair
      send in the bucket exchange is <= B_j/p + p < 2M/p + p — a
      STATIC capacity, so the all_to_all buffer is (p, 2M/p + p + 2)
      instead of the worst-case (p, M) a one-shot exchange would
      otherwise need.
    * Buckets land whole on their device with sizes b_j != m, so a
      final exchange places every element at its exact global rank g
      (device g//m, slot g%m; ranks from an all_gather of bucket
      sizes): output is exactly m per device, same sharding in as out.

    Values travel as total-order integer keys (_sort_key_fns: floats
    sign-flip-bitcast so unsigned order == numeric order with NaN
    forced last like np.sort; ints/bools are their own key), which
    also makes padding trivial: (key-space max, id >= n) sorts after
    every real key, takes ranks >= n, and is dropped by the final
    scatter's mode='drop'. NOT stable (equal values reorder by global
    id, which for distributed duplicates is original-position order —
    but the public contract stays "unstable"; stable_sort keeps the
    XLA path). NaN payloads collapse to one canonical NaN.

    with_payload=True builds the BY-KEY variant: the program takes
    (keys, values) and returns values reordered by ascending key. The
    payload rides every exchange under the same permutations (as its
    own total-order-key transport, so the sum-scatter trick still
    works), and the gid tiebreak makes this one STABLE — equal keys
    keep original global order.
    """
    import jax
    import jax.numpy as jnp
    from ..utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    p = mesh.shape[axis]

    def body(chunk, payload=None):
        m = chunk.shape[0]
        n = m * p
        to_key, from_key, kdt = _sort_key_fns(chunk.dtype)
        kmax = jnp.iinfo(kdt).max
        i = jax.lax.axis_index(axis)

        mp_ = -(-m // p)               # ceil(m/p)
        M = mp_ * p
        pad = M - m
        # ids/ranks span [0, n + p*pad): int32 until ~2^31 elements,
        # int64 beyond (needs x64; wrapped ids would break the
        # distinct-(key,gid) property the capacity bound rests on)
        if n + p * pad < 2 ** 31:
            idt = jnp.int32
        elif jax.config.jax_enable_x64:
            idt = jnp.int64
        else:
            raise ValueError(
                f"sort_sharded(sample): n={n} needs 64-bit ids; "
                "enable jax x64 or use method='odd_even'")
        # widen the device index BEFORE the product: i*m in int32 wraps
        # at the very scale the int64 path exists for
        gid = i.astype(idt) * m + jnp.arange(m, dtype=idt)
        v = to_key(chunk)              # total-order integer keys
        if payload is not None:
            # plain BIT transport (never compared): lossless for any
            # fixed-width dtype incl. NaN payload bits, and integer so
            # the final zero-identity sum-scatter stays exact
            to_pk, from_pk, pdt = _transport_fns(payload.dtype)
            w = to_pk(payload)
        if pad:
            v = jnp.concatenate([v, jnp.full((pad,), kmax, kdt)])
            gid = jnp.concatenate(
                [gid, jnp.asarray(n, idt) + i.astype(idt) * pad
                 + jnp.arange(pad, dtype=idt)])
            if payload is not None:
                w = jnp.concatenate([w, jnp.zeros((pad,), pdt)])

        a2a = functools.partial(jax.lax.all_to_all, axis_name=axis,
                                split_axis=0, concat_axis=0, tiled=True)

        def stripe(arr):
            return a2a(arr.reshape(mp_, p).T.reshape(p, mp_)).reshape(M)

        # ---- phase A: local sort + rank stripe (balances bucket
        # composition across sources; per-pair volume exactly M/p)
        order = jnp.lexsort((gid, v))
        v, gid = v[order], gid[order]
        if payload is not None:
            w = stripe(w[order])
        v, gid = stripe(v), stripe(gid)
        order = jnp.lexsort((gid, v))
        v, gid = v[order], gid[order]
        if payload is not None:
            w = w[order]

        # ---- phase B: p regular samples/device -> p^2 gathered ->
        # splitters at every p-th (p-1 of them)
        sv = jax.lax.all_gather(v[0::mp_][:p], axis).reshape(-1)
        sg = jax.lax.all_gather(gid[0::mp_][:p], axis).reshape(-1)
        sorder = jnp.lexsort((sg, sv))
        sv, sg = sv[sorder], sg[sorder]
        sv, sg = sv[p::p][:p - 1], sg[p::p][:p - 1]

        # ---- phase C: bucket by splitter count (lexicographic), ONE
        # capacity-bounded all_to_all
        less = (sv[None, :] < v[:, None]) | (
            (sv[None, :] == v[:, None]) & (sg[None, :] <= gid[:, None]))
        dest = less.sum(axis=1).astype(jnp.int32)          # (M,) in [0,p)
        counts = jnp.bincount(dest, length=p).astype(jnp.int32)
        cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(counts)[:-1]])
        off = jnp.arange(M, dtype=jnp.int32) - cum[dest]   # dest is sorted
        cap = 2 * mp_ + p + 2                              # PSRS bound + slack
        bv = jnp.zeros((p, cap), kdt).at[dest, off].set(v, mode="drop")
        bg = jnp.full((p, cap), jnp.iinfo(idt).max,
                      idt).at[dest, off].set(gid, mode="drop")
        rv = a2a(bv).reshape(-1)
        rg = a2a(bg).reshape(-1)
        rc = a2a(counts.reshape(p, 1)).reshape(p)          # per-src counts
        if payload is not None:
            bw = jnp.zeros((p, cap), pdt).at[dest, off].set(
                w, mode="drop")
            rw = a2a(bw).reshape(-1)

        # ---- local merge of my bucket (invalid slots sort last)
        invalid = (jnp.arange(cap, dtype=jnp.int32)[None, :]
                   >= rc[:, None]).reshape(-1)
        order = jnp.lexsort((rg, rv, invalid))
        rv, rg = rv[order], rg[order]
        if payload is not None:
            rw = rw[order]
        b_mine = rc.sum()

        # ---- phase D: exact global rank -> (device, slot) scatter.
        # bucket sizes all_gather'd; padding keys rank >= n and invalid
        # slots get dest p — both dropped by mode='drop'.
        sizes = jax.lax.all_gather(b_mine, axis).astype(idt)   # (p,)
        base = jnp.concatenate([jnp.zeros(1, idt),
                                jnp.cumsum(sizes)[:-1]])[i]
        pos = jnp.arange(p * cap, dtype=idt)
        grank = base + pos
        d2 = jnp.where((pos < b_mine) & (grank < n), grank // m, p)
        o2 = grank % m
        # exactly one source owns each global rank, empty slots are 0
        if payload is None:
            out = jnp.zeros((p, m), kdt).at[d2, o2].set(rv, mode="drop")
            return from_key(a2a(out).sum(axis=0))
        pout = jnp.zeros((p, m), pdt).at[d2, o2].set(rw, mode="drop")
        return from_pk(a2a(pout).sum(axis=0))

    if with_payload:
        return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(P(axis), P(axis)),
                                 out_specs=P(axis)))
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis)))


def sort_sharded(v: Any, mesh, axis: str = "x",
                 method: Optional[str] = None) -> Any:
    """Globally sort a 1-D array sharded over `axis` WITHOUT gathering.

    Two compiled strategies (reference analog: the segmented sort over
    partitioned data, SURVEY.md §2.4 segmented_algorithms):

    * ``sample``  — one-shot PSRS sample sort: O(1) all_to_all steps
      independent of mesh size (see _build_sample_sort). Default for
      p > 4: at pod scale, collective-step count is what matters.
    * ``odd_even`` — p rounds of neighbor merge-split. Default for
      p <= 4 where its simplicity and lack of capacity padding win.

    Both are fully compiled (static shapes, XLA collectives over ICI)
    and NOT stable; stable_sort keeps the XLA gather path."""
    p = mesh.shape[axis]
    if method is None:
        method = "odd_even" if p <= 4 else "sample"
    elif method not in ("sample", "odd_even"):
        raise ValueError(f"sort_sharded: unknown method {method!r} "
                         "(expected 'sample' or 'odd_even')")
    from ..core.programs import cached_program
    build = (_build_sample_sort if method == "sample"
             else _build_odd_even)
    prog = cached_program(_SHARDED_SORT_PROGRAMS, (method, mesh, axis),
                          lambda: build(mesh, axis))
    return prog(v)


def sort_sharded_by_key(keys: Any, values: Any, mesh,
                        axis: str = "x") -> Any:
    """Reorder a sharded 1-D `values` by ascending sharded `keys`
    WITHOUT gathering — the PSRS sample sort with the values riding
    every exchange as payload (lossless bit transport — payload NaN
    bit patterns survive). STABLE: the global-id tiebreak preserves
    original order for equal keys."""
    from ..core.programs import cached_program
    prog = cached_program(
        _SHARDED_SORT_PROGRAMS, ("sample_by_key", mesh, axis),
        lambda: _build_sample_sort(mesh, axis, with_payload=True))
    return prog(keys, values)


def sort(policy: ExecutionPolicy, rng: Any,
         key: Optional[Callable] = None) -> Any:
    """Returns the sorted range. `key` maps elements to sort keys
    (HPX's comparator generalized to the key form jax supports).
    A range sharded over a 1-D mesh sorts DISTRIBUTED — with or
    without a key — through the segmented-algorithms sort
    (sort_sharded / sort_sharded_by_key: no gather, O(1) collective
    steps on the sample path)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        sharded = _sharded_axis(rng)
        if sharded:
            mesh, axis = sharded
            if key is None:
                dispatch = lambda a: sort_sharded(a, mesh, axis)  # noqa: E731
            else:
                kp = _KEY_PROGRAMS.get(key)
                if kp is None:
                    kp = jax.jit(jax.vmap(key))
                    try:
                        _KEY_PROGRAMS[key] = kp
                    except TypeError:
                        pass

                def dispatch(a, kp=kp):
                    # keys computed shard-locally (elementwise vmap
                    # keeps the input's sharding), then the by-key
                    # program reorders the values — stable, like the
                    # single-device stable-argsort path below
                    return sort_sharded_by_key(kp(a), a, mesh, axis)
            fut = ex.async_execute_raw(dispatch, rng) \
                if hasattr(ex, "async_execute_raw") else \
                ex.async_execute(dispatch, rng)
            return fut if policy.is_task else fut.get()

        def kernel(a):
            flat = a.reshape(-1)
            if key is None:
                return jnp.sort(flat)
            ks = jax.vmap(key)(flat)
            return flat[jnp.argsort(ks, stable=True)]
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if key is None:
            return np.sort(arr, kind="stable")
        ks = np.array([key(x) for x in arr])
        return arr[np.argsort(ks, kind="stable")]

    return finish(policy, run)


stable_sort = sort  # device sort with stable argsort; numpy kind="stable"


def is_sorted(policy: ExecutionPolicy, rng: Any) -> Any:
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: (a.reshape(-1)[1:] >= a.reshape(-1)[:-1]).all(), rng)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        return bool(np.all(arr[1:] >= arr[:-1]))

    return finish(policy, run)


def merge(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Merge two sorted ranges into one sorted range."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a, b: jnp.sort(jnp.concatenate(
                [a.reshape(-1), b.reshape(-1)])), rng, rng2)
        return fut if policy.is_task else fut.get()
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        return np.sort(np.concatenate([a, b]), kind="stable")

    return finish(policy, run)


def reverse(policy: ExecutionPolicy, rng: Any) -> Any:
    if is_device_policy(policy, rng):
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: a[::-1], rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)
    return finish(policy, lambda: arr[::-1].copy())


def rotate(policy: ExecutionPolicy, rng: Any, middle: int) -> Any:
    """Left-rotate so that rng[middle] becomes the first element."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: jnp.roll(a, -middle), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        return np.roll(arr, -middle)

    return finish(policy, run)


def unique(policy: ExecutionPolicy, rng: Any) -> Any:
    """Remove consecutive duplicates (std::unique semantics, shrunk).

    Output size is data-dependent: device path computes the keep-mask on
    device and compacts at the host boundary (static shapes under jit)."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        mask_fut = ex.async_execute(
            lambda a: jnp.concatenate(
                [jnp.ones(1, bool),
                 a.reshape(-1)[1:] != a.reshape(-1)[:-1]]), rng)

        def run():
            import numpy as np
            # hpxlint: disable-next=HPX002 — data-dependent compaction:
            # device computed the uniqueness mask; host gather builds
            # the dynamic-shape result
            mask = np.asarray(mask_fut.get())
            # hpxlint: disable-next=HPX002 — host gather (see above)
            return jnp.asarray(np.asarray(rng).reshape(-1)[mask])
        return finish(policy, run)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if len(arr) == 0:
            return arr.copy()
        mask = np.concatenate([[True], arr[1:] != arr[:-1]])
        return arr[mask]

    return finish(policy, run)


def partition(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    """Stable partition: satisfying elements first; returns (range,
    partition_point)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            m = jax.vmap(pred)(flat)
            # stable partition via stable argsort of negated mask
            order = jnp.argsort(~m, stable=True)
            return flat[order], m.sum()
        fut = ex.async_execute(kernel, rng)

        def done(f):
            arr2, point = f.get()
            return arr2, int(point)
        return fut.then(done) if policy.is_task else done(fut)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        mask = np.array([bool(pred(x)) for x in arr], dtype=bool)
        return np.concatenate([arr[mask], arr[~mask]]), int(mask.sum())

    return finish(policy, run)


def partial_sort(policy: ExecutionPolicy, rng: Any, middle: int) -> Any:
    """Rearrange so the smallest `middle` elements are first and sorted;
    the tail is unspecified (std::partial_sort). Device path lowers to
    the full XLA sort — on TPU the compiler's O(n log n) sort network is
    the parallel sort, and a sorted tail satisfies 'unspecified'; the
    host path does a real introselect + head sort."""
    if is_device_policy(policy, rng):
        return sort(policy, rng)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if middle <= 0:
            return arr.copy()
        if middle >= len(arr):
            return np.sort(arr, kind="stable")
        out = np.partition(arr, middle - 1)
        out[:middle] = np.sort(out[:middle], kind="stable")
        return out

    return finish(policy, run)


def partial_sort_copy(policy: ExecutionPolicy, rng: Any, k: int) -> Any:
    """The k smallest elements, sorted (std::partial_sort_copy with a
    length-k destination). Device path: lax.top_k on the negated range —
    O(n log k), never materializes a full sort when k << n."""
    k = max(0, min(k, len(rng)))
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            if k == 0:                         # static shapes
                return flat[:0]
            if not jnp.issubdtype(flat.dtype, jnp.floating):
                # integer/bool negation wraps (unsigned always, signed
                # at INT_MIN): take the sort-slice path
                return jnp.sort(flat)[:k]
            neg, _ = jax.lax.top_k(-flat, k)   # top_k descending on the
            return -neg                        # negation == ascending k-smallest
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if k == 0:
            return arr[:0].copy()
        if k >= len(arr):
            return np.sort(arr, kind="stable")
        return np.sort(np.partition(arr, k - 1)[:k], kind="stable")

    return finish(policy, run)


def nth_element(policy: ExecutionPolicy, rng: Any, n: int) -> Any:
    """Rearrange so position n holds the element that would be there in
    a full sort, with everything before it <= and after it >=
    (std::nth_element). Device path lowers to the full XLA sort (which
    satisfies the postcondition); host path is numpy's introselect."""
    if is_device_policy(policy, rng):
        return sort(policy, rng)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if not 0 <= n < len(arr):
            return arr.copy()
        return np.partition(arr, n)

    return finish(policy, run)


def shift_left(policy: ExecutionPolicy, rng: Any, n: int) -> Any:
    """Shift elements n positions toward the front; the vacated tail
    keeps its original values ('unspecified' per std::shift_left)."""
    if n <= 0:
        from .elementwise import copy as _copy
        return _copy(policy, rng)
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: a if n >= a.shape[0] else
            jnp.concatenate([a[n:], a[a.shape[0] - n:]]), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        out = arr.copy()
        if n < len(arr):
            out[:len(arr) - n] = arr[n:]
        return out

    return finish(policy, run)


def shift_right(policy: ExecutionPolicy, rng: Any, n: int) -> Any:
    """Shift elements n positions toward the back; the vacated head
    keeps its original values ('unspecified' per std::shift_right)."""
    if n <= 0:
        from .elementwise import copy as _copy
        return _copy(policy, rng)
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: a if n >= a.shape[0] else
            jnp.concatenate([a[:n], a[:a.shape[0] - n]]), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        out = arr.copy()
        if n < len(arr):
            out[n:] = arr[:len(arr) - n]
        return out

    return finish(policy, run)


def swap_ranges(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Exchange the contents of two equal-length ranges; returns the
    (new_rng, new_rng2) pair (std::swap_ranges in the functional data
    model: a swap IS returning the copies crossed over)."""
    from .elementwise import copy as _copy
    if len(rng) != len(rng2):
        raise ValueError("swap_ranges: ranges must have equal length")
    a2 = _copy(policy, rng2)
    b2 = _copy(policy, rng)
    if policy.is_task:
        from ..futures.combinators import when_all
        return when_all(a2, b2).then(
            lambda f: tuple(x.get() for x in f.get()))
    return a2, b2


def partition_copy(policy: ExecutionPolicy, rng: Any,
                   pred: Callable) -> Any:
    """(true_part, false_part) — the pred-satisfying elements and the
    rest, each in stable order (std::partition_copy as a pair return)."""
    res = partition(policy, rng, pred)

    def split(pair):
        arr2, point = pair
        return arr2[:point], arr2[point:]
    if policy.is_task:
        return res.then(lambda f: split(f.get()))
    return split(res)


def is_heap_until(policy: ExecutionPolicy, rng: Any) -> Any:
    """Index of the first element that breaks the max-heap property
    (a[(i-1)//2] >= a[i]), or len(rng) when the whole range is a heap
    (std::is_heap_until as an index). One vectorized parent-compare —
    the heap property is embarrassingly parallel."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            f = a.reshape(-1)
            n = f.shape[0]
            if n <= 1:                 # static shape
                return jnp.asarray(n)
            i = jnp.arange(1, n)
            bad = f[(i - 1) // 2] < f[i]
            return jnp.where(bad.any(), jnp.argmax(bad) + 1, n)
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        n = len(arr)
        if n <= 1:
            return n
        i = np.arange(1, n)
        bad = np.flatnonzero(arr[(i - 1) // 2] < arr[i])
        # (via to_numpy_view), no device sync happens here
        return int(bad[0]) + 1 if bad.size else n

    return finish(policy, run)


def is_heap(policy: ExecutionPolicy, rng: Any) -> Any:
    """True when the range is a max-heap (std::is_heap)."""
    res = is_heap_until(policy, rng)
    if policy.is_task:
        return res.then(lambda f: f.get() == len(rng))
    return res == len(rng)
