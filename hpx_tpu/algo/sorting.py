"""Sorting and order ops: sort, stable_sort, is_sorted, merge, rotate,
reverse, unique, partition.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{sort,is_sorted,merge,rotate,reverse,unique,partition}.hpp (parallel
quicksort/merge). Device lowering: XLA's sort (bitonic-style network) via
jnp.sort/argsort — the compiler's sort IS the parallel sort.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Optional

from ..exec.policies import ExecutionPolicy
from ._core import (
    device_executor,
    finish,
    is_device_policy,
    to_numpy_view,
)


_SHARDED_SORT_PROGRAMS: dict = {}


def _sharded_axis(a) -> Optional[tuple]:
    """(mesh, axis) when `a` is a jax.Array sharded in contiguous
    chunks over one axis of a 1-D mesh; else None."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec
        sh = getattr(a, "sharding", None)
        if not isinstance(sh, NamedSharding):
            return None
        mesh = sh.mesh
        if len(mesh.axis_names) != 1 or mesh.size <= 1:
            return None
        axis = mesh.axis_names[0]
        if sh.spec != PartitionSpec(axis) or a.ndim != 1:
            return None
        if a.shape[0] % mesh.size:
            return None
        return mesh, axis
    except Exception:  # noqa: BLE001
        return None


def sort_sharded(v: Any, mesh, axis: str = "x") -> Any:
    """Globally sort a 1-D array sharded over `axis` WITHOUT gathering:
    odd-even transposition on blocks. Each device sorts its chunk, then
    p rounds of pairwise ppermute exchange + merge-split (lower-index
    partner keeps the low half) — the classic result that p
    merge-split phases over p locally sorted blocks sort globally.
    Static shapes, compiled exchanges over ICI; O(p) rounds vs the
    all-gather XLA falls back to for sharded jnp.sort at scale. NOT
    stable (merge-split loses equal-key origin order) — stable_sort
    keeps the XLA path."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    p = mesh.shape[axis]

    def build():
        def body(chunk):
            local = jnp.sort(chunk)
            idx = jax.lax.axis_index(axis)
            for r in range(p):
                # round parity picks the pairing: (0,1)(2,3)… then
                # (1,2)(3,4)…; partner = idx±1 by idx parity
                if r % 2 == 0:
                    pairs = [(i, i + 1) for i in range(0, p - 1, 2)]
                else:
                    pairs = [(i, i + 1) for i in range(1, p - 1, 2)]
                perm = [(a, b) for a, b in pairs] + \
                       [(b, a) for a, b in pairs]
                paired = jnp.zeros((), jnp.bool_)
                lower = jnp.zeros((), jnp.bool_)
                for a, b in pairs:
                    paired = paired | (idx == a) | (idx == b)
                    lower = lower | (idx == a)
                recv = jax.lax.ppermute(local, axis, perm)
                both = jnp.sort(jnp.concatenate([local, recv]))
                m = local.shape[0]
                keep = jnp.where(lower, both[:m], both[m:])
                local = jnp.where(paired, keep, local)
            return local

        return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(axis),),
                                 out_specs=P(axis)))

    # one jit object per (mesh, axis): jit's own cache handles shapes
    key_ = ("oet", mesh, axis)
    prog = _SHARDED_SORT_PROGRAMS.get(key_)
    if prog is None:
        prog = _SHARDED_SORT_PROGRAMS[key_] = build()
    return prog(v)


def sort(policy: ExecutionPolicy, rng: Any,
         key: Optional[Callable] = None) -> Any:
    """Returns the sorted range. `key` maps elements to sort keys
    (HPX's comparator generalized to the key form jax supports).
    A range sharded over a 1-D mesh sorts DISTRIBUTED (sort_sharded:
    merge-exchange over ppermute; the segmented-algorithms sort)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        sharded = key is None and _sharded_axis(rng)
        if sharded:
            mesh, axis = sharded
            fut = ex.async_execute_raw(
                lambda a: sort_sharded(a, mesh, axis), rng) \
                if hasattr(ex, "async_execute_raw") else \
                ex.async_execute(lambda a: sort_sharded(a, mesh, axis),
                                 rng)
            return fut if policy.is_task else fut.get()

        def kernel(a):
            flat = a.reshape(-1)
            if key is None:
                return jnp.sort(flat)
            ks = jax.vmap(key)(flat)
            return flat[jnp.argsort(ks, stable=True)]
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if key is None:
            return np.sort(arr, kind="stable")
        ks = np.array([key(x) for x in arr])
        return arr[np.argsort(ks, kind="stable")]

    return finish(policy, run)


stable_sort = sort  # device sort with stable argsort; numpy kind="stable"


def is_sorted(policy: ExecutionPolicy, rng: Any) -> Any:
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: (a.reshape(-1)[1:] >= a.reshape(-1)[:-1]).all(), rng)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        return bool(np.all(arr[1:] >= arr[:-1]))

    return finish(policy, run)


def merge(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Merge two sorted ranges into one sorted range."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a, b: jnp.sort(jnp.concatenate(
                [a.reshape(-1), b.reshape(-1)])), rng, rng2)
        return fut if policy.is_task else fut.get()
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        return np.sort(np.concatenate([a, b]), kind="stable")

    return finish(policy, run)


def reverse(policy: ExecutionPolicy, rng: Any) -> Any:
    if is_device_policy(policy, rng):
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: a[::-1], rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)
    return finish(policy, lambda: arr[::-1].copy())


def rotate(policy: ExecutionPolicy, rng: Any, middle: int) -> Any:
    """Left-rotate so that rng[middle] becomes the first element."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: jnp.roll(a, -middle), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        return np.roll(arr, -middle)

    return finish(policy, run)


def unique(policy: ExecutionPolicy, rng: Any) -> Any:
    """Remove consecutive duplicates (std::unique semantics, shrunk).

    Output size is data-dependent: device path computes the keep-mask on
    device and compacts at the host boundary (static shapes under jit)."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        mask_fut = ex.async_execute(
            lambda a: jnp.concatenate(
                [jnp.ones(1, bool),
                 a.reshape(-1)[1:] != a.reshape(-1)[:-1]]), rng)

        def run():
            import numpy as np
            mask = np.asarray(mask_fut.get())
            return jnp.asarray(np.asarray(rng).reshape(-1)[mask])
        return finish(policy, run)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if len(arr) == 0:
            return arr.copy()
        mask = np.concatenate([[True], arr[1:] != arr[:-1]])
        return arr[mask]

    return finish(policy, run)


def partition(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    """Stable partition: satisfying elements first; returns (range,
    partition_point)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            m = jax.vmap(pred)(flat)
            # stable partition via stable argsort of negated mask
            order = jnp.argsort(~m, stable=True)
            return flat[order], m.sum()
        fut = ex.async_execute(kernel, rng)

        def done(f):
            arr2, point = f.get()
            return arr2, int(point)
        return fut.then(done) if policy.is_task else done(fut)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        mask = np.array([bool(pred(x)) for x in arr])
        return np.concatenate([arr[mask], arr[~mask]]), int(mask.sum())

    return finish(policy, run)
