"""Sorting and order ops: sort, stable_sort, is_sorted, merge, rotate,
reverse, unique, partition.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{sort,is_sorted,merge,rotate,reverse,unique,partition}.hpp (parallel
quicksort/merge). Device lowering: XLA's sort (bitonic-style network) via
jnp.sort/argsort — the compiler's sort IS the parallel sort.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Optional

from ..exec.policies import ExecutionPolicy
from ._core import (
    device_executor,
    finish,
    is_device_policy,
    to_numpy_view,
)


def sort(policy: ExecutionPolicy, rng: Any,
         key: Optional[Callable] = None) -> Any:
    """Returns the sorted range. `key` maps elements to sort keys
    (HPX's comparator generalized to the key form jax supports)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            if key is None:
                return jnp.sort(flat)
            ks = jax.vmap(key)(flat)
            return flat[jnp.argsort(ks, stable=True)]
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if key is None:
            return np.sort(arr, kind="stable")
        ks = np.array([key(x) for x in arr])
        return arr[np.argsort(ks, kind="stable")]

    return finish(policy, run)


stable_sort = sort  # device sort with stable argsort; numpy kind="stable"


def is_sorted(policy: ExecutionPolicy, rng: Any) -> Any:
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: (a.reshape(-1)[1:] >= a.reshape(-1)[:-1]).all(), rng)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        return bool(np.all(arr[1:] >= arr[:-1]))

    return finish(policy, run)


def merge(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Merge two sorted ranges into one sorted range."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a, b: jnp.sort(jnp.concatenate(
                [a.reshape(-1), b.reshape(-1)])), rng, rng2)
        return fut if policy.is_task else fut.get()
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        return np.sort(np.concatenate([a, b]), kind="stable")

    return finish(policy, run)


def reverse(policy: ExecutionPolicy, rng: Any) -> Any:
    if is_device_policy(policy, rng):
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: a[::-1], rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)
    return finish(policy, lambda: arr[::-1].copy())


def rotate(policy: ExecutionPolicy, rng: Any, middle: int) -> Any:
    """Left-rotate so that rng[middle] becomes the first element."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: jnp.roll(a, -middle), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        return np.roll(arr, -middle)

    return finish(policy, run)


def unique(policy: ExecutionPolicy, rng: Any) -> Any:
    """Remove consecutive duplicates (std::unique semantics, shrunk).

    Output size is data-dependent: device path computes the keep-mask on
    device and compacts at the host boundary (static shapes under jit)."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        mask_fut = ex.async_execute(
            lambda a: jnp.concatenate(
                [jnp.ones(1, bool),
                 a.reshape(-1)[1:] != a.reshape(-1)[:-1]]), rng)

        def run():
            import numpy as np
            mask = np.asarray(mask_fut.get())
            return jnp.asarray(np.asarray(rng).reshape(-1)[mask])
        return finish(policy, run)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if len(arr) == 0:
            return arr.copy()
        mask = np.concatenate([[True], arr[1:] != arr[:-1]])
        return arr[mask]

    return finish(policy, run)


def partition(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    """Stable partition: satisfying elements first; returns (range,
    partition_point)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            m = jax.vmap(pred)(flat)
            # stable partition via stable argsort of negated mask
            order = jnp.argsort(~m, stable=True)
            return flat[order], m.sum()
        fut = ex.async_execute(kernel, rng)

        def done(f):
            arr2, point = f.get()
            return arr2, int(point)
        return fut.then(done) if policy.is_task else done(fut)
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        mask = np.array([bool(pred(x)) for x in arr])
        return np.concatenate([arr[mask], arr[~mask]]), int(mask.sum())

    return finish(policy, run)
