"""Distributed FFT over a sharded axis: pencil decomposition on ICI.

Reference analog: HPX ships no FFT in-tree, but the distributed FFT
built from `hpx::collectives::all_to_all` over `partitioned_vector`
data is its published flagship collectives workload (SURVEY.md §6,
PAPERS.md arXiv:2504.03657 — scaling HPX collectives vs MPI for FFT).
The TPU-native form: the transpose steps are `lax.all_to_all` inside
one `shard_map`-jitted program, so XLA schedules the exchange over ICI
and fuses the twiddle multiply into the surrounding FFTs; the local
1-D transforms are XLA's native `fft` batched over the non-transformed
dimension (MXU/VPU friendly, no tag-matched messaging anywhere).

Two surfaces, matching collectives/device.py:
  * whole-array helpers (`fft2_sharded`, `fft_sharded`, and inverses):
    take a jax.Array sharded over a mesh axis, run ONE jitted program,
    return the result sharded the same way in natural order;
  * `fft2_body` / `fft1d_body` for user shard_map SPMD code.

1-D algorithm (Bailey four-step), derived for a row-major matrix view
A[n1, n2] = v[n1*N2 + n2] with N = N1*N2 and the vector sharded into
contiguous chunks (= whole rows of A):

    X[k2*N1 + k1] = FFT_axis1( FFT_axis0(A)[k1, n2] * w(k1, n2) )[k1, k2]
    with twiddle w(k1, n2) = exp(-2*pi*i * k1 * n2 / N)

so the schedule is: all_to_all (rows -> full columns), column FFTs,
twiddle, all_to_all back, row FFTs, and one final all_to_all + local
transpose to deliver natural-order output (skippable — see
`natural_order` — exactly like classic distributed FFTs that leave the
result bit-transposed for a later inverse to undo).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Tuple

__all__ = ["fft", "ifft", "fft2_sharded", "ifft2_sharded", "fft_sharded",
           "ifft_sharded", "fft2_sharded_2d", "ifft2_sharded_2d",
           "fft2_body", "fft1d_body"]


# ---------------------------------------------------------------------------
# in-body pieces (run inside an enclosing shard_map over `axis`)
# ---------------------------------------------------------------------------

def _a2a(x, axis: str, split: int, concat: int):
    from jax import lax
    return lax.all_to_all(x, axis, split_axis=split, concat_axis=concat,
                          tiled=True)


def fft2_body(a, axis: str, inverse: bool = False,
              natural_order: bool = True):
    """2-D FFT of a matrix row-sharded over `axis`; local shard
    [N0/P, N1]. Returns the row-sharded result (or column-sharded
    [N0, N1/P] when natural_order=False, saving one all_to_all)."""
    import jax.numpy as jnp
    f = jnp.fft.ifft if inverse else jnp.fft.fft
    a = f(a, axis=1)                       # rows are local: N1 FFTs
    a = _a2a(a, axis, split=1, concat=0)   # -> [N0, N1/P]
    a = f(a, axis=0)                       # full columns now local
    if natural_order:
        a = _a2a(a, axis, split=0, concat=1)   # -> [N0/P, N1]
    return a


def fft1d_body(a, axis: str, n_shards: int, n: int,
               inverse: bool = False, natural_order: bool = True):
    """Four-step 1-D FFT; `a` is the [N1/P, N2] row-major matrix view
    of this device's contiguous vector chunk. Returns the [N/P]-shaped
    natural-order chunk (or the [N1/P, N2] D-matrix when
    natural_order=False; undo with the matching inverse)."""
    import jax
    import jax.numpy as jnp

    f = jnp.fft.ifft if inverse else jnp.fft.fft
    n1 = a.shape[0] * n_shards
    n2 = a.shape[1]
    t = _a2a(a, axis, split=1, concat=0)       # [N1, N2/P]
    b = f(t, axis=0)
    idx = jax.lax.axis_index(axis)
    n2_loc = n2 // n_shards
    k1 = jnp.arange(n1)[:, None]
    n2g = idx * n2_loc + jnp.arange(n2_loc)[None, :]
    sign = 2.0 if inverse else -2.0
    # k1*n2g < N1*N2 = N: cast BEFORE the product — an int32 multiply
    # silently wraps for N >= 2^31 and would corrupt the spectrum, while
    # the float product merely loses ulps (f32 exact to N ~ 16M; f64
    # when x64 is on)
    ftype = jnp.float64 if b.dtype == jnp.complex128 else jnp.float32
    tw = jnp.exp((sign * jnp.pi / n) * 1j
                 * (k1.astype(ftype) * n2g.astype(ftype))).astype(b.dtype)
    c = b * tw
    d = f(_a2a(c, axis, split=0, concat=1), axis=1)   # [N1/P, N2]
    # ifft normalizes each local transform by its length; the composed
    # 1-D inverse needs exactly 1/N total: patch N1*N2 -> N (they are
    # equal, so nothing to patch — kept explicit for readers)
    if not natural_order:
        return d
    e = _a2a(d, axis, split=1, concat=0)       # [N1, N2/P]
    return jnp.swapaxes(e, 0, 1).reshape(-1)   # X[k2*N1+k1] chunk


# ---------------------------------------------------------------------------
# whole-array helpers (one cached jitted program per shape/mesh)
# ---------------------------------------------------------------------------

from ..core.programs import cached_program

_PROGRAMS: dict = {}


def _program(key, build):
    return cached_program(_PROGRAMS, key, build)


def _shard_prog(mesh, spec, body):
    import jax
    from ..utils.jaxcompat import shard_map
    if isinstance(spec, str):
        from jax.sharding import PartitionSpec as P
        spec = P(spec)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                             out_specs=spec))


def fft2_sharded(x: Any, mesh, axis: str = "x", inverse: bool = False):
    """2-D FFT of a [N0, N1] array sharded over rows (dim 0 on mesh
    axis `axis`); both dims' per-device extents must divide evenly.
    One jitted program: local row FFTs, all_to_all transpose, column
    FFTs, all_to_all back."""
    p = mesh.shape[axis]
    n0, n1 = x.shape
    if n0 % p or n1 % p:
        raise ValueError(f"shape {x.shape} not tileable over {p} shards")

    def build():
        return _shard_prog(mesh, axis,
                           lambda a: fft2_body(a, axis, inverse=inverse))

    return _program(("fft2", mesh, axis, x.shape, x.dtype.name, inverse),
                    build)(x)


def ifft2_sharded(x: Any, mesh, axis: str = "x"):
    return fft2_sharded(x, mesh, axis, inverse=True)


def fft2_sharded_2d(x: Any, mesh, axes: Tuple[str, str] = ("x", "y"),
                    inverse: bool = False):
    """2-D FFT of an [N0, N1] array sharded over BOTH dims on a 2-D
    mesh (dim 0 over axes[0], dim 1 over axes[1]) — the layout real
    pods use (2-D ICI torus). Pencil schedule, one jitted program:

        a2a over axes[1] (rows whole)  -> row FFTs   -> a2a back
        a2a over axes[0] (cols whole)  -> column FFTs -> a2a back

    Each transpose stays INSIDE one mesh axis, so every exchange rides
    that axis's ICI ring; the other axis's sharding is untouched.
    Per-device extents must tile: Px*Py | N0/Px-side splits, i.e.
    N0 % (Px*Py) == 0 and N1 % (Px*Py) == 0.
    """
    ax0, ax1 = axes
    px, py = mesh.shape[ax0], mesh.shape[ax1]
    n0, n1 = x.shape
    if n0 % (px * py) or n1 % (px * py):
        raise ValueError(
            f"shape {x.shape} not tileable by Px*Py = {px}*{py} on both "
            f"dims (the intra-axis transposes re-split each dim)")

    def build():
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def body(a):                      # [N0/Px, N1/Py]
            f = jnp.fft.ifft if inverse else jnp.fft.fft
            # rows whole: redistribute dim 0 over the y axis too
            t = _a2a(a, ax1, split=0, concat=1)   # [N0/(PxPy), N1]
            t = f(t, axis=1)
            a = _a2a(t, ax1, split=1, concat=0)   # [N0/Px, N1/Py]
            # columns whole: redistribute dim 1 over the x axis
            t = _a2a(a, ax0, split=1, concat=0)   # [N0, N1/(PxPy)]
            t = f(t, axis=0)
            return _a2a(t, ax0, split=0, concat=1)

        return _shard_prog(mesh, P(ax0, ax1), body)

    return _program(("fft2_2d", mesh, axes, x.shape, x.dtype.name,
                     inverse), build)(x)


def ifft2_sharded_2d(x: Any, mesh, axes: Tuple[str, str] = ("x", "y")):
    return fft2_sharded_2d(x, mesh, axes, inverse=True)


def _split_n(n: int, p: int) -> Tuple[int, int]:
    """Factor n = n1*n2 with p | n1 and p | n2, n1 as near sqrt(n) as
    possible (balanced pencils minimize all_to_all volume skew)."""
    best = None
    d = p
    while d * d <= n * p:        # n1 candidates: multiples of p
        if n % d == 0 and (n // d) % p == 0:
            if best is None or abs(d - math.isqrt(n)) < abs(
                    best - math.isqrt(n)):
                best = d
        d += p
    if best is None:
        raise ValueError(
            f"cannot factor n={n} as n1*n2 with {p} | n1 and {p} | n2")
    return best, n // best


def fft_sharded(v: Any, mesh, axis: str = "x", inverse: bool = False):
    """1-D FFT of a length-N vector sharded in contiguous chunks over
    mesh axis `axis` (Bailey four-step; three all_to_alls; output in
    natural order, sharded the same way)."""
    p = mesh.shape[axis]
    (n,) = v.shape
    n1, n2 = _split_n(n, p)

    def build():
        def body(chunk):
            a = chunk.reshape(n1 // p, n2)
            return fft1d_body(a, axis, p, n, inverse=inverse)
        return _shard_prog(mesh, axis, body)

    return _program(("fft1", mesh, axis, n, v.dtype.name, inverse),
                    build)(v)


def ifft_sharded(v: Any, mesh, axis: str = "x"):
    return fft_sharded(v, mesh, axis, inverse=True)


def fft(v: Any, mesh=None, axis: str = "x", inverse: bool = False):
    """Front door: a sharded jax.Array (pass mesh) or a
    PartitionedVector (its layout carries mesh + axis) — the segmented-
    algorithm pattern (algo/__init__) applied to the FFT."""
    from ..containers.partitioned_vector import PartitionedVector
    if isinstance(v, PartitionedVector):
        if mesh is not None and mesh is not v.mesh:
            raise ValueError(
                "fft(pv, mesh=...): the layout's mesh governs; drop the "
                "mesh argument or pass the plain sharded array")
        if v.data.shape[0] != v.size:
            raise ValueError(
                f"fft over a padded partitioned_vector (size {v.size}, "
                f"padded {v.data.shape[0]}): resize so the axis divides "
                f"the length")
        out = fft_sharded(v.data, v.mesh, v.layout.axis, inverse)
        return PartitionedVector.from_array(out, layout=v.layout)
    if mesh is None:
        raise ValueError("pass mesh= for a plain sharded array")
    return fft_sharded(v, mesh, axis, inverse)


def ifft(v: Any, mesh=None, axis: str = "x"):
    return fft(v, mesh, axis, inverse=True)
