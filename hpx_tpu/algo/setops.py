"""Set operations on sorted ranges: set_union, set_intersection,
set_difference, set_symmetric_difference, includes.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{set_union,set_intersection,set_difference,set_symmetric_difference,
includes}.hpp — std multiset semantics (an element appearing m times in
a and n times in b appears max(m,n)/min(m,n)/max(m-n,0)/|m-n| times in
union/intersection/difference/symmetric_difference).

Device lowering: one jitted rank kernel per input. For sorted ranges the
multiset rules reduce to a per-element comparison of the element's
OCCURRENCE INDEX within its equal-run (i - searchsorted(a, a[i], 'left'))
against its multiplicity in the other range (searchsorted right - left):
e.g. a[i] survives set_difference iff occ(a,i) >= count_b(a[i]). That
turns data-dependent merge walks (the C++ formulation) into fixed-shape
vector ops XLA fuses into one pass; the data-dependent OUTPUT size is
compacted at the host boundary exactly like copy_if/unique (XLA needs
static shapes). `includes` has a static (boolean) result and stays fully
on device.
"""

from __future__ import annotations

from typing import Any

from ..exec.policies import ExecutionPolicy
from ._core import (
    device_executor,
    finish,
    is_device_policy,
    to_numpy_view,
)


def _rank_masks_device(which: str):
    """Mask kernel(s) for one side: keep a[i] by comparing its run-local
    occurrence index with its multiplicity in b."""
    import jax.numpy as jnp

    if which not in ("extra", "common"):
        raise ValueError(which)

    def mask(a, b):
        occ = jnp.arange(a.shape[0]) - jnp.searchsorted(a, a, side="left")
        cnt = (jnp.searchsorted(b, a, side="right")
               - jnp.searchsorted(b, a, side="left"))
        # "extra" copies max(m-n, 0) (difference side); "common" copies
        # min(m, n) (intersection side)
        return occ >= cnt if which == "extra" else occ < cnt

    return mask


def _np_rank_mask(a, b, which: str):
    import numpy as np
    occ = np.arange(len(a)) - np.searchsorted(a, a, side="left")
    cnt = (np.searchsorted(b, a, side="right")
           - np.searchsorted(b, a, side="left"))
    return occ >= cnt if which == "extra" else occ < cnt


def _masked_setop(policy: ExecutionPolicy, rng: Any, rng2: Any,
                  which_a: str, which_b: str | None, keep_all_a: bool):
    """Shared driver: device computes the keep-mask(s) in one jitted
    program; compaction + final merge happen at the host boundary
    (data-dependent sizes). Inputs must be sorted; output is sorted."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            fa, fb = a.reshape(-1), b.reshape(-1)
            ma = (jnp.ones(fa.shape, bool) if keep_all_a
                  else _rank_masks_device(which_a)(fa, fb))
            if which_b is None:
                return ma, jnp.zeros((0,), bool)
            return ma, _rank_masks_device(which_b)(fb, fa)
        mask_f = ex.async_execute(kernel, rng, rng2)

        def run():
            import numpy as np
            # hpxlint: disable-next=HPX002 — data-dependent compaction:
            # device computed the membership masks; the host gather
            # builds the dynamic-shape set result
            ma, mb = (np.asarray(m) for m in mask_f.get())
            # hpxlint: disable-next=HPX002 — host gather (see above)
            fa = np.asarray(rng).reshape(-1)[ma]
            if which_b is None:
                return jnp.asarray(fa)
            # hpxlint: disable-next=HPX002 — host gather (see above)
            fb = np.asarray(rng2).reshape(-1)[mb]
            # both pieces are sorted; a stable sort of the concat is the
            # merge (a-elements precede equal b-elements, std order)
            return jnp.asarray(np.sort(np.concatenate([fa, fb]),
                                       kind="stable"))
        return finish(policy, run)

    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        fa = a if keep_all_a else a[_np_rank_mask(a, b, which_a)]
        if which_b is None:
            return fa.copy() if fa is a else fa
        fb = b[_np_rank_mask(b, a, which_b)]
        return np.sort(np.concatenate([fa, fb]), kind="stable")

    return finish(policy, run)


def set_union(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Sorted union of two sorted ranges; an element with multiplicities
    (m, n) appears max(m, n) times (std::set_union)."""
    return _masked_setop(policy, rng, rng2, "all", "extra",
                         keep_all_a=True)


def set_intersection(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Sorted intersection; multiplicity min(m, n) (std::set_intersection)."""
    return _masked_setop(policy, rng, rng2, "common", None,
                         keep_all_a=False)


def set_difference(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Sorted a minus b; multiplicity max(m - n, 0) (std::set_difference)."""
    return _masked_setop(policy, rng, rng2, "extra", None,
                         keep_all_a=False)


def set_symmetric_difference(policy: ExecutionPolicy, rng: Any,
                             rng2: Any) -> Any:
    """Sorted symmetric difference; multiplicity |m - n|
    (std::set_symmetric_difference)."""
    return _masked_setop(policy, rng, rng2, "extra", "extra",
                         keep_all_a=False)


def includes(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """True when sorted rng contains every element of sorted rng2 with
    at least its multiplicity (std::includes). Static-shaped result —
    the device path never leaves the chip."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            fa, fb = a.reshape(-1), b.reshape(-1)
            if fb.shape[0] == 0:       # static shape: empty subset
                return jnp.asarray(True)
            return _rank_masks_device("common")(fb, fa).all()
        fut = ex.async_execute(kernel, rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        if len(b) == 0:
            return True
        return bool(_np_rank_mask(b, a, "common").all())

    return finish(policy, run)
