"""Algorithm dispatch machinery.

Reference analog: libs/core/algorithms' tag_invoke CPO dispatch +
partitioner/chunking utilities (hpx/parallel/util/detail/chunk_size.hpp,
foreach_partitioner.hpp). Structure kept deliberately (SURVEY.md §3.3):

    algorithm(policy, range, ...)            (CPO)
      -> route by policy/range:
           device  : one fused jit kernel (TpuExecutor / jax arrays)
           host    : chunk -> bulk_async_execute -> combine
           segmented (M6): per-segment dispatch via shard_map

so `par.on(tpu_executor())` reroutes a whole algorithm with no user-facing
change. Everything below the partitioner collapses into one XLA program on
the device path — chunking is the compiler's job there.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

from ..exec.params import default_chunker
from ..exec.policies import ExecutionPolicy, seq as seq_policy
from ..exec.tpu import TpuExecutor
from ..futures.future import Future, make_ready_future


def is_jax_array(x: Any) -> bool:
    import jax
    return isinstance(x, jax.Array)


def is_device_policy(policy: ExecutionPolicy, *ranges: Any) -> bool:
    """Device path when bound to a TpuExecutor, or when operating on jax
    arrays under a parallel/vectorizing policy with no explicit host
    executor (jax data wants jax execution)."""
    if isinstance(policy.executor, TpuExecutor):
        return True
    if policy.executor is not None:
        return False
    if (policy.parallel or policy.vectorize) and ranges and \
            all(is_jax_array(r) for r in ranges if r is not None):
        return True
    return False


def device_executor(policy: ExecutionPolicy) -> TpuExecutor:
    if isinstance(policy.executor, TpuExecutor):
        return policy.executor
    return _shared_tpu_executor()


_tpu_exec: Optional[TpuExecutor] = None


def _shared_tpu_executor() -> TpuExecutor:
    global _tpu_exec
    if _tpu_exec is None:
        _tpu_exec = TpuExecutor()
    return _tpu_exec


def finish(policy: ExecutionPolicy, value_fn: Callable[[], Any]) -> Any:
    """Respect the task policy: value, or future of value.

    value_fn is deferred so task-policy callers get true asynchrony on the
    host path (the device path is async regardless — dispatch is async).
    """
    if policy.is_task:
        from ..futures.async_ import async_
        return async_(value_fn)
    return value_fn()


def chunk_bounds(count: int, policy: ExecutionPolicy,
                 num_workers: int) -> List[Tuple[int, int]]:
    """[(begin, end)) chunks per the policy's chunking parameter."""
    chunking = policy.chunking or default_chunker()
    if policy.cores:
        num_workers = min(num_workers, policy.cores)
    sizes = chunking.chunks(count, max(1, num_workers))
    out = []
    pos = 0
    for s in sizes:
        out.append((pos, pos + s))
        pos += s
    return out


def host_bulk(policy: ExecutionPolicy, count: int,
              chunk_fn: Callable[[int, int], Any]) -> List[Any]:
    """Run chunk_fn over chunk bounds on the policy's executor; ordered
    results. Sequential policies run inline (no task overhead)."""
    ex = policy.get_executor()
    if not policy.parallel or count == 0:
        return [chunk_fn(0, count)] if count else []
    bounds = chunk_bounds(count, policy, ex.num_workers)
    if len(bounds) <= 1:
        return [chunk_fn(0, count)]
    futs = [ex.async_execute(chunk_fn, b, e) for (b, e) in bounds]
    return [f.get() for f in futs]


def to_numpy_view(rng: Any):
    """Host path works on numpy views (zero-copy for numpy input; device
    arrays materialize as read-only views, so those are copied to keep
    the mutate-in-place algorithms working)."""
    import numpy as np
    if isinstance(rng, np.ndarray):
        return rng
    # hpxlint: disable-next=HPX002 — to_numpy_view IS the
    # documented host materialization boundary for host-path
    # algorithms; device arrays land here on purpose
    arr = np.asarray(rng)
    if not arr.flags.writeable:
        arr = arr.copy()
    return arr
