"""Scans and adjacent ops: inclusive_scan, exclusive_scan, transform
variants, adjacent_difference, adjacent_find.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{inclusive_scan,exclusive_scan,transform_inclusive_scan,
transform_exclusive_scan,adjacent_difference,adjacent_find}.hpp and the
scan_partitioner (3-phase chunked scan) in parallel/util.

Device lowering: jax.lax.associative_scan — the parallel scan is exactly
what the scan_partitioner approximates on CPUs, but compiled; arbitrary
associative traceable ops supported.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Optional

from ..exec.policies import ExecutionPolicy
from ._core import (
    device_executor,
    finish,
    is_device_policy,
    to_numpy_view,
)


def _host_scan(arr, init, op, inclusive: bool, transform=None):
    import numpy as np
    if transform is None:
        # widen to the accumulator's dtype (init may promote, e.g. int
        # input with float init) — matches device-path/std semantics
        # hpxlint: disable-next=HPX002 — init is a host scalar;
        # asarray here is a dtype probe, not a device sync
        out = np.empty(len(arr), dtype=np.result_type(arr, np.asarray(init)))
        first = arr[0] if len(arr) else None
    else:
        # transform element 0 once: dtype probe AND iteration value
        first = transform(arr[0]) if len(arr) else None
        out = np.empty(len(arr),
                       # hpxlint: disable-next=HPX002 — dtype probe on the
                       # host-transformed first element, not a device sync
                       dtype=np.result_type(np.asarray(first))
                       if len(arr) else float)
    acc = init
    for i in range(len(arr)):
        v = first if i == 0 else (
            arr[i] if transform is None else transform(arr[i]))
        if inclusive:
            acc = op(acc, v)
            out[i] = acc
        else:
            out[i] = acc
            acc = op(acc, v)
    return out


def inclusive_scan(policy: ExecutionPolicy, rng: Any, init: Any = 0,
                   op: Callable = operator.add) -> Any:
    return transform_inclusive_scan(policy, rng, init, op, None)


def exclusive_scan(policy: ExecutionPolicy, rng: Any, init: Any = 0,
                   op: Callable = operator.add) -> Any:
    return transform_exclusive_scan(policy, rng, init, op, None)


def transform_inclusive_scan(policy: ExecutionPolicy, rng: Any, init: Any,
                             op: Callable,
                             transform: Optional[Callable]) -> Any:
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            if transform is not None:
                flat = jax.vmap(transform)(flat)
            scanned = jax.lax.associative_scan(jax.vmap(op), flat)
            # init is combined exactly once per prefix (not assumed to be
            # the op's identity): out[i] = op(init, fold(a[0..i]))
            init_a = jnp.asarray(init, flat.dtype)
            return jax.vmap(lambda x: op(init_a, x))(scanned)
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)
    return finish(policy,
                  lambda: _host_scan(arr, init, op, True, transform))


def transform_exclusive_scan(policy: ExecutionPolicy, rng: Any, init: Any,
                             op: Callable,
                             transform: Optional[Callable]) -> Any:
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        if rng.shape[0] == 0:  # std semantics: empty in, empty out
            return finish(policy, lambda: rng)
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            if transform is not None:
                flat = jax.vmap(transform)(flat)
            scanned = jax.lax.associative_scan(jax.vmap(op), flat)
            init_a = jnp.asarray(init, flat.dtype)
            # exclusive: out[0]=init, out[i]=op(init, fold(a[0..i-1])) —
            # init is NOT assumed to be the op's identity
            combined = jax.vmap(lambda x: op(init_a, x))(scanned[:-1])
            return jnp.concatenate([init_a[None], combined])
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)
    return finish(policy,
                  lambda: _host_scan(arr, init, op, False, transform))


def adjacent_difference(policy: ExecutionPolicy, rng: Any,
                        op: Callable = operator.sub) -> Any:
    """out[0]=a[0]; out[i]=op(a[i], a[i-1]) (std semantics)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            diffs = jax.vmap(op)(flat[1:], flat[:-1])
            return jnp.concatenate([flat[:1], diffs])
        fut = ex.async_execute(kernel, rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        out = np.empty_like(arr)
        if len(arr):
            out[0] = arr[0]
            for i in range(1, len(arr)):
                out[i] = op(arr[i], arr[i - 1])
        return out

    return finish(policy, run)


def adjacent_find(policy: ExecutionPolicy, rng: Any,
                  pred: Callable = operator.eq) -> Any:
    """Index of first i with pred(a[i], a[i+1]), or -1."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            flat = a.reshape(-1)
            m = jax.vmap(pred)(flat[:-1], flat[1:])
            return jnp.where(m.any(), jnp.argmax(m), -1)
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    arr = to_numpy_view(rng)

    def run():
        for i in range(len(arr) - 1):
            if pred(arr[i], arr[i + 1]):
                return i
        return -1

    return finish(policy, run)
