"""Parallel algorithms (local + segmented surface).

Reference analog: libs/core/algorithms — the CPO set over execution
policies — plus libs/full/segmented_algorithms: the SAME entry points
accept partitioned_vector arguments and dispatch the segmented overlay
(segmented.py), exactly as HPX routes segmented iterators through
segmented_iterator_traits. `preserves_shape` marks the algorithms whose
result is a same-length range (rewrapped in the source's layout).
"""

from . import elementwise as _ew
from . import fft  # noqa: F401  (sharded-array surface, not a CPO)
from . import reductions as _red
from . import scans as _sc
from . import setops as _set
from . import sorting as _so
from .segmented import segmentable as _seg

# -- elementwise (shape-preserving) ------------------------------------------
for_each = _seg(_ew.for_each, preserves_shape=True)
for_each_n = _seg(_ew.for_each_n)
for_loop = _seg(_ew.for_loop)
transform = _seg(_ew.transform, preserves_shape=True)
copy = _seg(_ew.copy, preserves_shape=True)
copy_n = _seg(_ew.copy_n)
copy_if = _seg(_ew.copy_if)
fill = _seg(_ew.fill, preserves_shape=True)
fill_n = _seg(_ew.fill_n)
generate = _seg(_ew.generate, preserves_shape=True)
generate_n = _seg(_ew.generate_n)
remove = _seg(_ew.remove)
remove_if = _seg(_ew.remove_if)
replace = _seg(_ew.replace, preserves_shape=True)
replace_if = _seg(_ew.replace_if, preserves_shape=True)

# -- reductions / searches (scalar results) ----------------------------------
reduce = _seg(_red.reduce)
transform_reduce = _seg(_red.transform_reduce)
count = _seg(_red.count)
count_if = _seg(_red.count_if)
all_of = _seg(_red.all_of)
any_of = _seg(_red.any_of)
none_of = _seg(_red.none_of)
min_element = _seg(_red.min_element)
max_element = _seg(_red.max_element)
minmax_element = _seg(_red.minmax_element)
equal = _seg(_red.equal)
mismatch = _seg(_red.mismatch)
find = _seg(_red.find)
find_if = _seg(_red.find_if)
find_first_of = _seg(_red.find_first_of)
is_sorted_until = _seg(_red.is_sorted_until)
is_partitioned = _seg(_red.is_partitioned)
lexicographical_compare = _seg(_red.lexicographical_compare)
reduce_by_key = _seg(_red.reduce_by_key)
search = _seg(_red.search)
search_n = _seg(_red.search_n)
find_end = _seg(_red.find_end)
contains = _seg(_red.contains)
contains_subrange = _seg(_red.contains_subrange)
starts_with = _seg(_red.starts_with)
ends_with = _seg(_red.ends_with)

# -- set operations on sorted ranges (data-dependent output sizes) -----------
set_union = _seg(_set.set_union)
set_intersection = _seg(_set.set_intersection)
set_difference = _seg(_set.set_difference)
set_symmetric_difference = _seg(_set.set_symmetric_difference)
includes = _seg(_set.includes)

# -- scans (shape-preserving) ------------------------------------------------
inclusive_scan = _seg(_sc.inclusive_scan, preserves_shape=True)
exclusive_scan = _seg(_sc.exclusive_scan, preserves_shape=True)
transform_inclusive_scan = _seg(_sc.transform_inclusive_scan,
                                preserves_shape=True)
transform_exclusive_scan = _seg(_sc.transform_exclusive_scan,
                                preserves_shape=True)
adjacent_difference = _seg(_sc.adjacent_difference, preserves_shape=True)
adjacent_find = _seg(_sc.adjacent_find)

# -- sorting / permutations --------------------------------------------------
sort = _seg(_so.sort, preserves_shape=True)
sort_sharded = _so.sort_sharded        # explicit distributed surface
sort_sharded_by_key = _so.sort_sharded_by_key
stable_sort = _seg(_so.stable_sort, preserves_shape=True)
is_sorted = _seg(_so.is_sorted)
merge = _seg(_so.merge)
reverse = _seg(_so.reverse, preserves_shape=True)
rotate = _seg(_so.rotate, preserves_shape=True)
unique = _seg(_so.unique)
partition = _seg(_so.partition)
partition_copy = _seg(_so.partition_copy)
is_heap = _seg(_so.is_heap)
is_heap_until = _seg(_so.is_heap_until)
partial_sort = _seg(_so.partial_sort, preserves_shape=True)
partial_sort_copy = _seg(_so.partial_sort_copy)
nth_element = _seg(_so.nth_element, preserves_shape=True)
shift_left = _seg(_so.shift_left, preserves_shape=True)
shift_right = _seg(_so.shift_right, preserves_shape=True)
swap_ranges = _so.swap_ranges          # pair-valued: no segmented overlay

# functional-data-model aliases: where the target already returns a NEW
# range (remove/unique compact, copy copies) the *_copy variant IS the
# in-place sibling, and std::move degenerates to copy. replace/replace_if
# mutate on the host path (std semantics), so their _copy variants are
# real copy-first wrappers (hpx/parallel/algorithms/{unique,remove_copy,
# replace_copy,move}.hpp surface).
unique_copy = unique
remove_copy = remove
remove_copy_if = remove_if
replace_copy = _seg(_ew.replace_copy, preserves_shape=True)
replace_copy_if = _seg(_ew.replace_copy_if, preserves_shape=True)
move = copy

# for_loop clause objects (hpx::experimental::induction/reduction)
induction = _ew.induction
reduction = _ew.reduction
Induction = _ew.Induction
Reduction = _ew.Reduction

__all__ = [
    "induction", "reduction", "Induction", "Reduction",
    "for_each", "for_each_n", "for_loop", "transform", "copy", "copy_n",
    "copy_if", "fill", "fill_n", "generate", "generate_n",
    "reduce", "transform_reduce", "count", "count_if",
    "all_of", "any_of", "none_of", "min_element", "max_element",
    "minmax_element", "equal", "mismatch", "find", "find_if",
    "find_first_of", "is_sorted_until", "is_partitioned",
    "lexicographical_compare", "remove", "remove_if", "replace",
    "replace_if",
    "inclusive_scan", "exclusive_scan", "transform_inclusive_scan",
    "transform_exclusive_scan", "adjacent_difference", "adjacent_find",
    "sort", "sort_sharded", "sort_sharded_by_key", "stable_sort", "is_sorted", "merge",
    "reverse", "rotate", "unique", "partition",
    "search", "search_n", "find_end", "contains", "contains_subrange",
    "starts_with", "ends_with",
    "set_union", "set_intersection", "set_difference",
    "set_symmetric_difference", "includes",
    "partition_copy", "partial_sort", "partial_sort_copy", "nth_element",
    "is_heap", "is_heap_until",
    "shift_left", "shift_right", "swap_ranges",
    "unique_copy", "remove_copy", "remove_copy_if", "replace_copy",
    "replace_copy_if", "move", "reduce_by_key",
]
