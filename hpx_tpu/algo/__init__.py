"""Parallel algorithms (local surface).

Reference analog: libs/core/algorithms — the CPO set over execution
policies. Segmented (distributed) overlays dispatch from the same entry
points once containers are partitioned (M6, libs/full/segmented_algorithms
analog).
"""

from .elementwise import (  # noqa: F401
    copy,
    copy_if,
    copy_n,
    fill,
    fill_n,
    for_each,
    for_each_n,
    for_loop,
    generate,
    generate_n,
    transform,
)
from .reductions import (  # noqa: F401
    all_of,
    any_of,
    count,
    count_if,
    equal,
    find,
    find_if,
    max_element,
    min_element,
    minmax_element,
    mismatch,
    none_of,
    reduce,
    transform_reduce,
)
from .scans import (  # noqa: F401
    adjacent_difference,
    adjacent_find,
    exclusive_scan,
    inclusive_scan,
    transform_exclusive_scan,
    transform_inclusive_scan,
)
from .sorting import (  # noqa: F401
    is_sorted,
    merge,
    partition,
    reverse,
    rotate,
    sort,
    stable_sort,
    unique,
)
