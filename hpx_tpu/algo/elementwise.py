"""Elementwise parallel algorithms: for_each, transform, copy, fill,
generate, for_loop.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{for_each,transform,copy,fill,generate,for_loop}.hpp.

Semantics note (TPU-first, documented divergence): HPX mutates ranges
through iterators; jax arrays are immutable, so every algorithm RETURNS
its result range. On the host path over numpy arrays the operation is
also applied in place where HPX would (for_each, fill), and the range is
returned as well so call sites are uniform across paths.

Device lowering: the user's elementwise callable is vmapped over the
flattened range and the whole algorithm becomes ONE jitted XLA program
(the per-chunk loop_n of HPX collapses into the kernel — SURVEY.md §3.3).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..exec.policies import ExecutionPolicy, seq
from ._core import (
    device_executor,
    finish,
    host_bulk,
    is_device_policy,
    to_numpy_view,
)


def _vmapped(f: Callable) -> Callable:
    import jax

    def kernel(*arrs):
        flat = [a.reshape(-1) for a in arrs]
        out = jax.vmap(f)(*flat)
        return out.reshape(arrs[0].shape)

    return kernel


def for_each(policy: ExecutionPolicy, rng: Any,
             f: Callable[[Any], Any]) -> Any:
    """Apply f to each element. Returns the (new) range.

    Device path: f must be jax-traceable elementwise; result is f applied
    elementwise (HPX's mutate-in-place becomes pure transform — for_each
    and transform coincide on immutable arrays).
    """
    if is_device_policy(policy, rng):
        ex = device_executor(policy)
        fut = ex.async_execute(_vmapped(f), rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)

    def chunk(b: int, e: int) -> None:
        for i in range(b, e):
            r = f(arr[i])
            if r is not None:       # allow mutating or transforming style
                arr[i] = r

    def run():
        host_bulk(policy, len(arr), chunk)
        return arr

    return finish(policy, run)


def for_each_n(policy: ExecutionPolicy, rng: Any, n: int,
               f: Callable[[Any], Any]) -> Any:
    return for_each(policy, rng[:n], f)


def transform(policy: ExecutionPolicy, rng: Any, f: Callable,
              rng2: Optional[Any] = None) -> Any:
    """Unary transform(policy, a, f) or binary transform(policy, a, f, b)."""
    if is_device_policy(policy, rng, rng2):
        ex = device_executor(policy)
        if rng2 is None:
            fut = ex.async_execute(_vmapped(f), rng)
        else:
            fut = ex.async_execute(_vmapped(f), rng, rng2)
        return fut if policy.is_task else fut.get()

    import numpy as np
    a = to_numpy_view(rng)
    if rng2 is not None:
        b = to_numpy_view(rng2)
        out = np.empty(len(a), dtype=np.result_type(a, b))

        def chunk(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                out[i] = f(a[i], b[i])
    else:
        out = np.empty(len(a), dtype=a.dtype)

        def chunk(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                out[i] = f(a[i])

    def run():
        host_bulk(policy, len(a), chunk)
        return out

    return finish(policy, run)


def copy(policy: ExecutionPolicy, rng: Any) -> Any:
    """Returns a copy of the range (copy-to-destination flattened into a
    functional return, matching the jax data model)."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(jnp.copy, rng)  # dtype-preserving copy
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)
    return finish(policy, lambda: arr.copy())


def copy_n(policy: ExecutionPolicy, rng: Any, n: int) -> Any:
    return copy(policy, rng[:n])


def copy_if(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    """Keep elements satisfying pred. Device note: output size is data-
    dependent — the device path computes the mask on device and compacts
    on host boundary (XLA needs static shapes)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)
        mask_f = ex.async_execute(
            lambda a: jax.vmap(pred)(a.reshape(-1)), rng)

        def run():
            import numpy as np
            # hpxlint: disable-next=HPX002 — data-dependent compaction:
            # the device kernel computed the mask; the host must
            # materialize it to build the dynamic-shape result
            mask = np.asarray(mask_f.get())
            # hpxlint: disable-next=HPX002 — host-side gather of the
            # source for the dynamic-shape result
            flat = np.asarray(rng).reshape(-1)
            return jnp.asarray(flat[mask])
        return finish(policy, run)

    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        mask_parts = host_bulk(
            policy, len(arr),
            lambda b, e: [bool(pred(arr[i])) for i in range(b, e)])
        mask = np.array([m for part in mask_parts for m in part], dtype=bool)
        return arr[mask]

    return finish(policy, run)


def fill(policy: ExecutionPolicy, rng: Any, value: Any) -> Any:
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a: jnp.full_like(a, value), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        host_bulk(policy, len(arr),
                  lambda b, e: arr.__setitem__(slice(b, e), value))
        return arr

    return finish(policy, run)


def fill_n(policy: ExecutionPolicy, rng: Any, n: int, value: Any) -> Any:
    return fill(policy, rng[:n], value)


def generate(policy: ExecutionPolicy, rng: Any, gen: Callable[[], Any]) -> Any:
    """generate fills with gen() per element. Device path: gen must be a
    traceable index-free thunk; generation order is unspecified (as in
    par/par_unseq HPX)."""
    if is_device_policy(policy, rng):
        import jax
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: jax.vmap(lambda _: gen())(a.reshape(-1)).reshape(a.shape),
            rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def chunk(b: int, e: int) -> None:
        for i in range(b, e):
            arr[i] = gen()

    def run():
        host_bulk(policy, len(arr), chunk)
        return arr

    return finish(policy, run)


def generate_n(policy: ExecutionPolicy, rng: Any, n: int, gen: Callable) -> Any:
    return generate(policy, rng[:n], gen)


class Induction:
    """hpx::experimental::induction(x0, stride): the body receives the
    induction value x0 + stride*(i - first) alongside i."""

    __slots__ = ("x0", "stride")

    def __init__(self, x0: Any, stride: Any = 1) -> None:
        self.x0 = x0
        self.stride = stride


class Reduction:
    """hpx::experimental::reduction(identity, op) — functional twist:
    instead of mutating a reduction variable, the body RETURNS its
    per-iteration contribution (a tuple when several reductions are
    declared); for_loop returns the combined value(s). op must be
    associative (it runs as a tree reduction on the device path)."""

    __slots__ = ("identity", "op")

    def __init__(self, identity: Any, op: Callable[[Any, Any], Any]) -> None:
        self.identity = identity
        self.op = op


def induction(x0: Any, stride: Any = 1) -> Induction:
    return Induction(x0, stride)


def reduction(identity: Any, op: Callable[[Any, Any], Any]) -> Reduction:
    return Reduction(identity, op)


def _for_loop_clauses(policy: ExecutionPolicy, first: int, last: int,
                      body: Callable, inds, reds) -> Any:
    """for_loop with induction/reduction clauses.

    body(i, *induction_values) -> reduction contribution(s).
    """
    count = max(0, last - first)
    if count == 0:
        vals = tuple(r.identity for r in reds)
        return vals[0] if len(vals) == 1 else vals

    if is_device_policy(policy):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)
        idx = jnp.arange(first, last)

        def kernel(ix):
            ind_vals = [i.x0 + i.stride * (ix - first) for i in inds]
            return jax.vmap(lambda j, *iv: body(j, *iv))(ix, *[
                jnp.asarray(v) for v in ind_vals])

        def run(ix):
            out = kernel(ix)
            if not reds:
                return out
            parts = out if isinstance(out, (tuple, list)) else (out,)
            combined = []
            for r, part in zip(reds, parts):
                acc = jnp.asarray(r.identity)
                combined.append(jax.lax.reduce(
                    part, acc, lambda a, b: r.op(a, b), (0,)))
            return combined[0] if len(combined) == 1 else tuple(combined)

        fut = ex.async_execute(run, idx)
        return fut if policy.is_task else fut.get()

    accs = [r.identity for r in reds]
    for i in range(first, last):
        ind_vals = [c.x0 + c.stride * (i - first) for c in inds]
        out = body(i, *ind_vals)
        if reds:
            parts = out if isinstance(out, (tuple, list)) else (out,)
            for j, r in enumerate(reds):
                accs[j] = r.op(accs[j], parts[j])
    if not reds:
        return None
    return accs[0] if len(accs) == 1 else tuple(accs)


def for_loop(policy: ExecutionPolicy, first: int, last: int,
             body: Callable[[int], Any], *clauses: Any) -> Any:
    """hpx::experimental::for_loop(policy, first, last, body[, clauses]).

    Without clauses: an indexed loop; returns the array/list of body(i)
    results (the device path is pure, so results are its only output;
    the host path collects for parity — returns None only if every body
    call returned None, i.e. a pure side-effect loop).

    With induction/reduction clauses (see those classes): body receives
    induction values and returns reduction contributions.
    """
    if clauses:
        inds = [c for c in clauses if isinstance(c, Induction)]
        reds = [c for c in clauses if isinstance(c, Reduction)]
        bad = [c for c in clauses
               if not isinstance(c, (Induction, Reduction))]
        if bad:
            from ..core.errors import BadParameter
            raise BadParameter(f"unknown for_loop clause: {bad[0]!r}")
        return _for_loop_clauses(policy, first, last, body, inds, reds)
    count = max(0, last - first)
    if is_device_policy(policy):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)
        idx = jnp.arange(first, last)
        fut = ex.async_execute(lambda ix: jax.vmap(body)(ix), idx)
        return fut if policy.is_task else fut.get()

    def chunk(b: int, e: int) -> list:
        return [body(first + i) for i in range(b, e)]

    def run():
        parts = host_bulk(policy, count, chunk)
        results = [r for part in parts for r in part]
        if all(r is None for r in results):
            return None
        return results

    return finish(policy, run)


def remove_if(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    """std::remove_if semantics, shrunk: elements NOT satisfying pred,
    order preserved (the complement of copy_if; size is data-dependent,
    so the device path compacts at the host boundary like copy_if)."""
    if is_device_policy(policy, rng):
        return copy_if(policy, rng, lambda x: ~pred(x))   # traced bool
    return copy_if(policy, rng, lambda x: not pred(x))


def remove(policy: ExecutionPolicy, rng: Any, value: Any) -> Any:
    """std::remove semantics, shrunk."""
    return remove_if(policy, rng, lambda x: x == value)


def replace_if(policy: ExecutionPolicy, rng: Any, pred: Callable,
               new_value: Any) -> Any:
    """Elements satisfying pred become new_value (shape-preserving —
    on device one fused where)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: jnp.where(jax.vmap(pred)(a.reshape(-1)).reshape(
                a.shape), jnp.asarray(new_value, a.dtype), a), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        # in place, like fill/for_each (the module's host convention
        # and std::replace_if's semantics)
        parts = host_bulk(
            policy, len(arr),
            lambda b, e: [(i, bool(pred(arr[i]))) for i in range(b, e)])
        for part in parts:
            for i, hit in part:
                if hit:
                    arr[i] = new_value
        return arr

    return finish(policy, run)


def replace(policy: ExecutionPolicy, rng: Any, old_value: Any,
            new_value: Any) -> Any:
    return replace_if(policy, rng, lambda x: x == old_value, new_value)


def _fresh_host_copy(rng: Any) -> Any:
    """A detached host copy when the input is a mutable numpy array; jax
    arrays are immutable and pass through."""
    import numpy as np
    return rng.copy() if isinstance(rng, np.ndarray) else rng


def replace_copy(policy: ExecutionPolicy, rng: Any, old_value: Any,
                 new_value: Any) -> Any:
    """Like replace, but NEVER modifies the input (std::replace_copy):
    the host path works on a fresh copy (replace's host convention is
    in-place, matching std::replace)."""
    return replace(policy, _fresh_host_copy(rng), old_value, new_value)


def replace_copy_if(policy: ExecutionPolicy, rng: Any, pred: Callable,
                    new_value: Any) -> Any:
    """Like replace_if, but NEVER modifies the input
    (std::replace_copy_if)."""
    return replace_if(policy, _fresh_host_copy(rng), pred, new_value)
