"""Reductions and searches: reduce, transform_reduce, count, any/all/none,
min/max/minmax element values, equal, mismatch, find.

Reference analog: libs/core/algorithms include/hpx/parallel/algorithms/
{reduce,transform_reduce,count,all_any_none,minmax,equal,mismatch,find}.hpp.

Device lowering: reduction with an arbitrary traceable binary op uses
jax.lax.reduce in ONE jitted program; transform_reduce fuses map+reduce —
this is the config #1 (SAXPY+dot) path where XLA fuses the multiply into
the reduction and the MXU/VPU stream the whole range from HBM once.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Optional

from ..exec.policies import ExecutionPolicy
from ._core import (
    device_executor,
    finish,
    host_bulk,
    is_device_policy,
    to_numpy_view,
)


import operator as _op

# Fast paths with known identities; lax.reduce would use `init` as the
# per-tile identity, which silently corrupts results for non-identity
# inits, so the general path folds via associative_scan (identity-free)
# and applies init exactly once.
_KNOWN_FOLDS = {}


def _known_folds():
    if not _KNOWN_FOLDS:
        import jax.numpy as jnp
        # (whole-array fold, traceable binary combiner) — the combiner is
        # needed because builtin min/max cannot run on tracers
        _KNOWN_FOLDS.update({
            _op.add: (jnp.sum, jnp.add), _op.mul: (jnp.prod, jnp.multiply),
            min: (jnp.min, jnp.minimum), max: (jnp.max, jnp.maximum),
        })
    return _KNOWN_FOLDS


def _device_reduce_kernel(op: Callable, init: Any):
    import jax
    import jax.numpy as jnp

    def kernel(a):
        flat = a.reshape(-1)
        known = _known_folds().get(op)
        if known is not None:
            fold, combine = known
            total = fold(flat)
        else:
            combine = op
            # associative fold without an identity requirement
            total = jax.lax.associative_scan(jax.vmap(op), flat)[-1]
        return combine(jnp.asarray(init, flat.dtype), total)

    return kernel


def reduce(policy: ExecutionPolicy, rng: Any, init: Any = 0,
           op: Callable = operator.add) -> Any:
    if is_device_policy(policy, rng):
        ex = device_executor(policy)
        fut = ex.async_execute(_device_reduce_kernel(op, init), rng)
        return fut if policy.is_task else fut.get()

    arr = to_numpy_view(rng)

    def chunk(b: int, e: int) -> Any:
        acc = None
        for i in range(b, e):
            acc = arr[i] if acc is None else op(acc, arr[i])
        return acc

    def run():
        partials = [p for p in host_bulk(policy, len(arr), chunk)
                    if p is not None]
        acc = init
        for p in partials:
            acc = op(acc, p)
        return acc

    return finish(policy, run)


def transform_reduce(policy: ExecutionPolicy, rng: Any, init: Any,
                     reduce_op: Callable, transform_op: Callable,
                     rng2: Optional[Any] = None) -> Any:
    """transform_reduce(policy, a, init, plus, f) or the binary
    (inner-product) form transform_reduce(policy, a, b, init, plus, mul)
    spelled transform_reduce(policy, a, init, plus, mul, rng2=b)."""
    if is_device_policy(policy, rng, rng2):
        import jax
        ex = device_executor(policy)

        if rng2 is None:
            def kernel(a):
                mapped = jax.vmap(transform_op)(a.reshape(-1))
                return _device_reduce_kernel(reduce_op, init)(mapped)
            fut = ex.async_execute(kernel, rng)
        else:
            def kernel2(a, b):
                mapped = jax.vmap(transform_op)(a.reshape(-1), b.reshape(-1))
                return _device_reduce_kernel(reduce_op, init)(mapped)
            fut = ex.async_execute(kernel2, rng, rng2)
        return fut if policy.is_task else fut.get()

    a = to_numpy_view(rng)
    b = to_numpy_view(rng2) if rng2 is not None else None

    def chunk(lo: int, hi: int) -> Any:
        acc = None
        for i in range(lo, hi):
            v = transform_op(a[i]) if b is None else transform_op(a[i], b[i])
            acc = v if acc is None else reduce_op(acc, v)
        return acc

    def run():
        partials = [p for p in host_bulk(policy, len(a), chunk)
                    if p is not None]
        acc = init
        for p in partials:
            acc = reduce_op(acc, p)
        return acc

    return finish(policy, run)


def count(policy: ExecutionPolicy, rng: Any, value: Any) -> Any:
    return count_if(policy, rng, lambda x: x == value)


def count_if(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(
            lambda a: jax.vmap(pred)(a.reshape(-1)).sum(dtype=jnp.int32), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def chunk(b: int, e: int) -> int:
        return sum(1 for i in range(b, e) if pred(arr[i]))

    return finish(policy,
                  lambda: sum(host_bulk(policy, len(arr), chunk)))


def _bool_query(policy: ExecutionPolicy, rng: Any, pred: Callable,
                combine: str) -> Any:
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            m = jax.vmap(pred)(a.reshape(-1))
            return jnp.all(m) if combine == "all" else jnp.any(m)
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    arr = to_numpy_view(rng)

    def chunk(b: int, e: int) -> bool:
        it = (bool(pred(arr[i])) for i in range(b, e))
        return all(it) if combine == "all" else any(it)

    def run():
        parts = host_bulk(policy, len(arr), chunk)
        return all(parts) if combine == "all" else any(parts)

    return finish(policy, run)


def all_of(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    return _bool_query(policy, rng, pred, "all")


def any_of(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    return _bool_query(policy, rng, pred, "any")


def none_of(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    r = any_of(policy, rng, pred)
    from ..futures.future import Future
    if isinstance(r, Future):
        return r.then(lambda f: not f.get())
    return not r


def min_element(policy: ExecutionPolicy, rng: Any) -> Any:
    return _minmax(policy, rng, "min")


def max_element(policy: ExecutionPolicy, rng: Any) -> Any:
    return _minmax(policy, rng, "max")


def minmax_element(policy: ExecutionPolicy, rng: Any) -> Any:
    return _minmax(policy, rng, "minmax")


def _minmax(policy: ExecutionPolicy, rng: Any, which: str) -> Any:
    """Returns the min/max VALUE (HPX returns iterators; values are the
    range-functional equivalent). minmax returns a (min, max) pair."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)
        if which == "min":
            fut = ex.async_execute(lambda a: a.min(), rng)
        elif which == "max":
            fut = ex.async_execute(lambda a: a.max(), rng)
        else:
            fut = ex.async_execute(
                lambda a: jnp.stack([a.min(), a.max()]), rng)
        return fut if policy.is_task else fut.get()
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if which == "min":
            return arr.min()
        if which == "max":
            return arr.max()
        return (arr.min(), arr.max())

    return finish(policy, run)


def equal(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)
        fut = ex.async_execute(lambda a, b: jnp.array_equal(a, b), rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        return bool(np.array_equal(a, b))

    return finish(policy, run)


def mismatch(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Index of first mismatch, or -1 (iterator-pair analog)."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            neq = (a.reshape(-1) != b.reshape(-1))
            any_neq = neq.any()
            idx = jnp.argmax(neq)
            return jnp.where(any_neq, idx, -1)
        fut = ex.async_execute(kernel, rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        neq = np.flatnonzero(a != b)
        # (via to_numpy_view), no device sync happens here
        return int(neq[0]) if neq.size else -1

    return finish(policy, run)


def find(policy: ExecutionPolicy, rng: Any, value: Any) -> Any:
    return find_if(policy, rng, lambda x: x == value)


def find_if(policy: ExecutionPolicy, rng: Any, pred: Callable) -> Any:
    """Index of first match, or -1."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            m = jax.vmap(pred)(a.reshape(-1))
            return jnp.where(m.any(), jnp.argmax(m), -1)
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    arr = to_numpy_view(rng)

    def chunk(b: int, e: int) -> int:
        for i in range(b, e):
            if pred(arr[i]):
                return i
        return -1

    def run():
        for idx in host_bulk(policy, len(arr), chunk):
            if idx != -1:
                return idx
        return -1

    return finish(policy, run)


def is_sorted_until(policy: ExecutionPolicy, rng: Any) -> Any:
    """Index of the first element breaking ascending order (the
    std::is_sorted_until iterator as an index), or len(rng) if sorted."""
    if is_device_policy(policy, rng):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            f = a.reshape(-1)
            if f.shape[0] <= 1:        # static shape: nothing to break
                return jnp.asarray(f.shape[0])
            bad = f[1:] < f[:-1]
            return jnp.where(bad.any(), jnp.argmax(bad) + 1, f.shape[0])
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        if len(arr) <= 1:
            return len(arr)
        bad = np.flatnonzero(arr[1:] < arr[:-1])
        return int(bad[0]) + 1 if bad.size else len(arr)

    return finish(policy, run)


def is_partitioned(policy: ExecutionPolicy, rng: Any,
                   pred: Callable) -> Any:
    """True when every pred-satisfying element precedes every
    non-satisfying one (std::is_partitioned)."""
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            m = jax.vmap(pred)(a.reshape(-1))
            # partitioned <=> mask is non-increasing
            return (m[1:].astype(jnp.int8)
                    <= m[:-1].astype(jnp.int8)).all()
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    arr = to_numpy_view(rng)

    def run():
        import numpy as np
        parts = host_bulk(
            policy, len(arr),
            lambda b, e: [bool(pred(arr[i])) for i in range(b, e)])
        mask = np.array([m for part in parts for m in part], dtype=bool)
        if mask.size <= 1:
            return True
        # partitioned <=> mask is non-increasing
        return bool((mask[1:].astype(np.int8)
                     <= mask[:-1].astype(np.int8)).all())

    return finish(policy, run)


def lexicographical_compare(policy: ExecutionPolicy, rng: Any,
                            rng2: Any) -> Any:
    """True when rng compares lexicographically LESS than rng2."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            fa, fb = a.reshape(-1), b.reshape(-1)
            n = min(fa.shape[0], fb.shape[0])
            if n == 0:                 # static: empty prefix — length
                return jnp.asarray(fa.shape[0] < fb.shape[0])  # decides
            lt = fa[:n] < fb[:n]
            ne = fa[:n] != fb[:n]
            first = jnp.where(ne.any(), jnp.argmax(ne), n)
            in_prefix = first < n
            # differ inside the common prefix: that position decides;
            # else the shorter range is the lesser
            return jnp.where(in_prefix,
                             lt[jnp.minimum(first, n - 1)],
                             fa.shape[0] < fb.shape[0])
        fut = ex.async_execute(kernel, rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: bool(f.get()))
        return bool(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        n = min(len(a), len(b))
        if n:
            ne = np.flatnonzero(a[:n] != b[:n])
            if ne.size:
                i = int(ne[0])
                return bool(a[i] < b[i])
        return len(a) < len(b)

    return finish(policy, run)


def find_first_of(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Index of the first element of rng that equals ANY element of
    rng2, or -1 (std::find_first_of)."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            fa, fb = a.reshape(-1), b.reshape(-1)
            if fa.shape[0] == 0 or fb.shape[0] == 0:   # static shapes
                return jnp.asarray(-1)
            m = (fa[:, None] == fb[None, :]).any(axis=1)
            return jnp.where(m.any(), jnp.argmax(m), -1)
        fut = ex.async_execute(kernel, rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        if len(a) == 0 or len(b) == 0:
            return -1
        hits = np.flatnonzero(np.isin(a, b))
        return int(hits[0]) if hits.size else -1

    return finish(policy, run)


def _window_match(jnp, fa, fb):
    """(n-m+1,) bool: window i of fa equals fb elementwise. Static
    shapes: the (n-m+1, m) window gather is one XLA gather the compiler
    tiles; fine at the m << n shapes subsequence search is for."""
    n, m = fa.shape[0], fb.shape[0]
    idx = jnp.arange(n - m + 1)[:, None] + jnp.arange(m)[None, :]
    return (fa[idx] == fb[None, :]).all(axis=1)


def search(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Index of the FIRST occurrence of subsequence rng2 in rng, or -1
    (std::search). An empty needle matches at 0."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            fa, fb = a.reshape(-1), b.reshape(-1)
            if fb.shape[0] == 0:                       # static shapes:
                return jnp.asarray(0)                  # empty needle
            if fb.shape[0] > fa.shape[0]:
                return jnp.asarray(-1)
            m = _window_match(jnp, fa, fb)
            return jnp.where(m.any(), jnp.argmax(m), -1)
        fut = ex.async_execute(kernel, rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        if len(b) == 0:
            return 0
        if len(b) > len(a):
            return -1
        starts = np.flatnonzero(a[:len(a) - len(b) + 1] == b[0])
        for i in starts:
            if np.array_equal(a[i:i + len(b)], b):
                return int(i)
        return -1

    return finish(policy, run)


def find_end(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """Index of the LAST occurrence of subsequence rng2 in rng, or -1
    (std::find_end). An empty needle matches at len(rng)."""
    if is_device_policy(policy, rng, rng2):
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a, b):
            fa, fb = a.reshape(-1), b.reshape(-1)
            if fb.shape[0] == 0:
                return jnp.asarray(fa.shape[0])
            if fb.shape[0] > fa.shape[0]:
                return jnp.asarray(-1)
            m = _window_match(jnp, fa, fb)
            last = m.shape[0] - 1 - jnp.argmax(m[::-1])
            return jnp.where(m.any(), last, -1)
        fut = ex.async_execute(kernel, rng, rng2)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    a, b = to_numpy_view(rng), to_numpy_view(rng2)

    def run():
        import numpy as np
        if len(b) == 0:
            return len(a)
        if len(b) > len(a):
            return -1
        starts = np.flatnonzero(a[:len(a) - len(b) + 1] == b[0])
        for i in starts[::-1]:
            if np.array_equal(a[i:i + len(b)], b):
                return int(i)
        return -1

    return finish(policy, run)


def search_n(policy: ExecutionPolicy, rng: Any, n: int,
             value: Any) -> Any:
    """Index of the first run of n consecutive elements equal to value,
    or -1 (std::search_n). n <= 0 matches at 0 (std semantics)."""
    if n <= 0:
        return finish(policy, lambda: 0)
    if is_device_policy(policy, rng):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(a):
            fa = a.reshape(-1)
            if n > fa.shape[0]:
                return jnp.asarray(-1)
            eq = (fa == value)
            # run length ending at i = (i+1) - (1 + last non-match
            # position <= i), the latter as a cummax of reset markers;
            # the first i with runlen >= n starts the match at i-n+1
            sz = fa.shape[0]
            run = jnp.arange(1, sz + 1) - jax.lax.cummax(
                jnp.where(eq, 0, jnp.arange(1, sz + 1)))
            hit = run >= n
            return jnp.where(hit.any(), jnp.argmax(hit) - (n - 1), -1)
        fut = ex.async_execute(kernel, rng)
        if policy.is_task:
            return fut.then(lambda f: int(f.get()))
        return int(fut.get())
    arr = to_numpy_view(rng)

    def run():
        count = 0
        for i, x in enumerate(arr):
            count = count + 1 if x == value else 0
            if count >= n:
                return i - n + 1
        return -1

    return finish(policy, run)


def contains(policy: ExecutionPolicy, rng: Any, value: Any) -> Any:
    """True when value appears in rng (std::ranges::contains)."""
    res = find(policy, rng, value)
    if policy.is_task:
        return res.then(lambda f: f.get() != -1)
    return res != -1


def contains_subrange(policy: ExecutionPolicy, rng: Any,
                      rng2: Any) -> Any:
    """True when rng2 appears as a contiguous subsequence of rng
    (std::ranges::contains_subrange)."""
    res = search(policy, rng, rng2)
    if policy.is_task:
        return res.then(lambda f: f.get() != -1)
    return res != -1


def starts_with(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """True when rng2 is a prefix of rng (std::ranges::starts_with)."""
    if len(rng2) > len(rng):
        return finish(policy, lambda: False)
    return equal(policy, rng[:len(rng2)], rng2)


def ends_with(policy: ExecutionPolicy, rng: Any, rng2: Any) -> Any:
    """True when rng2 is a suffix of rng (std::ranges::ends_with)."""
    if len(rng2) > len(rng):
        return finish(policy, lambda: False)
    if len(rng2) == 0:
        return finish(policy, lambda: True)
    return equal(policy, rng[len(rng) - len(rng2):], rng2)


def reduce_by_key(policy: ExecutionPolicy, keys: Any, values: Any,
                  op: Callable = _op.add) -> Any:
    """Collapse each run of CONSECUTIVE equal keys to one (key, reduced
    value) pair; returns (unique_run_keys, reduced_values)
    (hpx::experimental::reduce_by_key semantics — sort by key first for
    a global group-by).

    Device lowering: one jitted segmented associative scan — the carry
    is a (value, run_start) pair, so XLA's log-depth scan machinery does
    the segmentation (no data-dependent shapes inside jit); the
    data-dependent OUTPUT length compacts at the host boundary exactly
    like unique/copy_if."""
    if is_device_policy(policy, keys, values):
        import jax
        import jax.numpy as jnp
        ex = device_executor(policy)

        def kernel(ks, vs):
            ks, vs = ks.reshape(-1), vs.reshape(-1)
            n = ks.shape[0]
            if n == 0:                         # static shapes
                return jnp.zeros(0, bool), jnp.zeros(0, bool), vs
            start = jnp.concatenate(
                [jnp.ones(1, bool), ks[1:] != ks[:-1]])
            end = jnp.concatenate([start[1:], jnp.ones(1, bool)])
            known = _known_folds().get(op)
            combine = known[1] if known is not None else jax.vmap(op)

            def seg_combine(a, b):
                av, af = a
                bv, bf = b
                return jnp.where(bf, bv, combine(av, bv)), af | bf

            scanned, _ = jax.lax.associative_scan(
                seg_combine, (vs, start))
            return start, end, scanned
        fut = ex.async_execute(kernel, keys, values)

        def done(f):
            import numpy as np
            # hpxlint: disable-next=HPX002 — data-dependent gather: the
            # scan ran on device; unique-key extraction needs host
            # indexing to build the dynamic-shape result
            start, end, scanned = (np.asarray(x) for x in f.get())
            import jax.numpy as jnp
            # hpxlint: disable-next=HPX002 — host gather for the
            # dynamic-shape unique-keys result
            uk = jnp.asarray(np.asarray(keys).reshape(-1)[start])
            rv = jnp.asarray(scanned[end])
            return uk, rv
        return fut.then(done) if policy.is_task else done(fut)

    ks = to_numpy_view(keys).reshape(-1)
    vs = to_numpy_view(values).reshape(-1)

    def run():
        import numpy as np
        if len(ks) == 0:
            return ks.copy(), vs.copy()
        starts = np.flatnonzero(
            np.concatenate([[True], ks[1:] != ks[:-1]]))
        if op is _op.add:
            return ks[starts], np.add.reduceat(vs, starts)
        out = []
        bounds = np.append(starts, len(ks))
        for b, e in zip(bounds[:-1], bounds[1:]):
            acc = vs[b]
            for i in range(b + 1, e):
                acc = op(acc, vs[i])
            out.append(acc)
        return ks[starts], np.array(out)

    return finish(policy, run)
