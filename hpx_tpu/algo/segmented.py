"""Segmented algorithm dispatch.

Reference analog: libs/full/segmented_algorithms — when an algorithm
receives segmented iterators (partitioned_vector), HPX splits it into
per-segment local invocations (remote async to each segment's locality)
plus a combine step, dispatched via segmented_iterator_traits.

TPU-first collapse (SURVEY.md §7): the per-segment split IS the sharding.
Unwrapping a PartitionedVector yields its sharded jax.Array; the existing
device path then compiles ONE XLA program whose GSPMD partitioning runs
each shard's slice on its own device and inserts the combine collectives
(psum for reductions, all-to-all for sorts) over ICI. No per-segment
remote calls, no fan-in component — the compiler does the segmentation.

Shape-preserving algorithms rewrap the result in a PartitionedVector with
the source layout (sharding is propagated by XLA, so the rewrap is
zero-copy); reductions return scalars/host values unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

from ..containers.partitioned_vector import (
    PartitionedVector,
    PartitionedVectorView,
)
from ..futures.future import is_future


def _rewrap(result: Any, src: PartitionedVector) -> Any:
    """Wrap a same-length 1-D array result in a vector with src's layout.

    Host-path results are numpy arrays — those rewrap too, so the
    'shape-preserving algorithms return a PartitionedVector' contract
    holds regardless of which execution path the policy selected.
    """
    shape = getattr(result, "shape", None)
    # already; int() never touches device data
    if shape is not None and len(shape) == 1 and int(shape[0]) == src.size:
        return PartitionedVector.from_array(result, src.layout)
    return result


def segmentable(fn: Callable, preserves_shape: bool = False) -> Callable:
    """Add segmented-container dispatch to an algorithm entry point."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        src: Optional[PartitionedVector] = None
        segmented = False
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, PartitionedVector):
                if src is None:     # `or` would skip empty (falsy) vectors
                    src = a
                segmented = True
            elif isinstance(a, PartitionedVectorView):
                segmented = True
        if not segmented:
            return fn(*args, **kwargs)
        uargs = tuple(
            a.valid_array() if isinstance(a, PartitionedVector)
            else a.array() if isinstance(a, PartitionedVectorView) else a
            for a in args)
        ukw = {
            k: (v.valid_array() if isinstance(v, PartitionedVector)
                else v.array() if isinstance(v, PartitionedVectorView)
                else v)
            for k, v in kwargs.items()}
        result = fn(*uargs, **ukw)
        if not preserves_shape or src is None:
            return result
        if is_future(result):
            return result.then(lambda f: _rewrap(f.get(), src))
        return _rewrap(result, src)

    return wrapper
