"""Execution agents: cooperative yield/suspend for host tasks.

Reference analog: libs/core/execution_base (SURVEY.md §2.2) —
`hpx::execution_base::this_thread::{yield,suspend}`, `agent_ref`, and
`hpx::util::yield_while`. HPX parks a stackful coroutine and lets the
worker run other HPX threads; the TPU-native host runtime has no
stackful coroutines (futures/future.py's work-helping wait replaces
them), so "yield" here means: if the caller IS a pool worker, drain
one queued task from the pool (the same help_one primitive the
work-helping wait uses); otherwise release the GIL briefly. That is
exactly the cooperative behavior the reference's yield provides —
progress for other tasks while this one spins.

The VERIFY_LOCKS invariant applies (SURVEY.md §5.2): yielding while
holding a registered lock is the classic AMT deadlock, and
`yield_()`/`suspend()` run the same `verify_no_locks_held` check the
synchronization primitives use.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

from ..runtime.threadpool import current_worker_pool
from ..synchronization import verify_no_locks_held

__all__ = ["AgentRef", "agent", "yield_", "suspend", "yield_while",
           "this_task"]


@dataclasses.dataclass(frozen=True)
class AgentRef:
    """Identity of the current execution agent (hpx agent_ref analog):
    which pool's worker is running, or an external OS thread."""
    pool: Optional[str]          # None: not a pool worker
    in_worker: bool

    def description(self) -> str:
        return (f"worker@{self.pool}" if self.in_worker
                else "external-thread")


def agent() -> AgentRef:
    pool = current_worker_pool()
    if pool is not None:
        name = getattr(pool, "name", None) or type(pool).__name__
        return AgentRef(pool=name, in_worker=True)
    return AgentRef(pool=None, in_worker=False)


def yield_() -> bool:
    """Give other tasks a chance to run. On a pool worker: run one
    queued task inline (returns True if one ran). Elsewhere: plain OS
    yield, returns False."""
    verify_no_locks_held("yield")
    pool = current_worker_pool()
    if pool is not None:
        return bool(pool.help_one())
    # hpxlint: disable-next=HPX004 — this module IS the yield/backoff
    # substrate the rule points users to; sleep(0) is the OS yield
    time.sleep(0)
    return False


def suspend(seconds: float) -> None:
    """Cooperative sleep: keeps draining pool work until the deadline
    instead of parking the worker (the reference suspends the HPX
    thread; the worker analog must not go idle while work is queued)."""
    verify_no_locks_held("suspend")
    pool = current_worker_pool()
    if pool is None:
        # hpxlint: disable-next=HPX004 — substrate: nothing to help,
        # one plain wait
        time.sleep(seconds)
        return
    deadline = time.monotonic() + seconds
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        if not pool.help_one():
            # hpxlint: disable-next=HPX004 — substrate micro-park
            time.sleep(min(remaining, 0.0005))


def yield_while(pred: Callable[[], bool],
                timeout: Optional[float] = None,
                description: str = "yield_while") -> bool:
    """hpx::util::yield_while: spin-yield until pred() goes False.
    Returns False on timeout. The k-th retry backs off like the
    reference's yield_k (first retries pure yields, then micro-sleeps)."""
    verify_no_locks_held(description)
    deadline = None if timeout is None else time.monotonic() + timeout
    k = 0
    pool = current_worker_pool()
    while pred():
        if deadline is not None and time.monotonic() > deadline:
            return False
        helped = bool(pool.help_one()) if pool is not None else False
        if not helped:
            # hpxlint: disable-next=HPX004 — substrate yield_k backoff
            time.sleep(0 if k < 16 else 0.0002)
        k += 1
    return True


class _ThisTask:
    """Namespace object mirroring hpx::execution_base::this_thread."""
    agent = staticmethod(agent)
    yield_ = staticmethod(yield_)
    suspend = staticmethod(suspend)
    yield_while = staticmethod(yield_while)


this_task = _ThisTask()
