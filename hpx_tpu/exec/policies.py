"""Execution policies: seq / par / par_unseq / unseq / simd / par_simd.

Reference analog: libs/core/execution (hpx::execution::seq, par,
par_unseq, task policy modifier; rebindable via .on(executor) and
.with(params...) — SURVEY.md §3.3's CPO → policy → executor dispatch is
exactly what lets `par.on(tpu_executor)` reroute a whole algorithm).

Policies are immutable; .on/.with_/.task return modified copies. `simd`
maps to the device path (VPU vectorization inside one kernel) the way
HPX's datapar policies map to Vc/EVE lanes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

from .executors import BaseExecutor, ParallelExecutor, SequencedExecutor
from .params import ChunkSize, NumCores


@dataclasses.dataclass(frozen=True)
class ExecutionPolicy:
    name: str
    parallel: bool
    vectorize: bool = False
    is_task: bool = False
    executor: Optional[BaseExecutor] = None
    chunking: Optional[ChunkSize] = None
    cores: Optional[int] = None

    # -- rebinding (HPX .on / .with) ----------------------------------------
    def on(self, executor: BaseExecutor) -> "ExecutionPolicy":
        return dataclasses.replace(self, executor=executor)

    def with_(self, *params: Any) -> "ExecutionPolicy":
        p = self
        for prm in params:
            if isinstance(prm, ChunkSize):
                p = dataclasses.replace(p, chunking=prm)
            elif isinstance(prm, NumCores):
                p = dataclasses.replace(p, cores=prm.cores)
            else:
                from ..core.errors import BadParameter
                raise BadParameter(f"unknown execution parameter: {prm!r}")
        return p

    @property
    def task(self) -> "ExecutionPolicy":
        """par(task) analog: algorithms return futures instead of blocking."""
        return dataclasses.replace(self, is_task=True)

    # -- resolution ---------------------------------------------------------
    def get_executor(self) -> BaseExecutor:
        if self.executor is not None:
            return self.executor
        if not self.parallel:
            return _seq_exec
        return _par_exec

    def __repr__(self) -> str:
        bits = [self.name]
        if self.is_task:
            bits.append("task")
        if self.executor is not None:
            bits.append(f"on={self.executor!r}")
        return f"<policy {' '.join(bits)}>"


_seq_exec = SequencedExecutor()
_par_exec = ParallelExecutor()

seq = ExecutionPolicy("seq", parallel=False)
par = ExecutionPolicy("par", parallel=True)
par_unseq = ExecutionPolicy("par_unseq", parallel=True, vectorize=True)
unseq = ExecutionPolicy("unseq", parallel=False, vectorize=True)
simd = ExecutionPolicy("simd", parallel=False, vectorize=True)
par_simd = ExecutionPolicy("par_simd", parallel=True, vectorize=True)
# `task` as a standalone name mirrors hpx::execution::task used as
# `par(task)`; here: `par.task`.
