"""Executors — where work runs.

Reference analog: libs/core/executors. The executor CPO surface
(post / sync_execute / async_execute / bulk_async_execute / then_execute)
is kept verbatim; concrete executors:

  SequencedExecutor            hpx::execution::sequenced_executor
  ParallelExecutor             hpx::execution::parallel_executor (default)
  ThreadPoolExecutor           hpx::execution::thread_pool_executor (own pool)
  ForkJoinExecutor             hpx::execution::experimental::fork_join_executor
  TpuExecutor (exec/tpu.py)    the north-star device executor, replacing
                               hpx::cuda::experimental::cuda_executor

ParallelExecutor prefers the native C++ work-stealing pool
(hpx_tpu/native) and falls back to the pure-Python pool; both share the
same scheduling discipline and work-helping interface.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, List, Optional, Sequence

from ..futures.async_ import _run_into
from ..futures.future import Future, SharedState
from ..runtime.threadpool import WorkStealingPool, default_pool


class BaseExecutor:
    """Executor CPO surface. Subclasses implement post()."""

    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError

    def sync_execute(self, fn: Callable[..., Any], *args: Any,
                     **kwargs: Any) -> Any:
        return fn(*args, **kwargs)

    def async_execute(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> Future:
        state: SharedState = SharedState()
        self.post(_run_into, state, fn, args, kwargs)
        return Future(state)

    def then_execute(self, fn: Callable[..., Any], predecessor: Future,
                     *args: Any) -> Future:
        return predecessor.then(lambda f: fn(f, *args), executor=self)

    def bulk_async_execute(self, fn: Callable[..., Any],
                           indices: Sequence[Any], *args: Any) -> List[Future]:
        return [self.async_execute(fn, i, *args) for i in indices]

    def bulk_sync_execute(self, fn: Callable[..., Any],
                          indices: Sequence[Any], *args: Any) -> List[Any]:
        from ..futures.combinators import when_all
        futs = self.bulk_async_execute(fn, indices, *args)
        return [f.get() for f in when_all(futs).get()]

    @property
    def num_workers(self) -> int:
        return 1


class SequencedExecutor(BaseExecutor):
    """Runs everything inline, in order."""

    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        fn(*args, **kwargs)

    def async_execute(self, fn, *args, **kwargs) -> Future:
        state: SharedState = SharedState()
        _run_into(state, fn, args, kwargs)
        return Future(state)


def _make_pool(num_threads: Optional[int], name: str):
    """Native C++ pool when available/enabled, else the Python pool."""
    from ..core.config import runtime_config
    cfg = runtime_config()
    n = num_threads or cfg.os_threads()
    if cfg.get_bool("hpx.scheduler.native", True):
        try:
            from ..native.loader import NativePool
            return NativePool(n, name)
        except Exception:
            pass
    return WorkStealingPool(n, name)


class ParallelExecutor(BaseExecutor):
    """Default executor: schedules onto the (shared) host pool."""

    def __init__(self, pool: Any = None) -> None:
        self._pool = pool

    @property
    def pool(self):
        return self._pool if self._pool is not None else default_pool()

    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        self.pool.submit(fn, *args, **kwargs)

    @property
    def num_workers(self) -> int:
        return self.pool.num_threads


class ThreadPoolExecutor(ParallelExecutor):
    """Executor owning a private pool (restricted_thread_pool_executor)."""

    def __init__(self, num_threads: Optional[int] = None,
                 name: str = "pool-exec") -> None:
        super().__init__(_make_pool(num_threads, name))

    def shutdown(self) -> None:
        self.pool.shutdown()


class ForkJoinExecutor(BaseExecutor):
    """SPMD team executor for low-latency bulk regions.

    HPX's fork_join_executor keeps a worker team spinning between bulk
    calls to cut launch latency for tight iterative algorithms. Host
    analog: a dedicated pool + fan-out with a latch join (no respawn);
    the TPU analog of its 'team that stays hot' is a persistent
    shard_map program — see parallel/spmd.py (M6+).
    """

    def __init__(self, num_threads: Optional[int] = None) -> None:
        self._pool = _make_pool(num_threads, "fork-join")

    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        self._pool.submit(fn, *args, **kwargs)

    def bulk_sync_execute(self, fn: Callable[..., Any],
                          indices: Sequence[Any], *args: Any) -> List[Any]:
        from ..synchronization import Latch
        n = len(indices)
        if n == 0:
            return []
        results: List[Any] = [None] * n
        errors: List[BaseException] = []
        latch = Latch(n)

        def run(k: int, idx: Any) -> None:
            try:
                results[k] = fn(idx, *args)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
            finally:
                latch.count_down()

        for k, idx in enumerate(indices):
            self._pool.submit(run, k, idx)
        # The calling thread helps execute the team's work (fork-join
        # semantics: the caller is part of the team).
        while not latch.try_wait():
            if not self._pool.help_one():
                latch.wait(0.0005)
        if errors:
            raise errors[0]
        return results

    @property
    def num_workers(self) -> int:
        return self._pool.num_threads

    def shutdown(self) -> None:
        self._pool.shutdown()
