"""P2300 std::execution (senders/receivers) prototype.

Reference analog: libs/core/execution + executors
(`hpx::execution::experimental`: `schedule/just/then/when_all/bulk/
continues_on/let_value/sync_wait/start_detached`, `thread_pool_scheduler`,
`run_loop` — HPX carries a full P2300 implementation; SURVEY.md §2.2).

TPU-first shape: the sender algebra is the host-side composition layer.
`tpu_scheduler()` hands work to the TpuExecutor (compiled dispatch), so

    sndr = schedule(tpu_scheduler()) | then(lambda: x) | then(jit_fn)
    value = sync_wait(sndr)

builds the same pipeline a thread_pool_scheduler would, with the leaf
work running as XLA programs.

Protocol (duck-typed, like the reference's concepts):
  sender:   .connect(receiver) -> operation_state
  op-state: .start() -> None
  receiver: .set_value(*vals) / .set_error(exc) / .set_stopped()

Composition sugar: `sender | adaptor` pipes, matching P2300 usage.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple

from ..futures.future import Future, SharedState
from ..synchronization import Mutex

__all__ = [
    "Sender", "schedule", "just", "just_error", "just_stopped", "then",
    "then_on_device", "upon_error", "let_value", "when_all", "bulk",
    "continues_on",
    "transfer", "sync_wait", "start_detached", "ensure_started",
    "as_future", "ThreadPoolScheduler", "thread_pool_scheduler",
    "TpuScheduler", "tpu_scheduler", "InlineScheduler", "inline_scheduler",
    "RunLoop", "run_loop",
]


# ---------------------------------------------------------------------------
# core protocol helpers
# ---------------------------------------------------------------------------

class Sender:
    """Base class: provides `|` piping and .connect dispatch."""

    def connect(self, receiver: Any):
        raise NotImplementedError

    def __or__(self, adaptor: Callable[["Sender"], "Sender"]) -> "Sender":
        return adaptor(self)


class _FnOp:
    __slots__ = ("_fn",)

    def __init__(self, fn: Callable[[], None]) -> None:
        self._fn = fn

    def start(self) -> None:
        self._fn()


def _deliver(receiver: Any, fn: Callable[[], Tuple]) -> None:
    """Run fn; route its value/exception into the receiver."""
    try:
        vals = fn()
    except BaseException as e:  # noqa: BLE001
        receiver.set_error(e)
        return
    receiver.set_value(*vals)


# ---------------------------------------------------------------------------
# schedulers
# ---------------------------------------------------------------------------

class _ScheduleSender(Sender):
    """sender-of-nothing that completes on the scheduler's context."""

    __slots__ = ("_submit",)

    def __init__(self, submit: Callable[[Callable[[], None]], None]) -> None:
        self._submit = submit

    def connect(self, receiver: Any):
        return _FnOp(lambda: self._submit(
            lambda: _deliver(receiver, tuple)))


class ThreadPoolScheduler:
    """hpx::execution::experimental::thread_pool_scheduler analog."""

    def __init__(self, pool: Any = None) -> None:
        if pool is None:
            from ..runtime.threadpool import default_pool
            pool = default_pool()
        self._pool = pool

    def schedule(self) -> Sender:
        return _ScheduleSender(lambda fn: self._pool.submit(fn))


class InlineScheduler:
    """Completes inline on the calling thread (sequenced execution)."""

    def schedule(self) -> Sender:
        return _ScheduleSender(lambda fn: fn())


class TpuScheduler:
    """Scheduler whose context is the device-dispatch path: schedule()
    completes on a host pool thread, and `then_on_device` continuations
    dispatch COMPILED programs through its TpuExecutor (the reference's
    async_cuda -> sender bridge, libs/core/async_cuda)."""

    def __init__(self, executor: Any = None) -> None:
        if executor is None:
            from .tpu import TpuExecutor
            executor = TpuExecutor()
        self.executor = executor

    def schedule(self) -> Sender:
        from ..runtime.threadpool import default_pool
        pool = default_pool()
        return _ScheduleSender(lambda fn: pool.submit(fn))


def thread_pool_scheduler(pool: Any = None) -> ThreadPoolScheduler:
    return ThreadPoolScheduler(pool)


def inline_scheduler() -> InlineScheduler:
    return InlineScheduler()


def tpu_scheduler(executor: Any = None) -> TpuScheduler:
    return TpuScheduler(executor)


def schedule(scheduler: Any) -> Sender:
    """P2300 schedule(sch) -> sender completing on sch's context."""
    return scheduler.schedule()


# ---------------------------------------------------------------------------
# sender factories
# ---------------------------------------------------------------------------

class _JustSender(Sender):
    __slots__ = ("_vals",)

    def __init__(self, vals: Tuple) -> None:
        self._vals = vals

    def connect(self, receiver: Any):
        return _FnOp(lambda: receiver.set_value(*self._vals))


class _JustErrorSender(Sender):
    __slots__ = ("_exc",)

    def __init__(self, exc: BaseException) -> None:
        self._exc = exc

    def connect(self, receiver: Any):
        return _FnOp(lambda: receiver.set_error(self._exc))


class _JustStoppedSender(Sender):
    def connect(self, receiver: Any):
        return _FnOp(receiver.set_stopped)


def just(*vals: Any) -> Sender:
    return _JustSender(vals)


def just_error(exc: BaseException) -> Sender:
    return _JustErrorSender(exc)


def just_stopped() -> Sender:
    return _JustStoppedSender()


# ---------------------------------------------------------------------------
# adaptors
# ---------------------------------------------------------------------------

class _Passthrough:
    """Receiver base forwarding everything to a wrapped receiver."""

    __slots__ = ("_rx",)

    def __init__(self, rx: Any) -> None:
        self._rx = rx

    def set_value(self, *vals: Any) -> None:
        self._rx.set_value(*vals)

    def set_error(self, exc: BaseException) -> None:
        self._rx.set_error(exc)

    def set_stopped(self) -> None:
        self._rx.set_stopped()


class _AdaptorSender(Sender):
    __slots__ = ("_up", "_make_rx")

    def __init__(self, up: Sender, make_rx: Callable[[Any], Any]) -> None:
        self._up = up
        self._make_rx = make_rx

    def connect(self, receiver: Any):
        return self._up.connect(self._make_rx(receiver))


def then(fn: Callable[..., Any]):
    """sndr | then(f): transform the value channel."""
    def adapt(up: Sender) -> Sender:
        class Rx(_Passthrough):
            def set_value(self, *vals: Any) -> None:
                _deliver(self._rx, lambda: (fn(*vals),))
        return _AdaptorSender(up, Rx)
    return adapt


def then_on_device(fn: Callable[..., Any], executor: Any = None):
    """sndr | then_on_device(jit_fn): the TPU-native `then` — the
    continuation is compiled once (executor jit cache) and dispatched to
    the device; the value channel carries the resulting jax.Array."""
    # one executor per adaptor (not per delivery): a fresh executor per
    # set_value would start from an empty jit cache every run
    if executor is None:
        from .tpu import TpuExecutor
        executor = TpuExecutor()

    def adapt(up: Sender) -> Sender:
        class Rx(_Passthrough):
            def set_value(self, *vals: Any) -> None:
                _deliver(self._rx,
                         lambda: (executor.sync_execute(fn, *vals),))
        return _AdaptorSender(up, Rx)
    return adapt


def upon_error(fn: Callable[[BaseException], Any]):
    """sndr | upon_error(f): recover from the error channel."""
    def adapt(up: Sender) -> Sender:
        class Rx(_Passthrough):
            def set_error(self, exc: BaseException) -> None:
                _deliver(self._rx, lambda: (fn(exc),))
        return _AdaptorSender(up, Rx)
    return adapt


def let_value(fn: Callable[..., Sender]):
    """sndr | let_value(f): f(value) returns a new sender; pipe into it
    (monadic bind)."""
    def adapt(up: Sender) -> Sender:
        class Rx(_Passthrough):
            def set_value(self, *vals: Any) -> None:
                try:
                    inner = fn(*vals)
                    op = inner.connect(self._rx)
                except BaseException as e:  # noqa: BLE001
                    self._rx.set_error(e)
                    return
                op.start()
        return _AdaptorSender(up, Rx)
    return adapt


def bulk(shape: int, fn: Callable[..., None]):
    """sndr | bulk(n, f): run f(i, *values) for i in range(n), then
    forward the original values (P2300 bulk semantics, sequential here;
    the parallel-lowered path is the algorithms layer)."""
    def adapt(up: Sender) -> Sender:
        class Rx(_Passthrough):
            def set_value(self, *vals: Any) -> None:
                def work() -> Tuple:
                    for i in range(shape):
                        fn(i, *vals)
                    return vals
                _deliver(self._rx, work)
        return _AdaptorSender(up, Rx)
    return adapt


def continues_on(scheduler: Any):
    """sndr | continues_on(sch): complete downstream on sch's context
    (P2300 continues_on / former `transfer`)."""
    def adapt(up: Sender) -> Sender:
        class Rx(_Passthrough):
            def set_value(self, *vals: Any) -> None:
                sub = scheduler.schedule().connect(
                    _Resume(self._rx, vals))
                sub.start()
        return _AdaptorSender(up, Rx)
    return adapt


transfer = continues_on   # HPX's older spelling


class _Resume(_Passthrough):
    __slots__ = ("_vals",)

    def __init__(self, rx: Any, vals: Tuple) -> None:
        super().__init__(rx)
        self._vals = vals

    def set_value(self, *_ignored: Any) -> None:
        self._rx.set_value(*self._vals)


class _WhenAllSender(Sender):
    __slots__ = ("_senders",)

    def __init__(self, senders: Tuple[Sender, ...]) -> None:
        self._senders = senders

    def connect(self, receiver: Any):
        n = len(self._senders)
        if n == 0:
            # empty when_all completes immediately (P2300 semantics)
            return _FnOp(receiver.set_value)
        state = {"left": n, "vals": [None] * n, "done": False}
        lock = Mutex()

        def finish_error(exc: BaseException) -> None:
            with lock:
                if state["done"]:
                    return
                state["done"] = True
            receiver.set_error(exc)

        def finish_stopped() -> None:
            with lock:
                if state["done"]:
                    return
                state["done"] = True
            receiver.set_stopped()

        class Rx:
            __slots__ = ("_i",)

            def __init__(self, i: int) -> None:
                self._i = i

            def set_value(self, *vals: Any) -> None:
                with lock:
                    if state["done"]:
                        return
                    state["vals"][self._i] = vals
                    state["left"] -= 1
                    if state["left"]:
                        return
                    state["done"] = True
                out: List[Any] = []
                for v in state["vals"]:
                    out.extend(v)
                receiver.set_value(*out)

            set_error = staticmethod(finish_error)
            set_stopped = staticmethod(finish_stopped)

        ops = [s.connect(Rx(i)) for i, s in enumerate(self._senders)]

        class Op:
            def start(self) -> None:
                for op in ops:
                    op.start()

        return Op()


def when_all(*senders: Sender) -> Sender:
    """Combine senders; completes with the concatenated values."""
    return _WhenAllSender(senders)


# ---------------------------------------------------------------------------
# consumers
# ---------------------------------------------------------------------------

class _FutureReceiver:
    __slots__ = ("_st",)

    def __init__(self, st: SharedState) -> None:
        self._st = st

    def set_value(self, *vals: Any) -> None:
        if len(vals) == 0:
            self._st.set_value(None)
        elif len(vals) == 1:
            self._st.set_value(vals[0])
        else:
            self._st.set_value(tuple(vals))

    def set_error(self, exc: BaseException) -> None:
        self._st.set_exception(exc)

    def set_stopped(self) -> None:
        from ..core.errors import Error, HpxError
        self._st.set_exception(
            HpxError(Error.yield_aborted, "sender stopped"))


def as_future(sender: Sender) -> Future:
    """Bridge into the futures world (ensure_started semantics)."""
    st = SharedState()
    sender.connect(_FutureReceiver(st)).start()
    return Future(st)


ensure_started = as_future


def sync_wait(sender: Sender, timeout: Optional[float] = None) -> Any:
    """Run the sender to completion; return its (possibly tuple) value.
    Stopped completions return None (the reference returns empty
    optional)."""
    from ..core.errors import Error, HpxError
    try:
        return as_future(sender).get(timeout)
    except HpxError as e:
        if e.code == Error.yield_aborted:
            return None
        raise


def start_detached(sender: Sender) -> None:
    """Fire and forget; errors surface on the default error stream."""
    class Rx:
        def set_value(self, *vals: Any) -> None:
            pass

        def set_error(self, exc: BaseException) -> None:
            import traceback
            traceback.print_exception(type(exc), exc, exc.__traceback__)

        def set_stopped(self) -> None:
            pass

    sender.connect(Rx()).start()


# ---------------------------------------------------------------------------
# run_loop
# ---------------------------------------------------------------------------

class RunLoop:
    """P2300 run_loop: a manually driven FIFO execution context.

        loop = run_loop()
        sndr = schedule(loop.get_scheduler()) | then(f)
        start_detached(sndr)
        loop.finish(); loop.run()     # drains on the calling thread
    """

    def __init__(self) -> None:
        self._q: List[Callable[[], None]] = []
        self._cv = threading.Condition()
        self._finishing = False

    def _submit(self, fn: Callable[[], None]) -> None:
        with self._cv:
            self._q.append(fn)
            self._cv.notify_all()

    def get_scheduler(self):
        outer = self

        class _Sched:
            def schedule(self) -> Sender:
                return _ScheduleSender(outer._submit)
        return _Sched()

    def run(self) -> None:
        """Drain until finish() is called and the queue empties."""
        while True:
            with self._cv:
                while not self._q and not self._finishing:
                    self._cv.wait()
                if not self._q and self._finishing:
                    return
                fn = self._q.pop(0)
            fn()

    def finish(self) -> None:
        with self._cv:
            self._finishing = True
            self._cv.notify_all()


def run_loop() -> RunLoop:
    return RunLoop()
