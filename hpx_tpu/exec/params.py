"""Execution parameters (chunking control).

Reference analog: libs/core/executors execution parameters —
static_chunk_size, auto_chunk_size, dynamic_chunk_size, guided_chunk_size,
num_cores. Used by the algorithm partitioners (algo/) to decide how many
tasks a bulk region becomes on the HOST path. On the TPU path chunking is
XLA's job — the whole range lowers to one compiled kernel — so these only
shape host-pool execution (and the grid of Pallas kernels where used).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ChunkSize:
    """Base: yields per-chunk sizes for a range of `count` iterations."""

    def chunks(self, count: int, num_workers: int) -> list:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class StaticChunkSize(ChunkSize):
    """Fixed chunk size; 0 = count/num_workers (HPX default static)."""

    size: int = 0

    def chunks(self, count: int, num_workers: int) -> list:
        if count <= 0:
            return []
        size = self.size
        if size <= 0:
            size = max(1, (count + num_workers - 1) // num_workers)
        return [min(size, count - i) for i in range(0, count, size)]


@dataclasses.dataclass(frozen=True)
class AutoChunkSize(ChunkSize):
    """HPX auto_chunk_size measures ~1% of iterations to pick a grain
    hitting a target chunk time. Host analog: aim for ~4 chunks/worker
    (amortizes Python dispatch overhead while load-balancing);
    ``min_size`` floors the grain (hpx.exec.min_chunk_size)."""

    chunks_per_worker: int = 4
    min_size: int = 1

    def chunks(self, count: int, num_workers: int) -> list:
        if count <= 0:
            return []
        target = max(self.min_size, 1,
                     count // max(1, num_workers * self.chunks_per_worker))
        return [min(target, count - i) for i in range(0, count, target)]


@dataclasses.dataclass(frozen=True)
class DynamicChunkSize(ChunkSize):
    """Small fixed chunks, consumed dynamically (load imbalance friendly)."""

    size: int = 1

    def chunks(self, count: int, num_workers: int) -> list:
        size = max(1, self.size)
        return [min(size, count - i) for i in range(0, count, size)]


@dataclasses.dataclass(frozen=True)
class GuidedChunkSize(ChunkSize):
    """OpenMP-guided: exponentially decreasing chunks, floor min_size."""

    min_size: int = 1

    def chunks(self, count: int, num_workers: int) -> list:
        out = []
        remaining = count
        while remaining > 0:
            c = max(self.min_size, remaining // (2 * max(1, num_workers)))
            c = min(c, remaining)
            out.append(c)
            remaining -= c
        return out


@dataclasses.dataclass(frozen=True)
class NumCores:
    """Restrict a policy to n workers (hpx::execution::experimental::num_cores)."""

    cores: int = 0


def default_chunker() -> ChunkSize:
    """The chunker used when a policy carries no explicit ChunkSize —
    the hpx.exec.default_chunk / hpx.exec.min_chunk_size knobs:

      auto (default) | static[:N] | dynamic[:N] | guided | N (= static:N)
    """
    from ..core.config import runtime_config
    cfg = runtime_config()
    spec = (cfg.get("hpx.exec.default_chunk") or "auto").strip().lower()
    min_size = max(1, cfg.get_int("hpx.exec.min_chunk_size", 1))
    kind, _, arg = spec.partition(":")
    if kind == "auto" or kind == "":
        return AutoChunkSize(min_size=min_size)
    if kind == "static":
        return StaticChunkSize(int(arg) if arg else 0)
    if kind == "dynamic":
        return DynamicChunkSize(int(arg) if arg else max(1, min_size))
    if kind == "guided":
        return GuidedChunkSize(min_size=min_size)
    if kind.isdigit():
        return StaticChunkSize(int(kind))
    from ..core.errors import BadParameter
    raise BadParameter(
        f"hpx.exec.default_chunk={spec!r}: expected "
        "auto | static[:N] | dynamic[:N] | guided | N", "config")


static_chunk_size = StaticChunkSize
auto_chunk_size = AutoChunkSize
dynamic_chunk_size = DynamicChunkSize
guided_chunk_size = GuidedChunkSize
num_cores = NumCores
