from .executors import (  # noqa: F401
    BaseExecutor,
    ForkJoinExecutor,
    ParallelExecutor,
    SequencedExecutor,
    ThreadPoolExecutor,
)
from .params import (  # noqa: F401
    AutoChunkSize,
    ChunkSize,
    DynamicChunkSize,
    GuidedChunkSize,
    NumCores,
    StaticChunkSize,
    auto_chunk_size,
    dynamic_chunk_size,
    guided_chunk_size,
    num_cores,
    static_chunk_size,
)
from .policies import (  # noqa: F401
    ExecutionPolicy,
    par,
    par_simd,
    par_unseq,
    seq,
    simd,
    unseq,
)
from .tpu import Target, TpuExecutor, default_target, get_future, get_targets  # noqa: F401
from . import p2300  # noqa: F401
from .execution_base import AgentRef, this_task, yield_while  # noqa: F401
