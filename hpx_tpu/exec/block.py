"""BlockExecutor — bulk work distributed over a set of compute targets.

Reference analog: hpx::compute::host::block_executor
(libs/core/compute_local): an executor wrapping N NUMA-domain targets
that round-robins bulk work across per-target executors, used by the
reference's STREAM and Jacobi benchmark configurations. TPU-first
reading: the "NUMA domains" are addressable devices; each chunk of a
bulk call is dispatched to its target's device executor, and data placed
with `block_allocator`-style placement (place_blocks) lands shard i on
device i so the bulk work is local to its target.

For true single-program multi-device execution prefer the sharded path
(pjit/shard_map — parallel/); BlockExecutor is the explicit-placement
model for irregular or per-device-distinct work (the reference uses
block_executor exactly the same way relative to its SPMD constructs).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

from ..futures.future import Future
from .executors import BaseExecutor
from .tpu import Target, TpuExecutor, get_targets


class BlockExecutor(BaseExecutor):
    """Round-robins work over one executor per target."""

    def __init__(self, targets: Optional[Sequence[Target]] = None,
                 eager: Optional[bool] = None) -> None:
        import itertools
        self.targets = tuple(targets) if targets else get_targets()
        self._execs = [TpuExecutor(t, eager=eager) for t in self.targets]
        self._next = itertools.count()  # atomic under the GIL

    def _pick(self) -> TpuExecutor:
        return self._execs[next(self._next) % len(self._execs)]

    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        self._pick().post(fn, *args, **kwargs)

    def sync_execute(self, fn: Callable[..., Any], *args: Any,
                     **kwargs: Any) -> Any:
        return self._pick().sync_execute(fn, *args, **kwargs)

    def async_execute(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> Future:
        return self._pick().async_execute(fn, *args, **kwargs)

    def bulk_async_execute(self, fn: Callable[..., Any],
                           indices: Sequence[Any], *args: Any) -> List[Future]:
        # chunk i -> target i % N, in index order (HPX block distribution)
        return [self._execs[k % len(self._execs)].async_execute(fn, i, *args)
                for k, i in enumerate(indices)]

    @property
    def num_workers(self) -> int:
        return len(self._execs)

    def __repr__(self) -> str:
        return f"<BlockExecutor over {len(self._execs)} targets>"


def place_blocks(arrays: Sequence[Any],
                 targets: Optional[Sequence[Target]] = None) -> List[Any]:
    """block_allocator analog: put array i on target i % N's device."""
    import jax
    tgts = tuple(targets) if targets else get_targets()
    return [jax.device_put(a, tgts[i % len(tgts)].device)
            for i, a in enumerate(arrays)]
