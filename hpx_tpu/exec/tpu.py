"""TPU compute targets and the tpu_executor — the north-star device path.

Reference analog: libs/core/compute_local (hpx::compute::host::target,
block_executor) and libs/core/async_cuda (hpx::cuda::experimental::
cuda_executor whose async_execute launches a kernel and returns a future
completed by event polling integrated into the scheduler). Here the
"kernel launch" is an XLA program dispatch and the "event" is jax.Array
readiness.

Two completion models (hpx.tpu.eager_futures):

  eager (default): the returned future is READY immediately, holding the
    dispatched (possibly still-executing) jax.Array. JAX dispatch is
    asynchronous; downstream consumers that feed the array into further
    XLA programs get correct dataflow ordering from XLA itself, with zero
    host synchronization. This is the TPU-first answer to the task
    granularity chasm: the host races ahead, the device pipeline stays
    full. Materializing the value (np.asarray / block_until_ready) is the
    only synchronizing operation — exactly like .get() on an HPX future
    of GPU work.

  watched: the future completes only when the device result is actually
    ready (a watcher thread calls block_until_ready). Matches HPX
    semantics exactly (future ready == computation done) at the price of
    host round-trips; use for host-side control decisions on device data.

Error semantics (pinned by tests/test_executor_errors.py):
  * trace/compile failures -> exceptional future in BOTH modes
    (async_execute never leaks a raise).
  * device-side failures after a successful dispatch:
      watched — the watcher observes them; the future completes
      exceptionally and .get() raises (HPX contract).
      eager   — the future is already ready holding the in-flight
      array; the failure surfaces at the first MATERIALIZATION
      (np.asarray / block_until_ready / target.synchronize), NOT at
      .get(). This is the ONE deliberate divergence from HPX future
      semantics, the price of zero-sync dispatch — flip
      hpx.tpu.eager_futures=0 when exactness matters.
"""

from __future__ import annotations

import functools
import os
import queue as _queue
import threading
from typing import Any, Callable, List, Optional, Sequence

from ..core.config import runtime_config
from ..futures.future import (Future, SharedState, make_exceptional_future,
                              make_ready_future)
from .executors import BaseExecutor
from ..synchronization import Mutex


class Target:
    """A compute target = one addressable device (hpx::compute target).

    `synchronize()` is cuda::target::synchronize's analog.
    """

    def __init__(self, device: Any) -> None:
        self.device = device

    @property
    def platform(self) -> str:
        return self.device.platform

    @property
    def id(self) -> int:
        return self.device.id

    def synchronize(self) -> None:
        import jax
        # hpxlint: disable-next=HPX002 — synchronize() IS the
        # explicit fence API; blocking is its contract
        # Fence: a trivial computation placed on this device, blocked on.
        jax.block_until_ready(jax.device_put(0, self.device))

    def __repr__(self) -> str:
        return f"<Target {self.device}>"


@functools.lru_cache(maxsize=None)
def get_targets() -> tuple:
    """All device targets (hpx::compute::host::get_targets analog)."""
    import jax
    return tuple(Target(d) for d in jax.devices())


def default_target() -> Target:
    return get_targets()[0]


class _Watcher:
    """Completes futures when device values become ready.

    HPX integrates CUDA event polling into the scheduler loop; JAX has no
    public done-callback, so a small dedicated watcher pool calls
    block_until_ready off-thread (SURVEY.md §7 mitigation). Threads are
    started lazily and are daemons.
    """

    def __init__(self, num_threads: int) -> None:
        self._q: _queue.SimpleQueue = _queue.SimpleQueue()
        self._n = max(1, num_threads)
        self._started = False
        self._lock = Mutex()

    def _ensure_started(self) -> None:
        if self._started:
            return
        with self._lock:
            if self._started:
                return
            for i in range(self._n):
                threading.Thread(target=self._loop, daemon=True,
                                 name=f"hpx-tpu-watcher-{i}").start()
            self._started = True

    def _loop(self) -> None:
        import jax
        while True:
            state, value = self._q.get()
            try:
                # hpxlint: disable-next=HPX002 — the watcher thread
                # exists to absorb this block OFF the dispatch path (the
                # fix the rule suggests); this is that implementation
                jax.block_until_ready(value)
                state.set_value(value)
            except BaseException as e:  # noqa: BLE001 — device errors
                state.set_exception(e)

    def watch(self, value: Any) -> Future:
        self._ensure_started()
        state: SharedState = SharedState()
        self._q.put((state, value))
        return Future(state)


_watcher: Optional[_Watcher] = None
_watcher_lock = Mutex()


def _get_watcher() -> _Watcher:
    global _watcher
    if _watcher is None:
        with _watcher_lock:
            if _watcher is None:
                cfg = runtime_config()
                _watcher = _Watcher(cfg.get_int("hpx.tpu.watcher_threads", 2))
    return _watcher


def get_future(value: Any) -> Future:
    """Future tied to a dispatched jax value's completion
    (cuda_executor get_future(stream) analog)."""
    return _get_watcher().watch(value)


class TpuExecutor(BaseExecutor):
    """The device executor: async_execute dispatches a jitted XLA program.

    `par.on(TpuExecutor())` reroutes whole parallel algorithms onto the
    device (the executor/execution-policy plugin boundary is the only
    user-facing change — BASELINE.json north star).
    """

    import collections as _collections
    _jit_cache: "_collections.OrderedDict" = _collections.OrderedDict()
    _jit_cache_max = 4096
    _jit_lru: "_collections.OrderedDict" = _collections.OrderedDict()
    _jit_lru_max = 256
    # perf-counter feeds (class-level: all instances share the device
    # path). compile_count counts jit-wrapper cache misses — a proxy for
    # XLA compilations, which happen per (wrapper, shape) at first call.
    dispatch_count = 0
    compile_count = 0

    def __init__(self, target: Optional[Target] = None,
                 eager: Optional[bool] = None,
                 donate_argnums: tuple = ()) -> None:
        self.target = target if target is not None else default_target()
        if eager is None:
            eager = runtime_config().get_bool("hpx.tpu.eager_futures", True)
        self.eager = eager
        # donated positions alias into the outputs: callers must not
        # touch those bindings after dispatch (hpxlint HPX020 flags
        # use-after-donate through def-use chains)
        self._donate = donate_argnums

    # -- compilation --------------------------------------------------------
    def _compiled(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        import jax
        from ..utils.fnkey import fn_cache_key
        # Structural key: algorithm call sites create fresh lambdas every
        # call; identity keying would re-jit (and re-compile the XLA
        # program) each time. Cache is class-level so short-lived executor
        # instances share compilations. Identity-keyed fallbacks (closures
        # capturing arrays etc.) go to a bounded LRU so they can't pin
        # captured data for the process lifetime.
        fkey = fn_cache_key(fn)
        key = (fkey, self._donate)
        if fkey is fn:  # identity fallback
            lru = TpuExecutor._jit_lru
            cached = lru.get(key)
            if cached is None:
                TpuExecutor.compile_count += 1
                cached = jax.jit(fn, donate_argnums=self._donate)
                lru[key] = cached
                if len(lru) > TpuExecutor._jit_lru_max:
                    lru.popitem(last=False)
            else:
                lru.move_to_end(key)
            return cached
        cache = TpuExecutor._jit_cache
        cached = cache.get(key)
        if cached is None:
            TpuExecutor.compile_count += 1
            cached = jax.jit(fn, donate_argnums=self._donate)
            cache[key] = cached
            # structural keys embed closure scalars, so loops over varying
            # captures (e.g. a learning-rate schedule) still create new
            # entries — bound this cache too
            if len(cache) > TpuExecutor._jit_cache_max:
                cache.pop(next(iter(cache)))
        else:
            cache.move_to_end(key)
        return cached

    # -- executor surface ----------------------------------------------------
    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        # Raw call, NO jit: post is the generic fire-and-forget CPO that
        # async_/then/dataflow feed with arbitrary host callables (e.g.
        # _run_into closures) — jitting those is a type error. A jax fn
        # called raw still dispatches asynchronously. Use post_compiled
        # for an explicit compiled dispatch-and-forget.
        fn(*args, **kwargs)

    def post_compiled(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> None:
        TpuExecutor.dispatch_count += 1
        self._compiled(fn)(*args, **kwargs)

    def sync_execute(self, fn: Callable[..., Any], *args: Any,
                     **kwargs: Any) -> Any:
        import jax
        TpuExecutor.dispatch_count += 1
        # hpxlint: disable-next=HPX002 — sync_execute()'s contract
        # is to block until the result is ready
        return jax.block_until_ready(self._compiled(fn)(*args, **kwargs))

    def async_execute(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> Future:
        TpuExecutor.dispatch_count += 1
        try:
            value = self._compiled(fn)(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — trace/compile errors
            return make_exceptional_future(e)
        if self.eager:
            return make_ready_future(value)
        return get_future(value)

    def async_execute_raw(self, fn: Callable[..., Any], *args: Any,
                          **kwargs: Any) -> Future:
        """Dispatch an already-compiled/arbitrary callable (no jit wrap)."""
        TpuExecutor.dispatch_count += 1
        try:
            value = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            return make_exceptional_future(e)
        return make_ready_future(value) if self.eager else get_future(value)

    def then_execute(self, fn: Callable[..., Any], predecessor: Future,
                     *args: Any) -> Future:
        compiled = self._compiled(fn)
        if self.eager:
            return predecessor.then(lambda f: compiled(f.get(), *args))
        # watched mode: the continuation's future must complete only when
        # the device result is ready; then() unwraps the watcher future
        return predecessor.then(
            lambda f: get_future(compiled(f.get(), *args)))

    @property
    def num_workers(self) -> int:
        return 1  # one device; parallelism is inside the XLA program

    def __repr__(self) -> str:
        mode = "eager" if self.eager else "watched"
        return f"<TpuExecutor {self.target} {mode}>"
