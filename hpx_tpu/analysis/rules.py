"""The hpxlint rule pack — this runtime's real hazard classes.

Each rule is a small `ast` walk over one file.  Rules are heuristic by
design: they trade a few suppressible false positives for catching the
failure modes that are silent at runtime (SURVEY.md §5.2 suspension
deadlocks, §7 host/device sync stalls).  Every rule's docstring states
the hazard and the fix — the CLI prints these for ``--list-rules``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set, Tuple

from .engine import FileContext, Finding, Rule, register

# layers containing executor/continuation code where a hidden device
# sync stalls the dispatch pipeline (HPX002's scope)
HOT_SUBPATHS = ("hpx_tpu/futures", "hpx_tpu/exec",
                "hpx_tpu/algo", "hpx_tpu/ops")

# layers *above* hpx_tpu.synchronization where raw primitives are banned
# (HPX004's scope).  futures/, runtime/ and core/ sit BELOW it in the
# import graph (synchronization.py itself imports futures.future) and
# are the raw substrate; native/ is C++; analysis/ is host tooling.
RAW_PRIMITIVE_EXEMPT = (
    "hpx_tpu/synchronization.py", "hpx_tpu/runtime/", "hpx_tpu/core/",
    "hpx_tpu/futures/", "hpx_tpu/native/", "hpx_tpu/utils/",
    "hpx_tpu/testing.py", "hpx_tpu/analysis/",
)

_LOCK_TYPES = {"Mutex", "Spinlock", "SharedMutex"}


def _lock_symbols(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """Names / self-attributes assigned from Mutex()/Spinlock()/
    SharedMutex() anywhere in the module."""
    names: Set[str] = set()
    attrs: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, (ast.Name, ast.Attribute))):
            continue
        callee = (value.func.id if isinstance(value.func, ast.Name)
                  else value.func.attr)
        if callee not in _LOCK_TYPES:
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Attribute):
                attrs.add(t.attr)
    return names, attrs


def _is_lock_expr(expr: ast.AST, names: Set[str], attrs: Set[str]) -> str:
    """'' or the display name of a registered-lock `with` item."""
    # `with m.shared():` — SharedMutex read side registers too
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "shared":
        inner = _is_lock_expr(expr.func.value, names, attrs)
        return f"{inner}.shared()" if inner else ""
    if isinstance(expr, ast.Name) and expr.id in names:
        return expr.id
    if isinstance(expr, ast.Attribute) and expr.attr in attrs:
        base = expr.value
        prefix = f"{base.id}." if isinstance(base, ast.Name) else ""
        return f"{prefix}{expr.attr}"
    return ""


_WAIT_ATTRS = {"wait", "arrive_and_wait", "acquire", "result"}
_WAIT_NAMES = {"wait_all", "wait_any", "wait_some", "wait_each"}


@register
class LockHeldWaitRule(Rule):
    """HPX001: a blocking wait lexically inside a ``with`` block on a
    registered `hpx_tpu.synchronization` Mutex/Spinlock/SharedMutex.

    Suspending while holding a lock is the classic AMT deadlock the
    runtime's VERIFY_LOCKS mode aborts on — but only on executed paths;
    this catches it before any chip time is spent.  Fix: narrow the
    critical section so the wait happens after ``unlock()`` (snapshot
    state under the lock, wait outside), or restructure with a
    continuation (``future.then``) instead of a blocking ``get()``.
    """

    id = "HPX001"
    name = "lock-held-wait"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        names, attrs = _lock_symbols(ctx.tree)
        if not names and not attrs:
            return
        out: List[Finding] = []

        def scan_block(body: List[ast.stmt], lock_name: str) -> None:
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    func = node.func
                    if isinstance(func, ast.Attribute):
                        attr = func.attr
                        blocking = attr in _WAIT_ATTRS or (
                            # zero-arg .get() is a future get; dict.get
                            # always takes at least the key
                            attr == "get" and not node.args
                            and not node.keywords)
                        if blocking:
                            out.append(self.finding(
                                ctx, node,
                                f".{attr}() reachable while registered "
                                f"lock `{lock_name}` is held — "
                                "suspension under a lock deadlocks the "
                                "scheduler (VERIFY_LOCKS aborts here at "
                                "runtime); wait after unlock or use a "
                                "continuation"))
                    elif isinstance(func, ast.Name) \
                            and func.id in _WAIT_NAMES:
                        out.append(self.finding(
                            ctx, node,
                            f"{func.id}() reachable while registered "
                            f"lock `{lock_name}` is held — suspension "
                            "under a lock deadlocks the scheduler"))

        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lock_name = _is_lock_expr(item.context_expr, names, attrs)
                if lock_name:
                    scan_block(node.body, lock_name)
                    break
        yield from out


@register
class HostSyncHotPathRule(Rule):
    """HPX002: host-device synchronization in executor/continuation
    code (``hpx_tpu/{futures,exec,algo,ops}``).

    ``np.asarray`` / ``jax.device_get`` / ``.block_until_ready()`` /
    ``.item()`` / ``float(x[i])`` all block the host until the device
    catches up, stalling every queued dispatch behind them — the "task
    granularity chasm" (SURVEY.md §7).  Fix: keep values as jax.Arrays
    (dispatch is already async), move the materialization to the
    consumer boundary, or route it through ``exec.tpu``'s watcher so a
    future completes off-thread.  Intentional boundary syncs get an
    inline ``# hpxlint: disable=HPX002 — <why>``.
    """

    id = "HPX002"
    name = "host-sync-hot-path"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath(*HOT_SUBPATHS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve_call(node.func)
            if dotted == "numpy.asarray":
                yield self.finding(
                    ctx, node, "np.asarray() forces a device->host "
                    "transfer in hot-path code — keep the value a "
                    "jax.Array or sync at the consumer boundary")
            elif dotted == "jax.device_get":
                yield self.finding(
                    ctx, node, "jax.device_get() blocks on the device "
                    "in hot-path code — sync at the consumer boundary")
            elif dotted == "jax.block_until_ready" or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"):
                yield self.finding(
                    ctx, node, "block_until_ready() stalls the dispatch "
                    "pipeline in hot-path code — route through the "
                    "exec.tpu watcher so a future completes off-thread")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                yield self.finding(
                    ctx, node, ".item() materializes a device scalar on "
                    "the host in hot-path code — defer to the consumer "
                    "boundary")
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Subscript):
                # dataflow prover: int(np.flatnonzero(...)[0]) and
                # friends never touch the device — skip sinks whose
                # every reaching definition is host data
                from .dataflow import provably_host
                if provably_host(node.args[0], ctx):
                    continue
                yield self.finding(
                    ctx, node, f"{node.func.id}(x[...]) materializes a "
                    "device element on the host in hot-path code — "
                    "defer to the consumer boundary")


_FUTURE_FACTORIES = {"async_", "async_many", "dataflow"}


@register
class DroppedFutureRule(Rule):
    """HPX003: the future returned by ``async_()``, ``async_many()``,
    ``dataflow()`` or ``.then()`` discarded as an expression statement.

    A dropped future silently swallows the exception it may carry and
    severs the dependency graph (nothing can wait on the work).  Fix:
    keep the future (wait/compose it), or use ``post()`` /
    ``post_many()`` — the deliberate fire-and-forget API, which returns
    ``None`` and is therefore not flagged.
    """

    id = "HPX003"
    name = "dropped-future"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            func = node.value.func
            label = ""
            if isinstance(func, ast.Name) and func.id in _FUTURE_FACTORIES:
                label = f"{func.id}()"
            elif isinstance(func, ast.Attribute):
                if func.attr in _FUTURE_FACTORIES:
                    label = f"{func.attr}()"
                elif func.attr == "then":
                    label = ".then()"
            if label:
                yield self.finding(
                    ctx, node,
                    f"result of {label} is discarded — the future (and "
                    "any exception it carries) is lost; keep it, or use "
                    "post() for fire-and-forget")


_RAW_PRIMITIVES = {
    "threading.Lock": "hpx_tpu.synchronization.Mutex",
    "threading.RLock": "hpx_tpu.synchronization.Mutex (non-reentrant: "
                       "restructure, or justify keeping RLock)",
    "time.sleep": "exec.execution_base yield/backoff helpers or a "
                  "Latch/Event wait with timeout",
    "queue.Queue": "lcos.local.Channel (futures-returning) or "
                   "runtime.threadpool work queues",
}


@register
class RawPrimitiveRule(Rule):
    """HPX004: raw ``threading.Lock``/``threading.RLock``/
    ``time.sleep``/``queue.Queue`` in runtime layers above
    ``hpx_tpu.synchronization``.

    Raw primitives bypass the VERIFY_LOCKS held-lock registration, so
    the dynamic deadlock guard cannot see them, and raw sleeps/queues
    block OS threads the work-helping scheduler could otherwise use.
    Fix: use the ``hpx_tpu.synchronization`` equivalents (Mutex,
    ConditionVariable, Latch, Event, semaphores) or the lcos channels.
    The substrate below synchronization.py (futures/, runtime/, core/)
    is exempt — it is what those primitives are built from.
    """

    id = "HPX004"
    name = "raw-sync-primitive"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if "hpx_tpu/" not in ctx.display_path \
                or ctx.in_subpath(*RAW_PRIMITIVE_EXEMPT):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve_call(node.func)
            replacement = _RAW_PRIMITIVES.get(dotted)
            if replacement:
                yield self.finding(
                    ctx, node,
                    f"raw {dotted}() in a runtime module — invisible to "
                    f"VERIFY_LOCKS; use {replacement}")


@register
class JitInLoopRule(Rule):
    """HPX005: ``jax.jit`` constructed inside a loop body.

    Each ``jax.jit(f)`` call creates a fresh jitted callable with an
    empty trace cache, so a loop that rebuilds one recompiles every
    iteration (the recompile trap).  Fix: hoist the jit out of the
    loop, or memoize the built program on its static configuration
    (see ``models.transformer._cached_program``).
    """

    id = "HPX005"
    name = "jit-in-loop"
    severity = "warning"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []

        def is_jit(node: ast.AST) -> bool:
            return isinstance(node, (ast.Name, ast.Attribute)) and \
                ctx.resolve_call(node) in ("jax.jit", "jax.pjit")

        def walk(node: ast.AST, in_loop: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_in_loop = in_loop
                if isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                    child_in_loop = True
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                    # a def inside a loop still *runs* jit per iteration
                    # via its decorators; its body runs only when called
                    if in_loop and not isinstance(child, ast.Lambda):
                        for dec in child.decorator_list:
                            target = dec.func if isinstance(dec, ast.Call) \
                                else dec
                            if is_jit(target):
                                out.append(self._hit(ctx, dec))
                    child_in_loop = False
                if isinstance(child, ast.Call) and in_loop:
                    if is_jit(child.func):
                        out.append(self._hit(ctx, child))
                    elif ctx.resolve_call(child.func) == \
                            "functools.partial" and child.args \
                            and is_jit(child.args[0]):
                        out.append(self._hit(ctx, child))
                walk(child, child_in_loop)

        walk(ctx.tree, False)
        yield from out

    def _hit(self, ctx: FileContext, node: ast.AST) -> Finding:
        return self.finding(
            ctx, node, "jax.jit constructed inside a loop — a fresh "
            "jitted callable per iteration defeats the trace cache "
            "(recompile trap); hoist it or memoize on the static "
            "config (models.transformer._cached_program)")


@register
class BareExceptRule(Rule):
    """HPX006: bare ``except:``.

    A bare except catches ``BaseException`` — including
    ``KeyboardInterrupt``/``SystemExit`` and the runtime's own
    ``DeadlockError`` — so a failing continuation is silently swallowed
    instead of poisoning its future.  Fix: catch a concrete exception
    type, or ``except BaseException:`` + re-raise/``set_exception`` if
    the handler really must see everything (as the future completion
    paths do).
    """

    id = "HPX006"
    name = "bare-except"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    ctx, node, "bare except: swallows future exceptions "
                    "(and KeyboardInterrupt/DeadlockError) — catch a "
                    "concrete type or re-raise into the future")


_SPAN_FACTORIES = {"span", "annotate"}


@register
class SpanLeakRule(Rule):
    """HPX007: ``span(...)`` / ``annotate(...)`` called as a bare
    expression statement.

    Both return a context manager (``svc.tracing.span`` a B/E span,
    ``svc.profiling.annotate`` a jax TraceAnnotation); dropping the
    result records NOTHING — the begin never fires, so the region
    silently vanishes from every trace.  Worse, a tracer-level
    ``tracer.span(...)`` statement allocates a ``_Span`` that is never
    entered, leaking the annotation the author thought they added.
    Fix: ``with tracing.span("phase"): ...`` (or keep the object and
    enter it); for a point event use ``tracing.instant(...)``, which
    really is fire-and-forget.
    """

    id = "HPX007"
    name = "span-leak"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            func = node.value.func
            if isinstance(func, ast.Name):
                name = func.id
            elif isinstance(func, ast.Attribute):
                name = func.attr
            else:
                continue
            if name in _SPAN_FACTORIES:
                yield self.finding(
                    ctx, node,
                    f"result of {name}() is discarded — it returns a "
                    "context manager, so no event is ever recorded; "
                    "wrap the region in `with ... :` or use "
                    "tracing.instant() for a point event")


_PROGRAM_CACHE_CALLEES = {"cached_program", "_cached_program",
                          "_program"}


def _walk_function(fn: ast.AST) -> Iterable[ast.AST]:
    """Yield the nodes of one function body WITHOUT descending into
    nested function definitions (each is analyzed as its own scope)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_shape_read(value: ast.AST) -> bool:
    """`x.shape` or `x.shape[i]` — a raw array-extent read."""
    if isinstance(value, ast.Attribute) and value.attr == "shape":
        return True
    return (isinstance(value, ast.Subscript)
            and isinstance(value.value, ast.Attribute)
            and value.value.attr == "shape")


def _is_len_call(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "len")


@register
class UnbucketedProgramKeyRule(Rule):
    """HPX008: jit program cache keyed on a raw dynamic length.

    ``cached_program``-family memoization keyed on ``len(...)`` or a
    ``.shape`` extent compiles ONE program per distinct value — under
    mixed-length traffic (serving prompts, ragged batches) the cache
    becomes a compile storm and the trace cache an HBM leak.  Fix:
    round the extent to a bucket ladder and pad-then-mask inside the
    program (``models/serving.py``'s ``hpx.serving.prefill_buckets``
    discipline), so the cache is O(buckets).  A per-shape key is
    legitimate when the program truly cannot pad (whole-array FFTs,
    monolithic generate/scan bodies that bake trip counts) — keep
    those in the baseline with a justification.
    """

    id = "HPX008"
    name = "unbucketed-program-key"
    severity = "warning"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            yield from self._check_scope(ctx, fn)

    def _check_scope(self, ctx: FileContext,
                     fn: ast.AST) -> Iterable[Finding]:
        tainted: Set[str] = set()      # names holding len()/shape vals
        tuples: dict = {}              # local name -> ast.Tuple
        for node in _walk_function(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                if value is None:
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                dynamic = _is_len_call(value) or _is_shape_read(value)
                for t in targets:
                    names = ([t] if isinstance(t, ast.Name)
                             else list(t.elts)
                             if isinstance(t, ast.Tuple) else [])
                    for el in names:
                        if not isinstance(el, ast.Name):
                            continue
                        # `b, n = x.shape` taints every unpacked name
                        unpacked = (isinstance(t, ast.Tuple)
                                    and _is_shape_read(value))
                        if dynamic or unpacked:
                            tainted.add(el.id)
                        if isinstance(value, ast.Tuple) \
                                and isinstance(t, ast.Name):
                            tuples[el.id] = value
        seen: Set[Tuple[int, int]] = set()  # a key tuple built once
        # and passed to two call sites (mesh/no-mesh branches) is ONE
        # problem — report each offending element once per scope
        for node in _walk_function(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = ctx.resolve_call(node.func) or ""
            if callee.rsplit(".", 1)[-1] not in _PROGRAM_CACHE_CALLEES:
                continue
            for arg in node.args:
                key = arg
                if isinstance(key, ast.Name):
                    key = tuples.get(key.id)
                if not isinstance(key, ast.Tuple):
                    continue
                for elt in key.elts:
                    bad = (_is_len_call(elt) or _is_shape_read(elt)
                           or (isinstance(elt, ast.Name)
                               and elt.id in tainted))
                    if not bad:
                        continue
                    at = (elt.lineno, elt.col_offset)
                    if at in seen:
                        continue
                    seen.add(at)
                    desc = ast.unparse(elt)
                    fname = getattr(fn, "name", "<module>")
                    yield self.finding(
                        ctx, elt,
                        f"program cache key in {fname}() carries raw "
                        f"dynamic length {desc!r} — one compiled "
                        "program per distinct value; bucket it to a "
                        "ladder and pad-then-mask (serving's "
                        "hpx.serving.prefill_buckets discipline), or "
                        "baseline it with a justification")


# serving hot-loop functions whose device values must stay on device
# (HPX009's scope): the decode/speculation dispatch path in
# models/serving.py.  Admission/prefill code syncs legitimately (seed
# tokens need VALUES); these functions run once per decode step.
_SERVING_HOT_FUNCS = ("step", "run", "_step_inner", "_flush",
                      "_spec_step", "_draft_model_tokens",
                      "_prompt_drafts")


@register
class SpecHostSyncRule(Rule):
    """HPX009: host-device synchronization (``np.asarray`` /
    ``jax.device_get`` / ``.item()``) on draft/verify intermediates
    inside the serving hot loop (``models/serving.py``'s step, flush
    and speculation functions).

    The decode loop owes exactly ONE device->host read per step — the
    speculative path's packed targets+acceptance commit, or the
    non-speculative path's flush of buffered token vectors.  Syncing
    any other draft/verify intermediate (draft token columns, verify
    logits, acceptance counts read one at a time) serializes draft,
    verify and dispatch and turns the one-sync-per-window win back
    into one-sync-per-token.  The designed sync points stay in the
    baseline with a justification; anything new this rule flags is a
    regression.
    """

    id = "HPX009"
    name = "serving-hot-loop-host-sync"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath("hpx_tpu/models/serving"):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name not in _SERVING_HOT_FUNCS:
                continue
            for node in _walk_function(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = ctx.resolve_call(node.func)
                if dotted == "numpy.asarray":
                    yield self.finding(
                        ctx, node,
                        f"np.asarray() in serving hot-loop "
                        f"{fn.name}() syncs the device — the decode "
                        "loop owes ONE host read per step; keep "
                        "draft/verify intermediates on device and "
                        "commit through the step's single packed read")
                elif dotted == "jax.device_get":
                    yield self.finding(
                        ctx, node,
                        f"jax.device_get() in serving hot-loop "
                        f"{fn.name}() syncs the device — commit "
                        "through the step's single packed read")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    yield self.finding(
                        ctx, node,
                        f".item() in serving hot-loop {fn.name}() "
                        "materializes a device scalar per call — pack "
                        "scalars into the step's single device->host "
                        "read instead")


# modules on the paged-decode data path where a full-pool gather is a
# silent HBM-bandwidth regression (HPX010's scope); the gather oracle
# itself (ops/paged_attention.py) fires too and stays in the baseline.
# models/transformer is fenced since the (dp, tp) mesh work: shard_map
# bodies see per-shard pool slices there, and a pool gather inside one
# would ALSO be a cross-shard-correctness bug waiting to happen the
# moment the block axis stops being dp-replicated — keep every
# array-of-blocks read in the oracle module.
_PAGED_HOT_SUBPATHS = ("hpx_tpu/models/serving",
                       "hpx_tpu/models/transformer", "hpx_tpu/ops/",
                       "hpx_tpu/cache/")


@register
class FullPoolGatherRule(Rule):
    """HPX010: ``pool[table]``-shaped advanced indexing on a KV block
    pool in the paged serving hot path.

    Indexing a block pool with an int32 index array materializes the
    full mapped ``[B, max_blocks, block_size, n_kv, head_dim]`` view
    in HBM — the write-then-gather formulation whose bandwidth the
    fused Pallas kernel (``ops/attention_pallas.fused_paged_attention``)
    exists to eliminate: every byte the gather writes is immediately
    read back by the attention contraction that follows.  Fix: route
    decode attention through ``paged_decode_attention(..., fused=True)``
    so K/V stream table-directed through VMEM.  Array-of-blocks reads
    that must stay in XLA form belong in the designated oracle module
    (``ops/paged_attention.py``) — its sites are baselined with
    justification; anything new this rule flags is a regression.
    The fence covers mesh/shard_map code too (models/serving,
    models/transformer): inside a shard_map body the pool is a
    PER-SHARD slice whose block axis is dp-replicated — a gather there
    is the same bandwidth regression, plus a latent cross-shard bug if
    the replication invariant ever changes, so block tables stay
    per-shard int32 and gathers stay in the oracle.
    Detection is name-based (singular ``*pool*`` arrays are device
    block pools; plural ``pools`` is the host-side per-layer list) —
    a false positive takes an inline
    ``# hpxlint: disable=HPX010 — <why>``.
    """

    id = "HPX010"
    name = "full-pool-gather"
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath(*_PAGED_HOT_SUBPATHS):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)):
                continue
            base = node.value
            name = (base.id if isinstance(base, ast.Name)
                    else base.attr if isinstance(base, ast.Attribute)
                    else "")
            # singular pool names are device block pools (`pool`,
            # `pool_q`, `k_pool`); plural `pools` is the per-layer
            # host list (Python-int indexed) and `.at[...]` chains
            # are scatters, not gathers — both stay out of scope
            if "pool" not in name or name.endswith("s"):
                continue
            # only array-valued (advanced) indexing gathers; constant
            # subscripts and slices read O(1) blocks
            if not isinstance(node.slice, (ast.Name, ast.Attribute)):
                continue
            yield self.finding(
                ctx, node,
                f"advanced indexing {ast.unparse(node)!r} gathers the "
                "full mapped pool view through HBM — route decode "
                "attention through paged_decode_attention(..., "
                "fused=True); XLA-oracle gathers live only in "
                "ops/paged_attention.py (baselined with justification)")


# resiliency-bearing layers where ad-hoc retry/except patterns hide
# real faults (HPX011's scope): the serving/model layer and the
# distributed layer — the two places `svc/resiliency` policies exist
# to replace hand-rolled loops.
_RESILIENCY_SUBPATHS = ("hpx_tpu/models/", "hpx_tpu/dist/")

# calls that make a retry loop polite: cooperative suspension between
# attempts (exec.execution_base.suspend / yield_while) or a policy
# helper that owns backoff itself
_BACKOFF_CALLEES = {"suspend", "sleep", "yield_while", "sync_replay"}


@register
class NakedRetryRule(Rule):
    """HPX011: hand-rolled retry loops without backoff, and
    broad-except swallowing, in the serving (``hpx_tpu/models``) and
    distributed (``hpx_tpu/dist``) layers.

    Two shapes of quiet fault-amplification:

    * a ``for``/``while`` loop whose body catches an exception and
      goes around again with NO suspension between attempts — under a
      persistent fault (allocator exhausted, locality gone) that loop
      is a busy-wait hammering the failed resource; every retry path
      owes a cooperative backoff (``exec.execution_base.suspend``,
      never raw ``time.sleep`` — HPX004) or should route through
      ``svc.resiliency.sync_replay``/``async_replay``, which own the
      policy;
    * ``except Exception:``/``except BaseException:``/bare ``except:``
      whose handler is only ``pass`` — a swallowed fault in these
      layers silently corrupts serving state the checkpoint/restore
      ladder exists to keep consistent.  Faults must be typed,
      counted, or re-raised.

    The deliberate sites (resiliency's own replay loops live in
    ``svc/`` and are out of scope; in-scope survivors carry a
    justification) stay in the baseline; anything new this rule flags
    is a regression.
    """

    id = "HPX011"
    name = "naked-retry"
    severity = "warning"

    def _loop_retries(self, loop: ast.AST) -> bool:
        """Does some Try directly in this loop catch-and-continue?"""
        for node in _walk_function(loop):
            if isinstance(node, (ast.For, ast.While)):
                continue          # nested loops report themselves
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                body = h.body
                if body and isinstance(body[-1], ast.Continue):
                    return True
                if all(isinstance(s, ast.Pass) for s in body):
                    return True
                # the _replay_loop shape: handler records the
                # exception (assignment only) and falls through to
                # the next iteration
                if body and all(isinstance(s, (ast.Assign, ast.Pass))
                                for s in body):
                    return True
        return False

    def _loop_backs_off(self, loop: ast.AST) -> bool:
        for node in _walk_function(loop):
            if not isinstance(node, ast.Call):
                continue
            name = (node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else node.func.id
                    if isinstance(node.func, ast.Name) else "")
            if name in _BACKOFF_CALLEES:
                return True
        return False

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath(*_RESILIENCY_SUBPATHS):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            fname = fn.name
            for node in _walk_function(fn):
                if isinstance(node, (ast.For, ast.While)):
                    # a RETRY loop iterates attempts (`while ...` or
                    # `for _ in range(n)`); a for over a data
                    # collection with a per-item try is error
                    # ISOLATION, not a retry of the same operation
                    if isinstance(node, ast.For) and not (
                            isinstance(node.iter, ast.Call)
                            and isinstance(node.iter.func, ast.Name)
                            and node.iter.func.id == "range"):
                        continue
                    if self._loop_retries(node) \
                            and not self._loop_backs_off(node):
                        yield self.finding(
                            ctx, node,
                            f"retry loop in {fname}() re-attempts "
                            "with no backoff — a persistent fault "
                            "turns this into a busy-wait; suspend "
                            "between attempts (exec.execution_base."
                            "suspend) or route through svc.resiliency."
                            "sync_replay, which owns the policy")
                elif isinstance(node, ast.ExceptHandler):
                    broad = (node.type is None
                             or (isinstance(node.type, ast.Name)
                                 and node.type.id in ("Exception",
                                                      "BaseException")))
                    if broad and all(isinstance(s, ast.Pass)
                                     for s in node.body):
                        yield self.finding(
                            ctx, node,
                            f"broad except swallowed in {fname}() — "
                            "a pass-only Exception handler hides the "
                            "faults the restore/shed ladder must see; "
                            "type it, count it, or re-raise")


# ---------------------------------------------------------------------------
# HPX012 — unbounded remote wait: a blocking get() on a remote action's
# future with no timeout is a hang waiting for a locality to die. The
# disaggregated serving work made every cross-locality edge carry a
# per-attempt timeout + bounded retry (dist.actions.resilient_action);
# this rule keeps new code from quietly regressing to unbounded waits.
# ---------------------------------------------------------------------------

_REMOTE_SENDERS = ("async_action", "send_action")


@register
class UnboundedRemoteWaitRule(Rule):
    """HPX012: ``.get()`` with no timeout on a remote action future in
    non-test runtime code.

    ``async_action``/``send_action`` parcels cross a process boundary:
    the peer can die mid-call, and without a failure detector ping in
    flight the future then NEVER resolves — a caller blocked in a bare
    ``get()`` hangs forever instead of seeing a typed
    ``LocalityLost``. Every remote wait must either pass
    ``get(timeout_s)`` or route the whole call through
    ``dist.actions.resilient_action`` (per-attempt timeout + bounded
    backoff retry + idempotent re-delivery), which owns the policy.

    Flagged shapes (same-function dataflow only):

    * ``async_action(...).get()`` / ``send_action(...).get()``
      chained directly with no argument;
    * ``f = async_action(...)`` … ``f.get()`` with no argument.

    Deliberate survivors (callers that own deadline handling a level
    up, or infrastructure that must wait out bootstrap) stay in the
    baseline with justification; suppress a single site with
    ``# hpxlint: disable=HPX012 — <why>``.
    """

    id = "HPX012"
    name = "unbounded-remote-wait"
    severity = "warning"

    @staticmethod
    def _is_remote_send(call: ast.AST) -> bool:
        if not isinstance(call, ast.Call):
            return False
        fn = call.func
        name = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else "")
        return name in _REMOTE_SENDERS

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.display_path.startswith("tests/") \
                or "/tests/" in ctx.display_path:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            # names bound to a remote-send result inside this function
            remote_names: Set[str] = set()
            for node in _walk_function(fn):
                if isinstance(node, ast.Assign) \
                        and self._is_remote_send(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            remote_names.add(tgt.id)
            for node in _walk_function(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "get"
                        and not node.args and not node.keywords):
                    continue
                recv = node.func.value
                chained = self._is_remote_send(recv)
                via_name = (isinstance(recv, ast.Name)
                            and recv.id in remote_names)
                if chained or via_name:
                    yield self.finding(
                        ctx, node,
                        f"unbounded get() on a remote action future "
                        f"in {fn.name}() — a dead locality leaves "
                        "this blocked forever; pass get(timeout_s) "
                        "or route the call through dist.actions."
                        "resilient_action (timeout + bounded retry + "
                        "idempotent re-delivery)")


# the full counter-name grammar from svc/performance_counters._NAME_RE:
# /object{locality#N/instance}/counter  (N is a number or '*')
_COUNTER_NAME_RE = re.compile(
    r"^/[^{/]+\{locality#(\d+|\*)/[^}]+\}/[^{}]+$")

# registry entry points whose FIRST argument is a full counter name
_COUNTER_NAME_SINKS = {
    "register_counter", "unregister_counter", "query_counter",
    "query_counter_async", "parse_counter_name",
}

# helpers whose first two arguments are (object, counter) fragments
_COUNTER_FRAGMENT_SINKS = {"counter_name", "put"}


@register
class CounterNameDiscipline(Rule):
    """HPX016: counter names must parse against the registry grammar
    and histogram timers must not be silently dropped.  A counter
    name that fails ``/object{locality#N/instance}/counter`` raises
    only when the counter is first QUERIED — typically in a dashboard
    scrape long after the registering commit landed; and a bare
    ``h.record()`` statement mints a timing context manager and
    throws it away, recording nothing.  Fix: match the grammar
    (``performance_counters.counter_name`` builds it for you), and
    either pass ``record(value)`` or hold the timer in a ``with``."""

    id = "HPX016"
    name = "counter-name-discipline"
    severity = "error"

    @staticmethod
    def _literal_str(node: ast.AST) -> "str | None":
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            return node.value
        return None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.display_path.startswith("tests/") \
                or "/tests/" in ctx.display_path:
            return
        for node in ast.walk(ctx.tree):
            # dropped histogram timer: an expression STATEMENT whose
            # value is a no-arg .record() call
            if isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr == "record" \
                    and not node.value.args \
                    and not node.value.keywords:
                yield self.finding(
                    ctx, node,
                    "bare record() statement drops the timing "
                    "context manager without entering it — nothing "
                    "is recorded; pass record(value) or use "
                    "`with h.record():` around the timed region")
                continue
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            callee = (fn.attr if isinstance(fn, ast.Attribute)
                      else fn.id if isinstance(fn, ast.Name) else "")
            if callee in _COUNTER_NAME_SINKS and node.args:
                lit = self._literal_str(node.args[0])
                if lit is not None and lit.startswith("/") \
                        and not _COUNTER_NAME_RE.match(lit):
                    yield self.finding(
                        ctx, node,
                        f"counter name {lit!r} does not match "
                        "/object{locality#N/instance}/counter — it "
                        "registers silently and raises at first "
                        "query; build it with performance_counters."
                        "counter_name()")
            elif callee in _COUNTER_FRAGMENT_SINKS \
                    and len(node.args) >= 2:
                obj = self._literal_str(node.args[0])
                ctr = self._literal_str(node.args[1])
                if obj is not None and ctr is not None:
                    full = f"/{obj}{{locality#0/total}}/{ctr}"
                    if not _COUNTER_NAME_RE.match(full):
                        yield self.finding(
                            ctx, node,
                            f"counter fragments ({obj!r}, {ctr!r}) "
                            "assemble into a name that fails the "
                            "registry grammar /object{locality#N/"
                            "instance}/counter — it raises at first "
                            "query, not at registration")


@register
class ProgramCacheBypassRule(Rule):
    """HPX017: raw ``jax.jit`` in a models/ops hot path outside the
    profiled program-cache funnel.

    Every jit-program the serving stack builds flows through
    ``core.programs.cached_program`` (via a module's
    ``_cached_program`` / ``self._program`` wrapper) — the single
    funnel where the per-program profiler (``svc/progprof``)
    interposes to account compile wall time, per-call latency, and
    roofline fraction.  A raw ``jax.jit(...)`` (or ``@jax.jit``
    decorator) in ``models/`` or ``ops/`` builds a program the
    profiler and the ``/programs{...}`` counters can never see — its
    compiles and calls vanish from the --metrics-out artifact and
    every flight bundle.  Fix: build the program inside a builder
    handed to ``cached_program()`` (or the module's wrapper); truly
    one-shot or demo programs get a baseline entry with justification.
    """

    id = "HPX017"
    name = "program-cache-bypass"
    severity = "warning"

    _SCOPE = ("hpx_tpu/models/", "hpx_tpu/ops/")
    _JITS = ("jax.jit", "jax.pjit")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath(*self._SCOPE):
            return

        # builders sanctioned by being handed to a program-cache
        # callee: lambdas passed directly in the argument list, plus
        # local functions referenced there by name
        sanctioned_lambdas: Set[int] = set()
        sanctioned_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            callee = (fn.attr if isinstance(fn, ast.Attribute)
                      else fn.id if isinstance(fn, ast.Name) else "")
            if callee not in _PROGRAM_CACHE_CALLEES:
                continue
            for arg in list(node.args) + \
                    [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    sanctioned_lambdas.add(id(arg))
                elif isinstance(arg, ast.Name):
                    sanctioned_names.add(arg.id)

        def is_jit(node: ast.AST) -> bool:
            return isinstance(node, (ast.Name, ast.Attribute)) and \
                ctx.resolve_call(node) in self._JITS

        out: List[Finding] = []

        def hit(node: ast.AST, scope: str) -> None:
            out.append(self.finding(
                ctx, node,
                f"raw jax.jit in {scope}() bypasses the profiled "
                "program cache — svc/progprof never sees its compile "
                "time or per-call cost; build it inside a "
                "core.programs.cached_program() builder, or baseline "
                "a genuinely one-shot program with a justification"))

        def walk(node: ast.AST, scope: str, ok: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_scope, child_ok = scope, ok
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_scope = child.name
                    child_ok = ok or child.name in sanctioned_names
                    for dec in child.decorator_list:
                        if not child_ok and is_jit(dec):
                            hit(dec, child.name)
                elif isinstance(child, ast.Lambda):
                    child_ok = ok or id(child) in sanctioned_lambdas
                if isinstance(child, ast.Call) and not child_ok \
                        and is_jit(child.func):
                    hit(child, child_scope)
                walk(child, child_scope, child_ok)

        walk(ctx.tree, "<module>", False)
        yield from out


# instance attributes backed by declared-tunable config keys
# (``tunable=`` markers in core/config_schema.py) — the knob map
# svc/autotune.server_tuner binds.  Keyed attr -> backing config key
# so the finding names both.
_TUNABLE_KNOB_ATTRS = {
    "prefill_chunk": "hpx.serving.prefill_chunk",
    "_max_async": "hpx.serving.max_async_steps",
    "_spec_k": "hpx.serving.spec.k",
    "_ckpt_every": "hpx.serving.ckpt_every",
    "budget_blocks": "hpx.cache.radix_budget_blocks",
    "max_queue": "hpx.serving.disagg.max_queue",
}

# the config actuation path: construction reads the schema default,
# _reload_knobs() applies operator config writes at the flush
# boundary.  Everything else must go through the runtime config (or
# the AdaptiveTuner, whose KnobBinding setters live in svc/autotune).
_TUNE_SANCTIONED_FUNCS = {"__init__", "_reload_knobs"}


@register
class TunableKnobMutationRule(Rule):
    """HPX018: direct mutation of an adaptive-tuner-owned knob
    attribute outside the config actuation path.

    The serving knobs the online tuner owns (``prefill_chunk``,
    ``_max_async``, ``_spec_k``, ``_ckpt_every``, ``budget_blocks``,
    ``max_queue`` — the attributes backing the ``tunable=`` keys in
    ``core/config_schema``) change ONLY at the flush/admit boundary:
    construction reads the schema default, ``_reload_knobs()`` applies
    operator config writes, and ``svc/autotune``'s KnobBinding setters
    actuate tuner probes.  A write anywhere else races the controller
    — the tuner's next probe silently reverts it, its decision log no
    longer explains the live value, and flight-bundle replay diverges
    from what actually ran.  Fix: route the change through
    ``runtime_config().set(...)`` (picked up at the next flush) or
    declare the attribute's owner a tuner binding in svc/autotune.
    """

    id = "HPX018"
    name = "tunable-knob-mutation"
    severity = "warning"

    _SCOPE = ("hpx_tpu/models/", "hpx_tpu/svc/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath(*self._SCOPE):
            return
        # the tuner's KnobBinding setters ARE the actuation path
        if ctx.display_path.endswith("svc/autotune.py"):
            return
        out: List[Finding] = []

        def walk(node: ast.AST, scope: str) -> None:
            for child in ast.iter_child_nodes(node):
                child_scope = scope
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_scope = child.name
                targets: List[ast.expr] = []
                if isinstance(child, ast.Assign):
                    targets = list(child.targets)
                elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                    targets = [child.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr in _TUNABLE_KNOB_ATTRS \
                            and child_scope not in _TUNE_SANCTIONED_FUNCS:
                        key = _TUNABLE_KNOB_ATTRS[t.attr]
                        out.append(self.finding(
                            ctx, child,
                            f"direct write to tuner-owned knob "
                            f"attribute `{t.attr}` (backing {key}) in "
                            f"{child_scope}() bypasses the config "
                            "actuation path — it races the adaptive "
                            "tuner and breaks flight-bundle replay; "
                            "route it through runtime_config().set() "
                            "(applied by _reload_knobs at the next "
                            "flush) or a svc/autotune KnobBinding"))
                walk(child, child_scope)

        walk(ctx.tree, "<module>")
        yield from out


# shape-ladder knobs with a resolver chain: explicit operator config,
# then the perfdb learned tier, then the declared schema default.
# Keyed param/kwarg name -> the chain a baked literal bypasses.
_SHAPE_KNOB_PARAMS = {
    "block_size": "hpx.paged.block_size + the perfdb learned-blocks "
                  "tier (ops.attention_pallas.resolve_paged_block)",
    "prefill_chunk": "hpx.serving.prefill_chunk + the perfdb "
                     "learned-ladder tier",
    "prefill_buckets": "hpx.serving.prefill_buckets + the perfdb "
                       "learned-ladder tier",
    "spec_k": "hpx.serving.spec.k + the perfdb learned-ladder tier",
    "page_size": "hpx.paged.block_size + the perfdb learned-blocks "
                 "tier",
}


def _is_shape_literal(node: ast.AST) -> bool:
    """A bare int literal, or a tuple/list of them (bucket ladders)."""
    if isinstance(node, ast.Constant):
        return type(node.value) is int
    if isinstance(node, (ast.Tuple, ast.List)) and node.elts:
        return all(isinstance(e, ast.Constant)
                   and type(e.value) is int for e in node.elts)
    return False


@register
class BakedShapeConstantRule(Rule):
    """HPX024: a shape-ladder knob (``block_size``, ``prefill_chunk``,
    ``prefill_buckets``, ``spec_k``, ``page_size``) baked to an int
    literal in a parameter default or call-site keyword inside
    ``models/``/``svc/``/``ops/``.

    These knobs have three legitimate sources, consulted in order:
    explicit operator config (``hpx.serving.*``/``hpx.paged.*``), the
    perfdb learned tier (``hpx.perfdb.use_learned_ladders`` — the
    geometry benchmarks/ladder_search.py re-derived from measured
    costs), and the declared schema default.  A literal baked at a
    signature or call site silently pins the geometry for every
    caller: the learned ladder never applies there, and two
    components can disagree about a shape they must share (a prefill
    worker emitting 16-row segments into a decode pool tuned to 32).
    Fix: default the parameter to ``None`` and resolve through the
    chain (``resolve_paged_block``, ``_resolve_buckets``), or thread
    the owning component's already-resolved value.  A deliberate bake
    (reference path, fixed-geometry kernel) carries ``# hpxlint:
    disable=HPX024 — <why>`` or a baseline entry with justification.
    """

    id = "HPX024"
    name = "baked-shape-constant"
    severity = "warning"

    _SCOPE = ("hpx_tpu/models/", "hpx_tpu/svc/", "hpx_tpu/ops/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_subpath(*self._SCOPE):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                a = node.args
                pos = a.posonlyargs + a.args
                pairs = list(zip(pos[len(pos) - len(a.defaults):],
                                 a.defaults))
                pairs += [(p, d) for p, d in
                          zip(a.kwonlyargs, a.kw_defaults)
                          if d is not None]
                for param, default in pairs:
                    if param.arg in _SHAPE_KNOB_PARAMS \
                            and _is_shape_literal(default):
                        yield self.finding(
                            ctx, default,
                            f"parameter `{param.arg}` of "
                            f"{node.name}() bakes a shape constant "
                            "in its default — the resolver chain "
                            f"({_SHAPE_KNOB_PARAMS[param.arg]}) "
                            "never applies for callers that omit "
                            "it; default to None and resolve, or "
                            "thread the owner's resolved value")
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in _SHAPE_KNOB_PARAMS \
                            and _is_shape_literal(kw.value):
                        yield self.finding(
                            ctx, kw.value,
                            f"call-site keyword `{kw.arg}` bakes a "
                            "shape constant — it pins this "
                            "component's geometry against the "
                            "resolver chain "
                            f"({_SHAPE_KNOB_PARAMS[kw.arg]}); pass "
                            "the resolved value (or omit the "
                            "keyword and let the callee resolve)")
