"""hpxlint CLI: ``python -m hpx_tpu.analysis [paths...]`` (also
installed as the ``hpxlint`` console script).

Exit codes: 0 clean (all findings suppressed or baselined), 1 new
findings OR stale baseline entries, 2 usage error.  Run from the repo
root so the committed baseline's relative paths match.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

from .engine import (
    DEFAULT_BASELINE,
    all_rules,
    apply_baseline,
    lint_paths,
    load_baseline,
    stale_entries,
    update_baseline_file,
    write_baseline,
)


def _list_rules() -> str:
    lines = []
    for rule in all_rules():
        doc = (type(rule).__doc__ or "").strip().splitlines()
        head = doc[0].split(": ", 1)[-1] if doc else ""
        lines.append(f"{rule.id}  {rule.name:<20} [{rule.severity}]  "
                     f"{head}")
    return "\n".join(lines)


def _changed_py_files() -> List[str]:
    """Python files touched per git: unstaged + staged diffs against
    HEAD plus untracked files.  Paths come back repo-root-relative;
    returns only files that still exist (deletions drop out)."""
    def run(*argv: str) -> List[str]:
        out = subprocess.run(["git", *argv], capture_output=True,
                             text=True, check=True)
        return [ln.strip() for ln in out.stdout.splitlines()
                if ln.strip()]

    root = run("rev-parse", "--show-toplevel")[0]
    names = set(run("diff", "--name-only", "HEAD", "--"))
    names.update(run("ls-files", "--others", "--exclude-standard"))
    return sorted(os.path.join(root, n) for n in names
                  if n.endswith(".py")
                  and os.path.isfile(os.path.join(root, n)))


def _by_rule(findings) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return dict(sorted(counts.items()))


def _github_line(f) -> str:
    """GitHub Actions workflow-command annotation — renders the
    finding inline on the PR diff in CI logs."""
    level = "error" if f.severity == "error" else "warning"
    return (f"::{level} file={f.path},line={f.line},col={f.col},"
            f"title={f.rule}::{f.message}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hpxlint",
        description="AST-based async-misuse & TPU-hot-path linter for "
                    "the hpx_tpu runtime.")
    ap.add_argument("paths", nargs="*", default=["hpx_tpu"],
                    help="files/directories to lint (default: hpx_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default: the committed "
                         "hpx_tpu/analysis/hpxlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into --baseline "
                         "and exit 0 (fresh justifications)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline from current findings, "
                         "keeping justification strings of surviving "
                         "entries and pruning stale ones")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids/names to run "
                         "(default: all)")
    ap.add_argument("--only", default="", metavar="HPX0NN[,..]",
                    help="run only these rule ids (merged with "
                         "--select); the pre-commit fast path")
    ap.add_argument("--changed", action="store_true",
                    help="lint only Python files git reports as "
                         "changed (staged, unstaged, or untracked) "
                         "instead of the given paths; stale-baseline "
                         "checking is skipped for this partial scan")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    select = [s.strip() for s in args.select.split(",") if s.strip()]
    select += [s.strip() for s in args.only.split(",") if s.strip()]
    paths = args.paths
    if args.changed:
        try:
            paths = _changed_py_files()
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"hpxlint: --changed needs a git checkout: {e}",
                  file=sys.stderr)
            return 2
        if not paths:
            print("hpxlint: no changed Python files")
            return 0
    try:
        rules = all_rules(select or None)
        result = lint_paths(paths, rules)
    except (FileNotFoundError, ValueError) as e:
        print(f"hpxlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(result.findings, args.baseline)
        print(f"hpxlint: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.update_baseline:
        kept, pruned = update_baseline_file(result.findings,
                                            args.baseline)
        print(f"hpxlint: rewrote {args.baseline}: {kept} entrie(s) "
              f"kept, {pruned} stale entrie(s) pruned")
        return 0

    budget = {} if args.no_baseline else load_baseline(args.baseline)
    new, baselined = apply_baseline(result.findings, budget)
    # a partial scan (changed files only, or a rule subset) cannot
    # tell stale from simply-not-scanned — skip the burn-down check
    partial = args.changed or bool(select)
    stale = ({} if partial
             else stale_entries(result.findings, budget))

    if args.format == "json":
        new_ids = {id(f) for f in new}
        absorbed = [f for f in result.findings if id(f) not in new_ids]
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "baselined": baselined, "suppressed": result.suppressed,
            "suppressed_by_rule": dict(sorted(
                result.suppressed_by_rule.items())),
            "baselined_by_rule": _by_rule(absorbed),
            "stale_baseline_entries": [
                {"path": p, "rule": r, "message": m, "count": c}
                for (p, r, m), c in sorted(stale.items())],
            "checked_files": result.checked_files}, indent=1))
    elif args.format == "github":
        for f in new:
            print(_github_line(f))
        for (p, r, m), c in sorted(stale.items()):
            print(f"::warning file={p},title=stale-baseline::baseline "
                  f"entry no longer matches any finding ({r}: {m}); "
                  "run hpxlint --update-baseline")
    else:
        for f in new:
            print(f.format())
        for (p, r, m), c in sorted(stale.items()):
            print(f"{p}: stale baseline entry ({r}, count {c}): {m}")
        print(f"hpxlint: {result.checked_files} file(s), "
              f"{len(new)} new finding(s), {baselined} baselined, "
              f"{result.suppressed} suppressed, "
              f"{len(stale)} stale baseline entrie(s)")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
