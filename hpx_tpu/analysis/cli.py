"""hpxlint CLI: ``python -m hpx_tpu.analysis [paths...]`` (also
installed as the ``hpxlint`` console script).

Exit codes: 0 clean (all findings suppressed or baselined), 1 new
findings OR stale baseline entries, 2 usage error.  Run from the repo
root so the committed baseline's relative paths match.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .engine import (
    DEFAULT_BASELINE,
    all_rules,
    apply_baseline,
    lint_paths,
    load_baseline,
    stale_entries,
    update_baseline_file,
    write_baseline,
)


def _list_rules() -> str:
    lines = []
    for rule in all_rules():
        doc = (type(rule).__doc__ or "").strip().splitlines()
        head = doc[0].split(": ", 1)[-1] if doc else ""
        lines.append(f"{rule.id}  {rule.name:<20} [{rule.severity}]  "
                     f"{head}")
    return "\n".join(lines)


def _github_line(f) -> str:
    """GitHub Actions workflow-command annotation — renders the
    finding inline on the PR diff in CI logs."""
    level = "error" if f.severity == "error" else "warning"
    return (f"::{level} file={f.path},line={f.line},col={f.col},"
            f"title={f.rule}::{f.message}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hpxlint",
        description="AST-based async-misuse & TPU-hot-path linter for "
                    "the hpx_tpu runtime.")
    ap.add_argument("paths", nargs="*", default=["hpx_tpu"],
                    help="files/directories to lint (default: hpx_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default: the committed "
                         "hpx_tpu/analysis/hpxlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into --baseline "
                         "and exit 0 (fresh justifications)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline from current findings, "
                         "keeping justification strings of surviving "
                         "entries and pruning stale ones")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids/names to run "
                         "(default: all)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    select = [s.strip() for s in args.select.split(",") if s.strip()]
    try:
        rules = all_rules(select or None)
        result = lint_paths(args.paths, rules)
    except (FileNotFoundError, ValueError) as e:
        print(f"hpxlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(result.findings, args.baseline)
        print(f"hpxlint: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.update_baseline:
        kept, pruned = update_baseline_file(result.findings,
                                            args.baseline)
        print(f"hpxlint: rewrote {args.baseline}: {kept} entrie(s) "
              f"kept, {pruned} stale entrie(s) pruned")
        return 0

    budget = {} if args.no_baseline else load_baseline(args.baseline)
    new, baselined = apply_baseline(result.findings, budget)
    stale = stale_entries(result.findings, budget)

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "baselined": baselined, "suppressed": result.suppressed,
            "stale_baseline_entries": [
                {"path": p, "rule": r, "message": m, "count": c}
                for (p, r, m), c in sorted(stale.items())],
            "checked_files": result.checked_files}, indent=1))
    elif args.format == "github":
        for f in new:
            print(_github_line(f))
        for (p, r, m), c in sorted(stale.items()):
            print(f"::warning file={p},title=stale-baseline::baseline "
                  f"entry no longer matches any finding ({r}: {m}); "
                  "run hpxlint --update-baseline")
    else:
        for f in new:
            print(f.format())
        for (p, r, m), c in sorted(stale.items()):
            print(f"{p}: stale baseline entry ({r}, count {c}): {m}")
        print(f"hpxlint: {result.checked_files} file(s), "
              f"{len(new)} new finding(s), {baselined} baselined, "
              f"{result.suppressed} suppressed, "
              f"{len(stale)} stale baseline entrie(s)")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
