"""hpxlint dataflow tier (tier 3): def-use chains and the rules on top.

The per-file tier (rules.py) is lexical; the project tier (project.py)
resolves symbols, locks and call edges but stays flow-insensitive.
This tier adds the missing axis: *which definitions reach which uses*.
It builds intraprocedural reaching-definitions/def-use chains per
function over the SAME parsed trees (no file is parsed twice), plus
one-level interprocedural summaries from the ProjectIndex call graph
(locks held by every caller at the call site; jit-donation positions
of factory returns).

Four rules run on it:

* HPX019 — infer a guarded-by lock per ``self.attr`` from the sites
  that mutate it with a lock held; flag mutations reachable bare,
* HPX020 — an array binding donated to a jitted call (donate_argnums)
  is used again afterwards,
* HPX021 — axis-name literals inside a ``shard_map`` body that the
  enclosing mesh/specs never declare,
* HPX022 — flow-sensitive HPX002: a value whose every reaching
  definition is device-origin flows into ``float()``/``int()``/
  ``bool()``/``np.array()`` in hot-path code.  (HPX002 keeps the
  token-level sinks and consults :func:`provably_host` to drop its
  historical false positives.)

Pure stdlib, like the rest of the linter.  The def-use core is a
may-analysis (unions over forks, loops walked twice for back edges);
the rules that need certainty (HPX022, the HPX002 prover) therefore
demand agreement of EVERY reaching definition before speaking up.
"""

from __future__ import annotations

import ast
from typing import (Callable, Dict, FrozenSet, Iterable, List, Optional,
                    Sequence, Set, Tuple)

from .engine import DataflowRule, FileContext, Finding, register
from .project import ProjectIndex, FunctionInfo

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPE_NODES = _FUNC_NODES  # lambdas handled by shadowing, not scoping


# ---------------------------------------------------------------------------
# Reaching definitions / def-use chains for one function body
# ---------------------------------------------------------------------------

class Def:
    """One binding of a local name: the statement that bound it, the
    bound value expression when there is one, and how it was bound."""

    __slots__ = ("name", "node", "value", "kind")

    def __init__(self, name: str, node: ast.AST,
                 value: Optional[ast.AST] = None,
                 kind: str = "assign") -> None:
        self.name = name
        self.node = node
        self.value = value
        self.kind = kind  # assign|aug|param|for|with|except|import|func|class|donated|unknown

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"Def({self.name!r}, {self.kind}, line {getattr(self.node, 'lineno', '?')})"


class Use:
    """One Name load: the node and the definitions reaching it."""

    __slots__ = ("name", "node", "defs")

    def __init__(self, name: str, node: ast.AST,
                 defs: FrozenSet[Def]) -> None:
        self.name = name
        self.node = node
        self.defs = defs


Env = Dict[str, FrozenSet[Def]]
CallEffect = Callable[[ast.Call, Env], Optional[Dict[str, Def]]]


def _merge(*envs: Optional[Env]) -> Optional[Env]:
    """Union of reaching definitions over live branches (None = the
    branch cannot fall through)."""
    live = [e for e in envs if e is not None]
    if not live:
        return None
    if len(live) == 1:
        return dict(live[0])
    out: Env = {}
    for env in live:
        for name, defs in env.items():
            prev = out.get(name)
            out[name] = defs if prev is None else (prev | defs)
    return out


class DefUse:
    """Reaching-definitions walk of ONE function (or module) body.

    Statement-ordered abstract interpretation: `if` forks and merges,
    loops run twice so back-edge definitions reach first-iteration
    uses, `try` handlers start from every intermediate body state and
    `finally` sees both the normal and the escaping states (the HPX015
    walker's routing, rebuilt for environments instead of deltas).
    Nested ``def``/``lambda`` bodies are separate scopes — their loads
    are not recorded here (lambdas shadow their parameters).

    `call_effect` lets a rule rewrite the environment at call sites —
    HPX020 uses it to replace donated argument bindings with a
    ``donated`` definition that later loads then trip over.
    """

    def __init__(self, fn: ast.AST,
                 call_effect: Optional[CallEffect] = None) -> None:
        self.fn = fn
        self.call_effect = call_effect
        self.uses: List[Use] = []
        # id(Name node) -> reaching defs; loops record twice, the
        # second (superset, back edges included) wins
        self.use_at: Dict[int, FrozenSet[Def]] = {}
        env: Env = {}
        args = getattr(fn, "args", None)
        if args is not None:
            params = list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs)
            for a in params:
                env[a.arg] = frozenset({Def(a.arg, a, None, "param")})
            for va in (args.vararg, args.kwarg):
                if va is not None:
                    env[va.arg] = frozenset({Def(va.arg, va, None, "param")})
        self.exit_env = self._walk(getattr(fn, "body", []), env)

    # -- expression side ----------------------------------------------------

    def _use(self, node: ast.Name, env: Env,
             shadow: FrozenSet[str]) -> None:
        if node.id in shadow:
            return
        defs = env.get(node.id, frozenset())
        self.uses.append(Use(node.id, node, defs))
        self.use_at[id(node)] = defs

    def _expr(self, expr: Optional[ast.AST], env: Env,
              shadow: FrozenSet[str] = frozenset()) -> None:
        """Record loads and apply call effects, in evaluation-ish
        order (children before the call effect of their Call)."""
        if expr is None:
            return
        if isinstance(expr, ast.Name):
            if isinstance(expr.ctx, ast.Load):
                self._use(expr, env, shadow)
            return
        if isinstance(expr, ast.Lambda):
            for d in expr.args.defaults + [
                    d for d in expr.args.kw_defaults if d is not None]:
                self._expr(d, env, shadow)
            inner = shadow | {a.arg for a in (
                list(expr.args.posonlyargs) + list(expr.args.args)
                + list(expr.args.kwonlyargs)
                + [v for v in (expr.args.vararg, expr.args.kwarg) if v])}
            self._expr(expr.body, env, inner)
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            inner = shadow
            for i, gen in enumerate(expr.generators):
                # first iterable evaluates in the enclosing scope
                self._expr(gen.iter, env, inner if i else shadow)
                inner = inner | {n.id for n in ast.walk(gen.target)
                                 if isinstance(n, ast.Name)}
                for cond in gen.ifs:
                    self._expr(cond, env, inner)
            if isinstance(expr, ast.DictComp):
                self._expr(expr.key, env, inner)
                self._expr(expr.value, env, inner)
            else:
                self._expr(expr.elt, env, inner)
            return
        if isinstance(expr, ast.Call):
            self._expr(expr.func, env, shadow)
            for a in expr.args:
                self._expr(a, env, shadow)
            for kw in expr.keywords:
                self._expr(kw.value, env, shadow)
            if self.call_effect is not None:
                eff = self.call_effect(expr, env)
                if eff:
                    for name, d in eff.items():
                        env[name] = frozenset({d})
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._expr(child, env, shadow)
            elif isinstance(child, (ast.comprehension, ast.keyword)):
                self._expr(getattr(child, "value", None) or
                           getattr(child, "iter", None), env, shadow)

    # -- binding ------------------------------------------------------------

    def _bind(self, target: ast.AST, env: Env, node: ast.AST,
              value: Optional[ast.AST], kind: str) -> None:
        """Record base-loads of complex targets, then (re)bind plain
        names.  ``x[i] = v`` / ``x.f = v`` mutate, not rebind — the
        base is a use and ``x`` keeps its definitions."""
        if isinstance(target, ast.Name):
            env[target.id] = frozenset(
                {Def(target.id, node, value, kind)})
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                # element-wise values are not tracked through unpacking
                self._bind(elt, env, node, None,
                           "unknown" if kind == "assign" else kind)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, env, node, None, "unknown")
        else:
            self._expr(target, env)

    # -- statement side -----------------------------------------------------

    def _walk(self, stmts: Sequence[ast.stmt],
              env: Optional[Env]) -> Optional[Env]:
        for stmt in stmts:
            if env is None:
                return None
            env = self._stmt(stmt, env)
        return env

    def _stmt(self, stmt: ast.stmt, env: Env) -> Optional[Env]:
        if isinstance(stmt, _FUNC_NODES):
            for d in stmt.decorator_list:
                self._expr(d, env)
            for d in stmt.args.defaults + [
                    x for x in stmt.args.kw_defaults if x is not None]:
                self._expr(d, env)
            env[stmt.name] = frozenset(
                {Def(stmt.name, stmt, None, "func")})
            return env
        if isinstance(stmt, ast.ClassDef):
            for d in stmt.decorator_list + stmt.bases:
                self._expr(d, env)
            env[stmt.name] = frozenset(
                {Def(stmt.name, stmt, None, "class")})
            return env
        if isinstance(stmt, ast.Return):
            self._expr(stmt.value, env)
            return None
        if isinstance(stmt, ast.Raise):
            self._expr(stmt.exc, env)
            self._expr(stmt.cause, env)
            return None
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, env)
            for t in stmt.targets:
                self._bind(t, env, stmt, stmt.value, "assign")
            return env
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, env)
                self._bind(stmt.target, env, stmt, stmt.value, "assign")
            return env
        if isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                # read-modify-write: the target is a use first
                self._use(stmt.target, env, frozenset())
            else:
                self._expr(stmt.target, env)
            self._expr(stmt.value, env)
            self._bind(stmt.target, env, stmt, None, "aug")
            return env
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    env[t.id] = frozenset()
                else:
                    self._expr(t, env)
            return env
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, env)
            taken = self._walk(stmt.body, dict(env))
            other = self._walk(stmt.orelse, dict(env)) \
                if stmt.orelse else dict(env)
            return _merge(taken, other)
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, env)
            once = self._walk(stmt.body, dict(env))
            merged = _merge(env, once)
            twice = self._walk(stmt.body, dict(merged)) \
                if merged is not None else None
            out = _merge(env, once, twice)
            if out is not None and stmt.orelse:
                out = self._walk(stmt.orelse, out)
            return out
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, env)
            first = dict(env)
            self._bind(stmt.target, first, stmt, None, "for")
            once = self._walk(stmt.body, first)
            merged = _merge(first, once)
            twice = None
            if merged is not None:
                self._bind(stmt.target, merged, stmt, None, "for")
                twice = self._walk(stmt.body, merged)
            out = _merge(env, once, twice)
            if out is not None and stmt.orelse:
                out = self._walk(stmt.orelse, out)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, env, stmt,
                               item.context_expr, "with")
            return self._walk(stmt.body, env)
        if isinstance(stmt, ast.Try) or (
                hasattr(ast, "TryStar")
                and isinstance(stmt, ast.TryStar)):
            snapshots: List[Env] = [dict(env)]
            cur: Optional[Env] = env
            for s in stmt.body:
                cur = self._stmt(s, cur)
                if cur is None:
                    break
                snapshots.append(dict(cur))
            handler_entry = _merge(*snapshots)
            handler_outs: List[Optional[Env]] = []
            for h in stmt.handlers:
                henv = dict(handler_entry or {})
                if h.type is not None:
                    self._expr(h.type, henv)
                if h.name:
                    henv[h.name] = frozenset(
                        {Def(h.name, h, None, "except")})
                handler_outs.append(self._walk(h.body, henv))
            if cur is not None and stmt.orelse:
                cur = self._walk(stmt.orelse, cur)
            merged_out = _merge(cur, *handler_outs)
            if stmt.finalbody:
                # the finally runs on normal flow, caught-and-handled
                # flow AND escaping flow — walk it from the union so
                # its uses see every state it can observe
                fin_in = _merge(merged_out, *snapshots, *handler_outs)
                fin_out = self._walk(stmt.finalbody, fin_in or {})
                return None if merged_out is None else fin_out
            return merged_out
        if isinstance(stmt, ast.Match):
            self._expr(stmt.subject, env)
            arms: List[Optional[Env]] = [dict(env)]  # no case may match
            for case in stmt.cases:
                cenv = dict(env)
                for n in ast.walk(case.pattern):
                    name = getattr(n, "name", None)
                    if isinstance(name, str):
                        cenv[name] = frozenset(
                            {Def(name, case.pattern, None, "unknown")})
                if case.guard is not None:
                    self._expr(case.guard, cenv)
                arms.append(self._walk(case.body, cenv))
            return _merge(*arms)
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            for name in stmt.names:
                env[name] = frozenset()
            return env
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for a in stmt.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name.split(".")[0]
                env[bound] = frozenset({Def(bound, stmt, None, "import")})
            return env
        if isinstance(stmt, (ast.Break, ast.Continue, ast.Pass)):
            return env
        # Expr / Assert / anything simple: record every expression
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, env)
        return env


# ---------------------------------------------------------------------------
# Per-file scope map + lazy DefUse cache
# ---------------------------------------------------------------------------

def own_nodes(scope: ast.AST) -> Iterable[ast.AST]:
    """Every node in `scope`'s body that belongs to its scope — stops
    at nested function definitions (their bodies are separate scopes;
    lambdas stay, they cannot contain statements)."""
    stack = list(getattr(scope, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _SCOPE_NODES):
            continue  # the def itself is visible, its body is not
        stack.extend(ast.iter_child_nodes(node))


class FileDataflow:
    """Scope discovery + lazily-built :class:`DefUse` per scope for
    one file.  Cached on the FileContext so the per-file tier (the
    HPX002 prover) and the dataflow tier share one instance."""

    def __init__(self, ctx: FileContext) -> None:
        self.ctx = ctx
        self.scopes: List[ast.AST] = [ctx.tree]
        self._scope_of: Dict[int, ast.AST] = {}
        self._du: Dict[int, DefUse] = {}

        def map_under(node: ast.AST, scope: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                self._scope_of[id(child)] = scope
                if isinstance(child, _SCOPE_NODES):
                    self.scopes.append(child)
                    map_under(child, child)
                else:
                    map_under(child, scope)

        map_under(ctx.tree, ctx.tree)

    def scope_of(self, node: ast.AST) -> ast.AST:
        return self._scope_of.get(id(node), self.ctx.tree)

    def defuse(self, scope: ast.AST,
               call_effect: Optional[CallEffect] = None) -> DefUse:
        if call_effect is not None:  # rule-specific: never cached
            return DefUse(scope, call_effect)
        du = self._du.get(id(scope))
        if du is None:
            du = DefUse(scope)
            self._du[id(scope)] = du
        return du


def get_file_dataflow(ctx: FileContext) -> FileDataflow:
    fdf = getattr(ctx, "_hpxlint_dataflow", None)
    if fdf is None:
        fdf = FileDataflow(ctx)
        ctx._hpxlint_dataflow = fdf  # type: ignore[attr-defined]
    return fdf


# ---------------------------------------------------------------------------
# Origin classification: is this value provably host or device data?
# ---------------------------------------------------------------------------

_HOST_PREFIXES = ("numpy.", "math.", "time.", "os.", "collections.",
                  "itertools.", "statistics.", "random.")
_HOST_BUILTINS = {"len", "int", "float", "bool", "str", "min", "max",
                  "sum", "abs", "round", "range", "sorted", "list",
                  "tuple", "dict", "set", "enumerate", "zip", "divmod",
                  "ord", "repr", "hash", "format"}
_HOST_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
_DEVICE_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.",
                    "jax.scipy.", "jax.ops.")
_DEVICE_CALLS = {"jax.device_put", "jax.tree_util.tree_map"}
_JIT_FUNCS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_PROGRAM_FACTORIES = _JIT_FUNCS | {
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "shard_map", "hpx_tpu.utils.jaxcompat.shard_map"}
# array methods that preserve the host/device-ness of their receiver
_ARRAY_METHODS = {"sum", "mean", "max", "min", "astype", "reshape",
                  "copy", "ravel", "any", "all", "dot", "transpose",
                  "squeeze", "flatten", "cumsum", "argmax", "argmin",
                  "block_until_ready", "clip", "round"}


def _is_getattr_shape(call: ast.Call, dotted: str) -> bool:
    return (dotted == "getattr" and len(call.args) >= 2
            and isinstance(call.args[1], ast.Constant)
            and call.args[1].value in _HOST_ATTRS)


def _join2(a: str, b: str) -> str:
    if a == "unknown" or b == "unknown":
        return "unknown"
    if a == b:
        return a
    return "device"  # jax wins numpy in mixed arithmetic


def classify_origin(expr: ast.AST, du: DefUse, ctx: FileContext,
                    _depth: int = 0,
                    _seen: Optional[Set[int]] = None) -> str:
    """'host' / 'device' / 'unknown' for the value of `expr`, chasing
    Name loads through their reaching definitions (all must agree)."""
    if _depth > 8 or expr is None:
        return "unknown"
    seen = _seen if _seen is not None else set()
    if isinstance(expr, ast.Constant):
        return "host"
    if isinstance(expr, ast.Name):
        defs = du.use_at.get(id(expr))
        if not defs:
            return "unknown"
        verdict = None
        for d in defs:
            if id(d) in seen:
                continue  # cycle through a loop back edge: ignore
            seen.add(id(d))
            if d.kind not in ("assign", "with"):
                return "unknown"
            got = classify_origin(d.value, du, ctx, _depth + 1, seen)
            if got == "unknown":
                return "unknown"
            if verdict is None:
                verdict = got
            elif verdict != got:
                return "unknown"
        return verdict or "unknown"
    if isinstance(expr, ast.Subscript):
        return classify_origin(expr.value, du, ctx, _depth + 1, seen)
    if isinstance(expr, ast.Attribute):
        if expr.attr in _HOST_ATTRS:
            return "host"
        return "unknown"
    if isinstance(expr, ast.Call):
        dotted = ctx.resolve_call(expr.func)
        if dotted:
            if dotted.startswith(_HOST_PREFIXES) \
                    or dotted in _HOST_BUILTINS \
                    or _is_getattr_shape(expr, dotted):
                return "host"
            if dotted.startswith(_DEVICE_PREFIXES) \
                    or dotted in _DEVICE_CALLS:
                return "device"
            if dotted in _PROGRAM_FACTORIES:
                return "unknown"  # a callable, not an array
        if isinstance(expr.func, ast.Call):
            inner = ctx.resolve_call(expr.func.func)
            if inner in _PROGRAM_FACTORIES:
                return "device"  # jax.jit(f, ...)(x)
        if isinstance(expr.func, ast.Name):
            defs = du.use_at.get(id(expr.func))
            if defs and all(
                    d.kind == "assign" and isinstance(d.value, ast.Call)
                    and ctx.resolve_call(d.value.func)
                    in _PROGRAM_FACTORIES for d in defs):
                return "device"  # prog = jax.jit(f); prog(x)
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in _ARRAY_METHODS:
            return classify_origin(expr.func.value, du, ctx,
                                   _depth + 1, seen)
        return "unknown"
    if isinstance(expr, ast.BinOp):
        return _join2(
            classify_origin(expr.left, du, ctx, _depth + 1, seen),
            classify_origin(expr.right, du, ctx, _depth + 1, seen))
    if isinstance(expr, ast.UnaryOp):
        return classify_origin(expr.operand, du, ctx, _depth + 1, seen)
    if isinstance(expr, (ast.BoolOp,)):
        got = [classify_origin(v, du, ctx, _depth + 1, seen)
               for v in expr.values]
        out = got[0]
        for g in got[1:]:
            out = out if out == g else "unknown"
        return out
    if isinstance(expr, ast.Compare):
        out = classify_origin(expr.left, du, ctx, _depth + 1, seen)
        for c in expr.comparators:
            out = _join2(out, classify_origin(c, du, ctx,
                                              _depth + 1, seen))
        return out
    if isinstance(expr, ast.IfExp):
        a = classify_origin(expr.body, du, ctx, _depth + 1, seen)
        b = classify_origin(expr.orelse, du, ctx, _depth + 1, seen)
        return a if a == b else "unknown"
    if isinstance(expr, (ast.Tuple, ast.List)):
        got = {classify_origin(e, du, ctx, _depth + 1, seen)
               for e in expr.elts}
        return "host" if got == {"host"} else "unknown"
    return "unknown"


def provably_host(expr: ast.AST, ctx: FileContext) -> bool:
    """True when every reaching definition of `expr` is host data —
    the HPX002 token rule calls this to drop sinks that can never
    touch the device (``int(np.flatnonzero(...)[0])`` and friends)."""
    fdf = get_file_dataflow(ctx)
    du = fdf.defuse(fdf.scope_of(expr))
    return classify_origin(expr, du, ctx) == "host"


# ---------------------------------------------------------------------------
# DataflowIndex: project-wide summaries shared by the tier-3 rules
# ---------------------------------------------------------------------------

def _call_desc(func: ast.AST) -> Optional[tuple]:
    """The ProjectIndex call descriptor for a call's func expression
    (same shapes _scan_exprs collects)."""
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                return ("self", func.attr)
            return ("dotted", base.id, func.attr)
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self":
            return ("selfattr", base.attr, func.attr)
        return None
    if isinstance(func, ast.Name):
        return ("name", func.id)
    return None


def _literal_ints(node: ast.AST) -> FrozenSet[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.add(e.value)
        return frozenset(out)
    return frozenset()


def jit_donate_positions(call: ast.Call,
                         ctx: FileContext) -> FrozenSet[int]:
    """Donated argument positions of a ``jax.jit(f, donate_argnums=...)``
    call expression ('' when the callee is not a jit family member or
    the positions are not literal)."""
    if ctx.resolve_call(call.func) not in _JIT_FUNCS:
        return frozenset()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_ints(kw.value)
    return frozenset()


class DataflowIndex:
    """The ProjectIndex plus the one-level interprocedural summaries
    the tier-3 rules share: locks held at every resolved call site
    (→ entry-held sets, the HPX013 machinery reused one level deep)
    and jit-donation positions of program-factory returns."""

    def __init__(self, index: ProjectIndex) -> None:
        self.index = index
        self._entry_held: Optional[Dict[str, FrozenSet[str]]] = None
        self._donate_summary: Dict[str, FrozenSet[int]] = {}
        self._info_of_node: Dict[int, FunctionInfo] = {
            id(info.node): info for info in index.functions.values()}

    def file_dataflow(self, display_path: str) -> FileDataflow:
        return get_file_dataflow(self.index.contexts[display_path])

    def info_for(self, fn_node: ast.AST) -> Optional[FunctionInfo]:
        return self._info_of_node.get(id(fn_node))

    def entry_held(self, qname: str) -> FrozenSet[str]:
        """Locks held at EVERY resolved call site of `qname` (one
        level: the callers' lexical held sets, no propagation).
        Empty for functions without resolved in-edges."""
        if self._entry_held is None:
            eh: Dict[str, FrozenSet[str]] = {}
            for q in sorted(self.index.functions):
                info = self.index.functions[q]
                for desc, _node, held in info.calls:
                    for callee in self.index.resolve_call(info, desc):
                        s = frozenset(held)
                        eh[callee] = s if callee not in eh \
                            else (eh[callee] & s)
            self._entry_held = eh
        return self._entry_held.get(qname, frozenset())

    def jit_donate_summary(self, qname: str) -> FrozenSet[int]:
        """Donated positions when `qname` returns a jit-donate call
        (``def _jit_step(...): return jax.jit(step, donate_argnums=..)``)
        — the one-level summary HPX020 chases factory calls through."""
        if qname in self._donate_summary:
            return self._donate_summary[qname]
        out: FrozenSet[int] = frozenset()
        info = self.index.functions.get(qname)
        if info is not None and isinstance(info.node, _FUNC_NODES):
            ctx = self.index.contexts.get(info.path)
            if ctx is not None:
                for node in own_nodes(info.node):
                    if isinstance(node, ast.Return) \
                            and isinstance(node.value, ast.Call):
                        out = out | jit_donate_positions(node.value, ctx)
        self._donate_summary[qname] = out
        return out


# ---------------------------------------------------------------------------
# HPX019 — unguarded shared state (inferred guarded-by)
# ---------------------------------------------------------------------------

_HPX019_SUBPATHS = ("hpx_tpu/svc/", "hpx_tpu/models/", "hpx_tpu/cache/",
                    "hpx_tpu/dist/")
_INIT_METHODS = {"__init__", "__post_init__", "__new__",
                 "__init_subclass__"}


@register
class UnguardedSharedState(DataflowRule):
    """HPX019: an instance attribute is mutated under a lock at most
    sites but bare at others — the classic torn-update race that turns
    into corrupted state once ROADMAP item 1 splits the fleet into
    real localities.  The guard is INFERRED: when a strict majority of
    a ``self.attr``'s non-``__init__`` mutation sites (in ``svc/``,
    ``models/``, ``cache/``, ``dist/``) hold the same registered lock
    — lexically or via every caller (one-level entry-held sets) — the
    remaining bare sites are flagged.  Attributes touched by only one
    method (scratch) and ``__init__``-only attributes are exempt.
    Fix: widen the critical section to cover the bare site, or
    justify single-threaded access with an inline
    ``# hpxlint: disable=HPX019 — <why>``."""

    id = "HPX019"
    name = "unguarded-shared-state"
    severity = "error"

    def check_dataflow(self, dfx: DataflowIndex) -> Iterable[Finding]:
        index = dfx.index
        # (module, cls) -> attr -> [(kind, node, held_eff, info)]
        groups: Dict[Tuple[str, str],
                     Dict[str, List[tuple]]] = {}
        for q in sorted(index.functions):
            info = index.functions[q]
            if info.cls is None:
                continue
            if not any(s in info.path for s in _HPX019_SUBPATHS):
                continue
            eff = dfx.entry_held(q)
            for kind, attr, node, held in info.attr_ops:
                groups.setdefault((info.module, info.cls), {}) \
                    .setdefault(attr, []) \
                    .append((kind, node, frozenset(held) | eff, info))
        for mod_cls in sorted(groups):
            _mod, cls = mod_cls
            for attr in sorted(groups[mod_cls]):
                ops = groups[mod_cls][attr]
                if len({op[3].qname for op in ops}) <= 1:
                    continue  # single-method scratch attribute
                muts = [op for op in ops if op[0] == "write"
                        and op[3].node.name not in _INIT_METHODS]
                if not muts:
                    continue  # __init__-only (or read-only) attribute
                counts: Dict[str, int] = {}
                for _k, _n, held, _i in muts:
                    for lid in held:
                        counts[lid] = counts.get(lid, 0) + 1
                if not counts:
                    continue  # never guarded anywhere: no contract
                guard = max(sorted(counts), key=lambda L: counts[L])
                n_held, total = counts[guard], len(muts)
                if 2 * n_held <= total:
                    continue  # no majority: no inferable contract
                short = ".".join(guard.split(".")[-2:])
                for _k, node, held, info in muts:
                    if guard in held:
                        continue
                    yield self.finding_at(
                        info.path, node,
                        f"self.{attr} is mutated in "
                        f"{cls}.{info.node.name}() without holding "
                        f"{short} — {n_held} of {total} mutation sites "
                        "hold it (inferred guarded-by); widen the "
                        "critical section or justify the bare access")


# ---------------------------------------------------------------------------
# HPX020 — donation use-after-donate
# ---------------------------------------------------------------------------

@register
class DonationUseAfterDonate(DataflowRule):
    """HPX020: a binding passed at a donated position of a jitted call
    (``donate_argnums``) is used again afterwards — XLA aliases the
    donated buffer into the outputs, so the old array is dead and
    reads return garbage (or error under
    ``jax_debug_nans``-style guards).  Tracked through def-use
    chains: direct ``jax.jit(f, donate_argnums=..)(x)`` calls,
    programs bound to locals, and one level of factory indirection
    (``prog = self._jit_step(step)`` where the factory returns a
    jit-donate call).  Fix: rebind the result over the donated name
    (``x, s = prog(x, s)``) or stop donating that argument."""

    id = "HPX020"
    name = "donation-use-after-donate"
    severity = "error"

    def check_dataflow(self, dfx: DataflowIndex) -> Iterable[Finding]:
        index = dfx.index
        for path in sorted(index.contexts):
            ctx = index.contexts[path]
            if "donate_argnums" not in ctx.source:
                continue
            fdf = dfx.file_dataflow(path)
            for scope in fdf.scopes:
                if not isinstance(scope, _FUNC_NODES):
                    continue
                info = dfx.info_for(scope)

                def effect(call: ast.Call, env: Env,
                           _info=info) -> Optional[Dict[str, Def]]:
                    positions: Set[int] = set()
                    func = call.func
                    if isinstance(func, ast.Call):
                        positions |= jit_donate_positions(func, ctx)
                    elif isinstance(func, ast.Name):
                        for d in env.get(func.id, ()):
                            v = d.value
                            if not isinstance(v, ast.Call):
                                continue
                            positions |= jit_donate_positions(v, ctx)
                            desc = _call_desc(v.func)
                            if desc and _info is not None:
                                for callee in index.resolve_call(
                                        _info, desc):
                                    positions |= \
                                        dfx.jit_donate_summary(callee)
                    if not positions:
                        return None
                    out: Dict[str, Def] = {}
                    for p in sorted(positions):
                        if p < len(call.args) \
                                and isinstance(call.args[p], ast.Name):
                            name = call.args[p].id
                            out[name] = Def(name, call, None, "donated")
                    return out or None

                du = fdf.defuse(scope, call_effect=effect)
                seen_sites: Set[Tuple[int, int]] = set()
                for use in du.uses:
                    if not any(d.kind == "donated" for d in use.defs):
                        continue
                    site = (use.node.lineno, use.node.col_offset)
                    if site in seen_sites:
                        continue  # loops record uses twice
                    seen_sites.add(site)
                    yield self.finding_at(
                        path, use.node,
                        f"`{use.name}` is used after being donated to "
                        "a jitted call — XLA aliases donated buffers "
                        "into the outputs, so this read sees freed "
                        "memory; rebind the call's result over "
                        f"`{use.name}` or drop it from donate_argnums")


# ---------------------------------------------------------------------------
# HPX021 — mesh-axis consistency inside shard_map bodies
# ---------------------------------------------------------------------------

_SHARD_MAP_NAMES = {"shard_map"}
_PSPEC_NAMES = {"P", "PartitionSpec"}
_COLLECTIVE_AXIS_ARG = {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
                        "ppermute": 1, "all_gather": 1, "all_to_all": 1,
                        "psum_scatter": 1, "axis_index": 0, "pvary": 1}


def _axis_literals(node: ast.AST) -> FrozenSet[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
        return frozenset(out)
    return frozenset()


def _pspec_axes(expr: ast.AST, ctx: FileContext) -> FrozenSet[str]:
    """Axis-name string literals inside P(...)/PartitionSpec(...)
    fragments of a specs expression."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            callee = ctx.resolve_call(node.func)
            if callee.split(".")[-1] in _PSPEC_NAMES:
                for a in node.args:
                    out |= _axis_literals(a)
    return frozenset(out)


def _specs_axes_complete(expr: ast.AST, du: DefUse, ctx: FileContext,
                         depth: int = 0) -> Optional[FrozenSet[str]]:
    """The FULL axis set of a specs expression, or None when any
    fragment is opaque (a call result, a variable P(axis), ...) — an
    incomplete declared set must skip the check, never flag against
    it.  Spec names are chased one def-use hop (``data_spec =
    P("dp", None)``)."""
    if depth > 4:
        return None
    if isinstance(expr, ast.Constant):
        # P(None) / spec=None placeholders declare nothing
        return frozenset() if expr.value is None else None
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in expr.elts:
            got = _specs_axes_complete(e, du, ctx, depth + 1)
            if got is None:
                return None
            out |= got
        return frozenset(out)
    if isinstance(expr, ast.Call):
        if ctx.resolve_call(expr.func).split(".")[-1] \
                not in _PSPEC_NAMES:
            return None
        out = set()
        for a in expr.args:
            if isinstance(a, ast.Constant) and a.value is None:
                continue
            lits = _axis_literals(a)
            if not lits:
                return None  # P(axis) with a variable: opaque
            out |= lits
        return frozenset(out)
    if isinstance(expr, ast.Name):
        defs = du.use_at.get(id(expr))
        if not defs:
            return None
        out = set()
        for d in defs:
            if d.value is None:
                return None
            got = _specs_axes_complete(d.value, du, ctx, depth + 1)
            if got is None:
                return None
            out |= got
        return frozenset(out)
    return None


def _mesh_axes_from_call(call: ast.Call,
                         ctx: FileContext) -> FrozenSet[str]:
    if ctx.resolve_call(call.func).split(".")[-1] not in (
            "Mesh", "AbstractMesh", "make_mesh"):
        return frozenset()
    axes: FrozenSet[str] = frozenset()
    if len(call.args) >= 2:
        axes = axes | _axis_literals(call.args[1])
    for kw in call.keywords:
        if kw.arg in ("axis_names", "axis_name"):
            axes = axes | _axis_literals(kw.value)
    return axes


@register
class MeshAxisConsistency(DataflowRule):
    """HPX021: a collective (``psum``/``ppermute``/``all_gather``/...)
    or ``PartitionSpec`` fragment inside a ``shard_map`` body names an
    axis the enclosing mesh/specs never declare — jax raises a
    NameError-like failure only when that branch first traces on a
    pod, long after the edit that renamed the axis.  Declared axes are
    collected from literal ``Mesh(..., ("dp","tp"))`` axis tuples
    (chased through def-use when ``mesh=`` is a local name) and from
    literal P()/PartitionSpec() fragments in ``in_specs``/
    ``out_specs``; bodies are resolved through local def-use (named
    inner functions, lambdas, ``functools.partial``) plus same-file
    helpers they call.  Sites whose axis set cannot be resolved
    statically are skipped, not guessed.  Fix: use the axis names the
    mesh declares, or thread the axis name in as a parameter."""

    id = "HPX021"
    name = "mesh-axis-consistency"
    severity = "error"

    def check_dataflow(self, dfx: DataflowIndex) -> Iterable[Finding]:
        index = dfx.index
        for path in sorted(index.contexts):
            ctx = index.contexts[path]
            if "shard_map" not in ctx.source:
                continue
            fdf = dfx.file_dataflow(path)
            module_defs = {
                s.name: s for s in ctx.tree.body
                if isinstance(s, _FUNC_NODES)}
            for scope in fdf.scopes:
                for node in own_nodes(scope):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = ctx.resolve_call(node.func)
                    if callee.split(".")[-1] not in _SHARD_MAP_NAMES:
                        continue
                    yield from self._check_site(
                        node, scope, ctx, fdf, module_defs, path)

    def _check_site(self, sm: ast.Call, scope: ast.AST,
                    ctx: FileContext, fdf: FileDataflow,
                    module_defs: Dict[str, ast.AST],
                    path: str) -> Iterable[Finding]:
        du = fdf.defuse(scope)
        # the mesh declares the COMPLETE axis universe; specs only
        # reference it.  Resolve the mesh first (literal call, or a
        # local chased one def-use hop); only when the mesh is opaque
        # fall back to the specs — and then only if EVERY fragment
        # resolves, because flagging against a partial set invents
        # false positives
        declared: Set[str] = set()
        spec_exprs = []
        for kw in sm.keywords:
            if kw.arg == "mesh":
                if isinstance(kw.value, ast.Call):
                    declared |= _mesh_axes_from_call(kw.value, ctx)
                elif isinstance(kw.value, ast.Name):
                    for d in du.use_at.get(id(kw.value), ()):
                        if isinstance(d.value, ast.Call):
                            declared |= _mesh_axes_from_call(
                                d.value, ctx)
            elif kw.arg in ("in_specs", "out_specs"):
                spec_exprs.append(kw.value)
        if not declared:
            for expr in spec_exprs:
                got = _specs_axes_complete(expr, du, ctx)
                if got is None:
                    return  # opaque fragment: skip, don't guess
                declared |= got
        if not declared:
            return  # unresolvable statically: skip, don't guess

        body = self._resolve_body(
            sm.args[0] if sm.args else None, du, ctx, module_defs)
        if body is None:
            return
        decl = ", ".join(sorted(declared))
        seen_fns: Set[int] = set()
        queue: List[Tuple[str, ast.AST]] = [body]
        while queue:
            fname, fnode = queue.pop(0)
            if id(fnode) in seen_fns:
                continue
            seen_fns.add(id(fnode))
            nodes = own_nodes(fnode) if hasattr(fnode, "body") \
                else ast.walk(fnode)
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                callee = ctx.resolve_call(node.func)
                leaf = callee.split(".")[-1]
                if callee.startswith("jax.") \
                        and leaf in _COLLECTIVE_AXIS_ARG:
                    pos = _COLLECTIVE_AXIS_ARG[leaf]
                    axis_expr = None
                    if len(node.args) > pos:
                        axis_expr = node.args[pos]
                    for kw in node.keywords:
                        if kw.arg == "axis_name":
                            axis_expr = kw.value
                    if axis_expr is None:
                        continue
                    for ax in sorted(_axis_literals(axis_expr)):
                        if ax not in declared:
                            yield self.finding_at(
                                path, node,
                                f"{leaf}() over axis '{ax}' inside "
                                f"shard_map body `{fname}` — the "
                                "enclosing mesh/specs only declare "
                                f"({decl}); rename the axis or thread "
                                "it in as a parameter")
                elif leaf in _PSPEC_NAMES and callee != leaf:
                    for a in node.args:
                        for ax in sorted(_axis_literals(a)):
                            if ax not in declared:
                                yield self.finding_at(
                                    path, node,
                                    f"PartitionSpec axis '{ax}' inside "
                                    f"shard_map body `{fname}` — the "
                                    "enclosing mesh/specs only declare "
                                    f"({decl}); rename the axis or "
                                    "thread it in as a parameter")
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in module_defs:
                    queue.append((node.func.id,
                                  module_defs[node.func.id]))

    def _resolve_body(self, expr: Optional[ast.AST], du: DefUse,
                      ctx: FileContext,
                      module_defs: Dict[str, ast.AST]
                      ) -> Optional[Tuple[str, ast.AST]]:
        if expr is None:
            return None
        if isinstance(expr, ast.Lambda):
            return ("<lambda>", expr.body)
        if isinstance(expr, ast.Call):  # functools.partial(f, ...)
            if ctx.resolve_call(expr.func).split(".")[-1] == "partial" \
                    and expr.args:
                return self._resolve_body(expr.args[0], du, ctx,
                                          module_defs)
            return None
        if isinstance(expr, ast.Name):
            for d in du.use_at.get(id(expr), ()):
                if d.kind == "func":
                    return (expr.id, d.node)
            if expr.id in module_defs:
                return (expr.id, module_defs[expr.id])
        return None


# ---------------------------------------------------------------------------
# HPX022 — flow-sensitive host sync (HPX002 on dataflow)
# ---------------------------------------------------------------------------

_SYNC_BUILTINS = {"float", "int", "bool"}


@register
class FlowSensitiveHostSync(DataflowRule):
    """HPX022: a value that is device-origin on EVERY reaching
    definition (jax.numpy/jax.lax results, jitted-program outputs)
    flows into ``float()``/``int()``/``bool()``/``np.array()`` in
    hot-path code (``hpx_tpu/{futures,exec,algo,ops}``) — the same
    dispatch-pipeline stall HPX002 catches lexically, found through
    def-use chains on sinks the token rule cannot see (bare names
    instead of subscripts).  Sinks HPX002 already reports are skipped,
    so the two rules never double-report one site.  Fix: keep the
    value a jax.Array, or sync at the consumer boundary with an
    inline ``# hpxlint: disable=HPX022 — <why>``."""

    id = "HPX022"
    name = "flow-sensitive-host-sync"
    severity = "error"

    def check_dataflow(self, dfx: DataflowIndex) -> Iterable[Finding]:
        from .rules import HOT_SUBPATHS
        index = dfx.index
        for path in sorted(index.contexts):
            ctx = index.contexts[path]
            if not ctx.in_subpath(*HOT_SUBPATHS):
                continue
            fdf = dfx.file_dataflow(path)
            for scope in fdf.scopes:
                sinks: List[Tuple[ast.Call, str, ast.AST]] = []
                for node in own_nodes(scope):
                    if not isinstance(node, ast.Call):
                        continue
                    if isinstance(node.func, ast.Name) \
                            and node.func.id in _SYNC_BUILTINS \
                            and len(node.args) == 1 \
                            and isinstance(node.args[0], ast.Name):
                        # float(x[i]) is HPX002's token sink; float(x)
                        # on a bare name is ours
                        sinks.append((node, node.func.id,
                                      node.args[0]))
                    elif ctx.resolve_call(node.func) == "numpy.array" \
                            and node.args:
                        # np.asarray is HPX002's; np.array is not
                        sinks.append((node, "np.array", node.args[0]))
                if not sinks:
                    continue
                du = fdf.defuse(scope)
                seen: Set[Tuple[int, int]] = set()
                for call, label, arg in sinks:
                    site = (call.lineno, call.col_offset)
                    if site in seen:
                        continue
                    seen.add(site)
                    if classify_origin(arg, du, ctx) != "device":
                        continue
                    what = arg.id if isinstance(arg, ast.Name) \
                        else "its argument"
                    yield self.finding_at(
                        path, call,
                        f"{label}({what}) forces a device->host sync "
                        f"in hot-path code: `{what}` is device-origin "
                        "on every reaching definition — keep it a "
                        "jax.Array or sync at the consumer boundary")
