"""hpxlint engine: findings, rule registry, suppressions, baseline.

Pure stdlib (`ast` + `tokenize` + `json`): the linter must be runnable
in CI images that have no accelerator stack at all, and importing it
must never pull in jax — rules reason about *source*, not live objects.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Type

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: rule id, severity, location, stable message.

    Messages must be deterministic and free of line numbers — the
    baseline matches on ``(path, rule, message)`` so findings survive
    unrelated edits that shift lines.
    """

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.message)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Type["Rule"]] = {}


def register(cls: Type["Rule"]) -> Type["Rule"]:
    """Class decorator adding a rule to the global registry."""
    if not re.fullmatch(r"HPX\d{3}", cls.id):
        raise ValueError(f"rule id must look like HPX001, got {cls.id!r}")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"{cls.id}: severity must be one of {SEVERITIES}")
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


class Rule:
    """Base class: subclass, set the class attrs, implement check().

    check() receives a :class:`FileContext` and yields findings via
    ``self.finding(ctx, node, message)``.  Keep messages line-number
    free (see Finding) and make each rule's docstring say how to fix
    the violation — the CLI prints it for ``--list-rules``.
    """

    id: str = "HPX000"
    name: str = ""
    severity: str = "error"

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=ctx.display_path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message)


def all_rules(select: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instances of every registered rule (or the selected subset, by
    id or name), in id order."""
    from . import rules as _rules  # noqa: F401  (registers on import)

    chosen = []
    for rid in sorted(_REGISTRY):
        cls = _REGISTRY[rid]
        if select and rid not in select and cls.name not in select:
            continue
        chosen.append(cls())
    if select and not chosen:
        known = [f"{r} ({_REGISTRY[r].name})" for r in sorted(_REGISTRY)]
        raise ValueError(f"--select matched no rules; known: {known}")
    return chosen


# ---------------------------------------------------------------------------
# Per-file context: parsed tree, import aliases, suppressions
# ---------------------------------------------------------------------------

class FileContext:
    """Everything a rule needs about one file, computed once."""

    def __init__(self, source: str, display_path: str) -> None:
        self.source = source
        # posix-style path as shown in findings and matched by the
        # baseline; callers pass paths relative to the scan root (repo
        # root in CI) so records are machine-independent
        self.display_path = display_path.replace(os.sep, "/")
        self.tree = ast.parse(source)
        self._aliases = _import_aliases(self.tree)

    def resolve_call(self, func: ast.AST) -> str:
        """Canonical dotted name of a call target, import-aliases
        resolved: ``np.asarray`` -> ``numpy.asarray`` under
        ``import numpy as np``; ``Lock`` -> ``threading.Lock`` under
        ``from threading import Lock``.  Unresolvable shapes
        (subscripts, calls-of-calls) give ''."""
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return ""
        parts.append(node.id)
        parts.reverse()
        head = parts[0]
        if head in self._aliases:
            parts[0:1] = self._aliases[head].split(".")
        return ".".join(parts)

    def in_subpath(self, *fragments: str) -> bool:
        return any(f in self.display_path for f in fragments)


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name != "*":
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


# ---------------------------------------------------------------------------
# Inline suppressions
# ---------------------------------------------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*hpxlint:\s*(disable|disable-next|disable-file)"
    r"\s*=\s*([A-Za-z0-9_,\-\s]+)")


class Suppressions:
    """Parsed ``# hpxlint:`` directives for one file.

    * ``# hpxlint: disable=HPX003``        — this line (trailing comment);
      on a comment-only line it behaves like ``disable-next``
    * ``# hpxlint: disable-next=HPX003``   — the next *code* line
      (continuation comment lines in between are skipped, so a
      justification may span several comment lines)
    * ``# hpxlint: disable-file=HPX004``   — the whole file
    * ``all`` suppresses every rule; ids and rule names both work.

    A justification belongs in the same comment, after the directive:
    ``# hpxlint: disable=HPX002 — boundary sync, see docstring``.
    """

    def __init__(self, source: str) -> None:
        self.by_line: Dict[int, set] = {}
        self.whole_file: set = set()
        code_lines: set = set()
        _skip = (tokenize.COMMENT, tokenize.NL, tokenize.NEWLINE,
                 tokenize.INDENT, tokenize.DEDENT, tokenize.ENDMARKER,
                 tokenize.ENCODING)
        try:
            comments = []
            for t in tokenize.generate_tokens(io.StringIO(source).readline):
                if t.type == tokenize.COMMENT:
                    comments.append((t.start[0], t.string))
                elif t.type not in _skip:
                    for ln in range(t.start[0], t.end[0] + 1):
                        code_lines.add(ln)
        except (tokenize.TokenError, IndentationError):
            comments = []
        for lineno, text in comments:
            m = _DIRECTIVE.search(text)
            if not m:
                continue
            kind = m.group(1)
            names = {n.strip() for n in
                     m.group(2).split("—")[0].split(",") if n.strip()}
            if kind == "disable-file":
                self.whole_file |= names
                continue
            if kind == "disable" and lineno in code_lines:
                target = lineno            # trailing comment on a code line
            else:
                # disable-next, or a standalone disable comment: apply to
                # the next code line so justifications can span lines
                target = next((ln for ln in sorted(code_lines)
                               if ln > lineno), lineno + 1)
            self.by_line.setdefault(target, set()).update(names)

    def suppresses(self, finding: Finding) -> bool:
        rule_cls = _REGISTRY.get(finding.rule)
        labels = {finding.rule, "all"}
        if rule_cls is not None:
            labels.add(rule_cls.name)
        if labels & self.whole_file:
            return True
        return bool(labels & self.by_line.get(finding.line, set()))


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int = 0
    checked_files: int = 0


def lint_source(source: str, display_path: str,
                rules: Optional[Sequence[Rule]] = None) -> LintResult:
    """Lint one in-memory source blob (the unit the fixture tests use)."""
    rules = list(rules) if rules is not None else all_rules()
    try:
        ctx = FileContext(source, display_path)
    except SyntaxError as e:
        return LintResult(findings=[Finding(
            rule="HPX000", severity="error",
            path=display_path.replace(os.sep, "/"),
            line=e.lineno or 1, col=(e.offset or 0) or 1,
            message=f"syntax error: {e.msg}")], checked_files=1)
    sup = Suppressions(source)
    kept: List[Finding] = []
    n_sup = 0
    for rule in rules:
        for f in rule.check(ctx):
            if sup.suppresses(f):
                n_sup += 1
            else:
                kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=kept, suppressed=n_sup, checked_files=1)


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        else:
            raise FileNotFoundError(p)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    rules = list(rules) if rules is not None else all_rules()
    total = LintResult(findings=[])
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))  # parent of hpx_tpu/
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        absolute = os.path.abspath(path)
        if absolute.startswith(root + os.sep):
            # anchor at the repo root so baseline paths match no matter
            # what cwd or path spelling the linter was invoked with
            display = os.path.relpath(absolute, root)
        else:
            rel = os.path.relpath(path)
            # keep display paths rooted at the scan target, never "../.."
            display = path if rel.startswith("..") else rel
        res = lint_source(source, display, rules)
        total.findings.extend(res.findings)
        total.suppressed += res.suppressed
        total.checked_files += 1
    total.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return total


# ---------------------------------------------------------------------------
# Baseline: committed record of accepted pre-existing findings
# ---------------------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "hpxlint_baseline.json")


def load_baseline(path: str = DEFAULT_BASELINE
                  ) -> Dict[Tuple[str, str, str], int]:
    """{(path, rule, message): allowed_count}. Missing file = empty."""
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except OSError:
        return {}
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in rec.get("entries", []):
        key = (e["path"], e["rule"], e["message"])
        budget[key] = budget.get(key, 0) + int(e.get("count", 1))
    return budget


def apply_baseline(findings: Sequence[Finding],
                   budget: Dict[Tuple[str, str, str], int],
                   ) -> Tuple[List[Finding], int]:
    """Split findings into (new, n_baselined): each baseline entry
    absorbs up to `count` findings with the same (path, rule, message)."""
    remaining = dict(budget)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        k = f.baseline_key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            matched += 1
        else:
            new.append(f)
    return new, matched


def write_baseline(findings: Sequence[Finding], path: str,
                   justification: str = "accepted pre-existing finding "
                   "(hpxlint --write-baseline)") -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    lines: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        k = f.baseline_key()
        counts[k] = counts.get(k, 0) + 1
        lines.setdefault(k, f.line)
    entries = [{"path": p, "rule": r, "message": m, "count": c,
                "near_line": lines[(p, r, m)],
                "justification": justification}
               for (p, r, m), c in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "hpxlint baseline — pre-existing findings "
                   "accepted with justification; new findings beyond "
                   "these counts fail the gate. near_line is advisory "
                   "only (matching ignores it).",
                   "entries": entries}, f, indent=1)
        f.write("\n")
