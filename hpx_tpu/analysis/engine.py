"""hpxlint engine: findings, rule registry, suppressions, baseline.

Pure stdlib (`ast` + `tokenize` + `json`): the linter must be runnable
in CI images that have no accelerator stack at all, and importing it
must never pull in jax — rules reason about *source*, not live objects.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Type

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: rule id, severity, location, stable message.

    Messages must be deterministic and free of line numbers — the
    baseline matches on ``(path, rule, message)`` so findings survive
    unrelated edits that shift lines.
    """

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.message)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Type["Rule"]] = {}


def register(cls: Type["Rule"]) -> Type["Rule"]:
    """Class decorator adding a rule to the global registry."""
    if not re.fullmatch(r"HPX\d{3}", cls.id):
        raise ValueError(f"rule id must look like HPX001, got {cls.id!r}")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"{cls.id}: severity must be one of {SEVERITIES}")
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


class Rule:
    """Base class: subclass, set the class attrs, implement check().

    check() receives a :class:`FileContext` and yields findings via
    ``self.finding(ctx, node, message)``.  Keep messages line-number
    free (see Finding) and make each rule's docstring say how to fix
    the violation — the CLI prints it for ``--list-rules``.
    """

    id: str = "HPX000"
    name: str = ""
    severity: str = "error"
    scope: str = "file"

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=ctx.display_path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message)


class ProjectRule(Rule):
    """Whole-program rule: runs once per lint over the shared
    :class:`~.project.ProjectIndex` (every file parsed exactly once,
    symbol/lock/call information pre-resolved) instead of once per
    file.  Subclasses implement check_project(); check() never runs.
    """

    scope: str = "project"

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        return ()

    def check_project(self, index) -> Iterable[Finding]:
        raise NotImplementedError

    def finding_at(self, display_path: str, node: ast.AST,
                   message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=display_path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message)


class DataflowRule(ProjectRule):
    """Tier-3 rule: runs once per lint over the shared
    :class:`~.dataflow.DataflowIndex` (the ProjectIndex plus def-use
    chains and one-level interprocedural summaries, still one parse
    per file).  Subclasses implement check_dataflow()."""

    scope: str = "dataflow"

    def check_project(self, index) -> Iterable[Finding]:
        return ()

    def check_dataflow(self, dfx) -> Iterable[Finding]:
        raise NotImplementedError


def all_rules(select: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instances of every registered rule (or the selected subset, by
    id or name), in id order."""
    from . import rules as _rules  # noqa: F401  (registers on import)

    from . import project as _project  # noqa: F401  (registers on import)

    from . import dataflow as _dataflow  # noqa: F401  (registers on import)

    chosen = []
    for rid in sorted(_REGISTRY):
        cls = _REGISTRY[rid]
        if select and rid not in select and cls.name not in select:
            continue
        chosen.append(cls())
    if select and not chosen:
        known = [f"{r} ({_REGISTRY[r].name})" for r in sorted(_REGISTRY)]
        raise ValueError(f"--select matched no rules; known: {known}")
    return chosen


# ---------------------------------------------------------------------------
# Per-file context: parsed tree, import aliases, suppressions
# ---------------------------------------------------------------------------

# Total ast.parse calls since import — the perf-guard test asserts a
# full two-tier run over N files bumps this by exactly N (the project
# tier shares the per-file tier's parsed trees, never re-parses).
_PARSE_COUNT = 0


def parse_count() -> int:
    return _PARSE_COUNT


class FileContext:
    """Everything a rule needs about one file, computed once."""

    def __init__(self, source: str, display_path: str) -> None:
        global _PARSE_COUNT
        self.source = source
        # posix-style path as shown in findings and matched by the
        # baseline; callers pass paths relative to the scan root (repo
        # root in CI) so records are machine-independent
        self.display_path = display_path.replace(os.sep, "/")
        self.tree = ast.parse(source)
        _PARSE_COUNT += 1
        self._aliases = _import_aliases(self.tree)
        self._header_lines = _statement_header_lines(self.tree)

    def suppression_lines(self, line: int) -> set:
        """All lines where an inline directive may suppress a finding
        reported at `line`: the line itself plus the first line of any
        multi-line statement whose header span covers it (so a
        ``# hpxlint: disable=`` on a ``with``/``def`` header works for
        findings on the header's continuation lines)."""
        return {line} | self._header_lines.get(line, set())

    def resolve_call(self, func: ast.AST) -> str:
        """Canonical dotted name of a call target, import-aliases
        resolved: ``np.asarray`` -> ``numpy.asarray`` under
        ``import numpy as np``; ``Lock`` -> ``threading.Lock`` under
        ``from threading import Lock``.  Unresolvable shapes
        (subscripts, calls-of-calls) give ''."""
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return ""
        parts.append(node.id)
        parts.reverse()
        head = parts[0]
        if head in self._aliases:
            parts[0:1] = self._aliases[head].split(".")
        return ".".join(parts)

    def in_subpath(self, *fragments: str) -> bool:
        return any(f in self.display_path for f in fragments)


def _statement_header_lines(tree: ast.Module) -> Dict[int, set]:
    """line -> {first line of each multi-line statement whose HEADER
    span covers it}.  For compound statements (with/def/for/...) the
    header span runs up to the first body statement; for simple
    statements it is the whole statement.  Suppressions on the header
    line then reach findings anchored to continuation lines, without
    letting a ``with``-line directive blanket the whole block body."""
    out: Dict[int, set] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        # a decorated def/class anchors findings on the `def` line but
        # readers put the directive next to the decorator — let every
        # decorator line reach the def-line findings (and vice versa)
        for dec in getattr(node, "decorator_list", []) or []:
            dec_end = getattr(dec, "end_lineno", dec.lineno) or dec.lineno
            for ln in range(dec.lineno, dec_end + 1):
                out.setdefault(node.lineno, set()).add(ln)
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        body = getattr(node, "body", None)
        if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
            end = body[0].lineno - 1
        if end <= node.lineno:
            continue
        for ln in range(node.lineno + 1, end + 1):
            out.setdefault(ln, set()).add(node.lineno)
    return out


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name != "*":
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


# ---------------------------------------------------------------------------
# Inline suppressions
# ---------------------------------------------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*hpxlint:\s*(disable|disable-next|disable-file)"
    r"\s*=\s*([A-Za-z0-9_,\-\s]+)")


class Suppressions:
    """Parsed ``# hpxlint:`` directives for one file.

    * ``# hpxlint: disable=HPX003``        — this line (trailing comment);
      on a comment-only line it behaves like ``disable-next``
    * ``# hpxlint: disable-next=HPX003``   — the next *code* line
      (continuation comment lines in between are skipped, so a
      justification may span several comment lines)
    * ``# hpxlint: disable-file=HPX004``   — the whole file
    * ``all`` suppresses every rule; ids and rule names both work.

    A justification belongs in the same comment, after the directive:
    ``# hpxlint: disable=HPX002 — boundary sync, see docstring``.
    """

    def __init__(self, source: str) -> None:
        self.by_line: Dict[int, set] = {}
        self.whole_file: set = set()
        code_lines: set = set()
        _skip = (tokenize.COMMENT, tokenize.NL, tokenize.NEWLINE,
                 tokenize.INDENT, tokenize.DEDENT, tokenize.ENDMARKER,
                 tokenize.ENCODING)
        try:
            comments = []
            for t in tokenize.generate_tokens(io.StringIO(source).readline):
                if t.type == tokenize.COMMENT:
                    comments.append((t.start[0], t.string))
                elif t.type not in _skip:
                    for ln in range(t.start[0], t.end[0] + 1):
                        code_lines.add(ln)
        except (tokenize.TokenError, IndentationError):
            comments = []
        for lineno, text in comments:
            m = _DIRECTIVE.search(text)
            if not m:
                continue
            kind = m.group(1)
            names = {n.strip() for n in
                     m.group(2).split("—")[0].split(",") if n.strip()}
            if kind == "disable-file":
                self.whole_file |= names
                continue
            if kind == "disable" and lineno in code_lines:
                target = lineno            # trailing comment on a code line
            else:
                # disable-next, or a standalone disable comment: apply to
                # the next code line so justifications can span lines
                target = next((ln for ln in sorted(code_lines)
                               if ln > lineno), lineno + 1)
            self.by_line.setdefault(target, set()).update(names)

    def suppresses(self, finding: Finding,
                   lines: Optional[Iterable[int]] = None) -> bool:
        """`lines` widens the match beyond the reported line — callers
        pass ctx.suppression_lines(finding.line) so a directive on a
        multi-line statement's header also suppresses."""
        rule_cls = _REGISTRY.get(finding.rule)
        labels = {finding.rule, "all"}
        if rule_cls is not None:
            labels.add(rule_cls.name)
        if labels & self.whole_file:
            return True
        for ln in (lines if lines is not None else (finding.line,)):
            if labels & self.by_line.get(ln, set()):
                return True
        return False


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int = 0
    checked_files: int = 0
    # inline-suppression tallies per rule id (justified silences)
    suppressed_by_rule: Dict[str, int] = dataclasses.field(
        default_factory=dict)


def lint_sources(sources: Dict[str, str],
                 rules: Optional[Sequence[Rule]] = None) -> LintResult:
    """The core three-tier runner over in-memory sources
    ({display_path: source}).

    Tier 1 runs every file-scope rule per file; tier 2 builds one
    :class:`~.project.ProjectIndex` from the SAME parsed trees (no
    re-parse) and runs the project-scope rules across them; tier 3
    wraps that index in a :class:`~.dataflow.DataflowIndex` (def-use
    chains, still the same trees) for the dataflow-scope rules.
    Inline suppressions apply to every tier, matched in the file a
    finding is reported in.
    """
    rules = list(rules) if rules is not None else all_rules()
    file_rules = [r for r in rules if r.scope == "file"]
    project_rules = [r for r in rules if r.scope == "project"]
    dataflow_rules = [r for r in rules if r.scope == "dataflow"]

    kept: List[Finding] = []
    n_sup = 0
    sup_by_rule: Dict[str, int] = {}
    contexts: Dict[str, FileContext] = {}
    sups: Dict[str, Suppressions] = {}
    n_files = 0

    def suppress(f: Finding) -> bool:
        nonlocal n_sup
        ctx = contexts.get(f.path)
        sup = sups.get(f.path)
        if sup is not None and sup.suppresses(
                f, ctx.suppression_lines(f.line) if ctx else None):
            n_sup += 1
            sup_by_rule[f.rule] = sup_by_rule.get(f.rule, 0) + 1
            return True
        return False

    for display_path, source in sources.items():
        n_files += 1
        display = display_path.replace(os.sep, "/")
        try:
            ctx = FileContext(source, display_path)
        except SyntaxError as e:
            kept.append(Finding(
                rule="HPX000", severity="error", path=display,
                line=e.lineno or 1, col=(e.offset or 0) or 1,
                message=f"syntax error: {e.msg}"))
            continue
        contexts[display] = ctx
        sups[display] = Suppressions(source)
        for rule in file_rules:
            for f in rule.check(ctx):
                if not suppress(f):
                    kept.append(f)

    if (project_rules or dataflow_rules) and contexts:
        from .project import ProjectIndex
        index = ProjectIndex(list(contexts.values()))
        for rule in project_rules:
            for f in rule.check_project(index):
                if not suppress(f):
                    kept.append(f)
        if dataflow_rules:
            from .dataflow import DataflowIndex
            dfx = DataflowIndex(index)
            for rule in dataflow_rules:
                for f in rule.check_dataflow(dfx):
                    if not suppress(f):
                        kept.append(f)

    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=kept, suppressed=n_sup,
                      checked_files=n_files,
                      suppressed_by_rule=sup_by_rule)


def lint_source(source: str, display_path: str,
                rules: Optional[Sequence[Rule]] = None) -> LintResult:
    """Lint one in-memory source blob (the unit the fixture tests use)."""
    return lint_sources({display_path: source}, rules)


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        else:
            raise FileNotFoundError(p)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))  # parent of hpx_tpu/
    sources: Dict[str, str] = {}
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        absolute = os.path.abspath(path)
        if absolute.startswith(root + os.sep):
            # anchor at the repo root so baseline paths match no matter
            # what cwd or path spelling the linter was invoked with
            display = os.path.relpath(absolute, root)
        else:
            rel = os.path.relpath(path)
            # keep display paths rooted at the scan target, never "../.."
            display = path if rel.startswith("..") else rel
        sources[display] = source
    return lint_sources(sources, rules)


# ---------------------------------------------------------------------------
# Baseline: committed record of accepted pre-existing findings
# ---------------------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "hpxlint_baseline.json")


def load_baseline(path: str = DEFAULT_BASELINE
                  ) -> Dict[Tuple[str, str, str], int]:
    """{(path, rule, message): allowed_count}. Missing file = empty."""
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except OSError:
        return {}
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in rec.get("entries", []):
        key = (e["path"], e["rule"], e["message"])
        budget[key] = budget.get(key, 0) + int(e.get("count", 1))
    return budget


def apply_baseline(findings: Sequence[Finding],
                   budget: Dict[Tuple[str, str, str], int],
                   ) -> Tuple[List[Finding], int]:
    """Split findings into (new, n_baselined): each baseline entry
    absorbs up to `count` findings with the same (path, rule, message)."""
    remaining = dict(budget)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        k = f.baseline_key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            matched += 1
        else:
            new.append(f)
    return new, matched


def write_baseline(findings: Sequence[Finding], path: str,
                   justification: str = "accepted pre-existing finding "
                   "(hpxlint --write-baseline)") -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    lines: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        k = f.baseline_key()
        counts[k] = counts.get(k, 0) + 1
        lines.setdefault(k, f.line)
    entries = [{"path": p, "rule": r, "message": m, "count": c,
                "near_line": lines[(p, r, m)],
                "justification": justification}
               for (p, r, m), c in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "hpxlint baseline — pre-existing findings "
                   "accepted with justification; new findings beyond "
                   "these counts fail the gate. near_line is advisory "
                   "only (matching ignores it). Entries are emitted in "
                   "stable (path, rule, message) order so diffs stay "
                   "reviewable.",
                   "entries": entries}, f, indent=1,
                  ensure_ascii=False)
        f.write("\n")


def stale_entries(findings: Sequence[Finding],
                  budget: Dict[Tuple[str, str, str], int],
                  ) -> Dict[Tuple[str, str, str], int]:
    """Baseline budget no current finding consumes: {key: leftover}.
    A non-empty result means the code got cleaner than the baseline
    records — the gate fails until the baseline is rewritten
    (``--update-baseline``), so the baseline only burns down."""
    remaining = dict(budget)
    for f in findings:
        k = f.baseline_key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
    return {k: v for k, v in remaining.items() if v > 0}


def update_baseline_file(findings: Sequence[Finding], path: str,
                         default_justification: str = "accepted "
                         "pre-existing finding (hpxlint --update-baseline)",
                         ) -> Tuple[int, int]:
    """Rewrite the baseline from the CURRENT findings, keeping the
    committed justification string of every entry that survives and
    pruning entries nothing matches anymore.  Returns
    (entries_written, entries_pruned)."""
    old_just: Dict[Tuple[str, str, str], str] = {}
    old_keys: set = set()
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
        for e in rec.get("entries", []):
            k = (e["path"], e["rule"], e["message"])
            old_keys.add(k)
            j = e.get("justification")
            if j:
                old_just.setdefault(k, j)
    except OSError:
        pass
    counts: Dict[Tuple[str, str, str], int] = {}
    lines: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        k = f.baseline_key()
        counts[k] = counts.get(k, 0) + 1
        lines.setdefault(k, f.line)
    entries = [{"path": p, "rule": r, "message": m, "count": c,
                "near_line": lines[(p, r, m)],
                "justification": old_just.get(
                    (p, r, m), default_justification)}
               for (p, r, m), c in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "hpxlint baseline — pre-existing findings "
                   "accepted with justification; new findings beyond "
                   "these counts fail the gate. near_line is advisory "
                   "only (matching ignores it). Entries are emitted in "
                   "stable (path, rule, message) order so diffs stay "
                   "reviewable.",
                   "entries": entries}, f, indent=1,
                  ensure_ascii=False)
        f.write("\n")
    pruned = len(old_keys - set(counts))
    return len(entries), pruned
