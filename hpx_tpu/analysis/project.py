"""hpxlint whole-program tier: symbol index, call graph, cross-module rules.

The per-file tier (rules.py) reasons about one ``FileContext`` at a
time; this tier builds one :class:`ProjectIndex` over the SAME parsed
trees (the engine hands the contexts over — no file is parsed twice)
and resolves what a single file cannot see:

* module-level name resolution (import aliases, including relative
  imports, mapped back onto the modules in the linted set),
* lock identity across instances (``self._lock`` in class ``C`` of
  module ``m`` is the one lock ``m.C._lock`` for ordering purposes),
* intra-package call edges (``self.m()``, ``self.attr.m()`` via
  attribute-type inference, ``mod.f()`` via aliases).

Three rules run on the index:

* HPX013 — lock-order inversion across the call graph,
* HPX014 — every ``cfg.get*("hpx....")`` read checked against the
  ``core/config_schema.py`` registry (undeclared reads, dead keys,
  getter/type mismatches),
* HPX015 — incref/pin vs decref/unpin balance on every exit path
  (the static twin of ``BlockAllocator.leaked_blocks()``).

Pure stdlib, like the rest of the linter.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, Finding, ProjectRule, register

_LOCK_TYPES = {"Mutex", "Spinlock", "SharedMutex"}
# raw threading primitives register as lock identities for the
# dataflow tier's guarded-by inference (HPX019) but are EXCLUDED from
# HPX013 ordering — the runtime's own Mutex family is the ordering
# contract, raw locks guard leaf state
_RAW_LOCK_TYPES = {"Lock", "RLock"}

# container methods that mutate their receiver in place — a
# ``self.attr.append(...)`` is a write to the shared structure for
# guarded-by purposes even though the binding never changes
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "update", "setdefault",
    "add", "rotate", "sort", "reverse"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _module_name(display_path: str) -> str:
    p = display_path
    if p.startswith("./"):
        p = p[2:]
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _relative_aliases(tree: ast.Module, module: str,
                      is_package: bool) -> Dict[str, str]:
    """Import-alias map with relative imports resolved against
    `module` (FileContext's own alias map only handles absolute
    imports — cross-module resolution needs ``from . import x`` too)."""
    aliases: Dict[str, str] = {}
    parts = module.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                # package containing this module, then up level-1 more
                keep = len(parts) - (0 if is_package else 1) \
                    - (node.level - 1)
                if keep < 0:
                    continue
                pkg = ".".join(parts[:keep])
                base = f"{pkg}.{node.module}" if node.module else pkg
            if not base:
                continue
            for a in node.names:
                if a.name != "*":
                    aliases[a.asname or a.name] = f"{base}.{a.name}"
    return aliases


class FunctionInfo:
    """One function/method: lock acquisitions and outgoing calls, each
    annotated with the locks held at that point (class-level lock
    identity, lexical `with` nesting)."""

    __slots__ = ("qname", "module", "cls", "node", "path",
                 "acquires", "calls", "reads", "attr_ops")

    def __init__(self, qname: str, module: str, cls: Optional[str],
                 node: ast.AST, path: str) -> None:
        self.qname = qname
        self.module = module
        self.cls = cls
        self.node = node
        self.path = path
        # (lock_id, node, held_tuple_at_acquire)
        self.acquires: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        # (descriptor, node, held_tuple) — resolved to qnames later
        self.calls: List[Tuple[tuple, ast.AST, Tuple[str, ...]]] = []
        # (getter, key, node) config reads
        self.reads: List[Tuple[str, str, ast.AST]] = []
        # ("write"|"read", attr, node, held_tuple) — every self.attr
        # access with the locks held at that point (HPX019's input);
        # subscript stores, aug-assigns and mutating container-method
        # calls on the attribute all count as writes
        self.attr_ops: List[Tuple[str, str, ast.AST,
                                  Tuple[str, ...]]] = []


_GETTERS = {"get": None, "get_int": "int",
            "get_bool": "bool", "get_float": "float"}


class ProjectIndex:
    """Symbol index + call graph over every successfully-parsed file
    in one lint invocation. Built once, shared by all project rules."""

    def __init__(self, contexts: Sequence[FileContext]) -> None:
        self.contexts = {c.display_path: c for c in contexts}
        self.module_of_path: Dict[str, str] = {}
        self.path_of_module: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[Tuple[str, str], ast.ClassDef] = {}
        self.locks: Set[str] = set()
        self.raw_locks: Set[str] = set()  # threading.Lock/RLock subset
        # (module, cls) -> {attr -> (type_module, type_class)}
        self.attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        self.aliases: Dict[str, Dict[str, str]] = {}
        # config reads across the whole set: (getter, key, node, path)
        self.config_reads: List[Tuple[str, str, ast.AST, str]] = []

        for ctx in contexts:
            mod = _module_name(ctx.display_path)
            self.module_of_path[ctx.display_path] = mod
            self.path_of_module[mod] = ctx.display_path
            is_pkg = ctx.display_path.endswith("__init__.py")
            self.aliases[mod] = _relative_aliases(ctx.tree, mod, is_pkg)
            self._collect_symbols(ctx, mod)
        for ctx in contexts:
            self._collect_functions(ctx, self.module_of_path[ctx.display_path])

    # -- pass 1: classes, lock identities, attribute types ------------------

    def _collect_symbols(self, ctx: FileContext, mod: str) -> None:
        def record(lid: str, raw: bool) -> None:
            self.locks.add(lid)
            if raw:
                self.raw_locks.add(lid)

        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self.classes[(mod, stmt.name)] = stmt
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                for name, raw in self._lock_targets(stmt,
                                                    want_self=False):
                    record(f"{mod}.{name}", raw)
        for (m, cname), cdef in list(self.classes.items()):
            if m != mod:
                continue
            for stmt in cdef.body:
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    for name, raw in self._lock_targets(
                            stmt, want_self=False):
                        record(f"{mod}.{cname}.{name}", raw)
            for meth in cdef.body:
                if not isinstance(meth, _FUNC_NODES):
                    continue
                for node in ast.walk(meth):
                    if isinstance(node, (ast.Assign, ast.AnnAssign)):
                        for name, raw in self._lock_targets(
                                node, want_self=True):
                            record(f"{mod}.{cname}.{name}", raw)

    def _lock_targets(self, stmt: ast.AST,
                      want_self: bool) -> Iterable[Tuple[str, bool]]:
        value = getattr(stmt, "value", None)
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, (ast.Name, ast.Attribute))):
            return
        callee = (value.func.id if isinstance(value.func, ast.Name)
                  else value.func.attr)
        if callee not in _LOCK_TYPES and callee not in _RAW_LOCK_TYPES:
            return
        raw = callee in _RAW_LOCK_TYPES
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for t in targets:
            if want_self:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    yield t.attr, raw
            elif isinstance(t, ast.Name):
                yield t.id, raw

    # -- pass 2: per-function acquire/call/read collection ------------------

    def _collect_functions(self, ctx: FileContext, mod: str) -> None:
        self._infer_attr_types(ctx, mod)
        for stmt in ctx.tree.body:
            if isinstance(stmt, _FUNC_NODES):
                self._scan_function(ctx, mod, None, stmt)
            elif isinstance(stmt, ast.ClassDef):
                for meth in stmt.body:
                    if isinstance(meth, _FUNC_NODES):
                        self._scan_function(ctx, mod, stmt.name, meth)

    def _infer_attr_types(self, ctx: FileContext, mod: str) -> None:
        """self.X = Cls(...) / self.X = annotated_param / self.X: Cls
        where Cls is a class in the linted set."""
        amap = self.aliases[mod]

        def resolve_cls(name_expr: ast.AST) -> Optional[Tuple[str, str]]:
            if isinstance(name_expr, ast.Name):
                dotted = amap.get(name_expr.id, f"{mod}.{name_expr.id}")
            elif isinstance(name_expr, ast.Attribute) \
                    and isinstance(name_expr.value, ast.Name):
                head = amap.get(name_expr.value.id, name_expr.value.id)
                dotted = f"{head}.{name_expr.attr}"
            else:
                return None
            m, _, c = dotted.rpartition(".")
            return (m, c) if (m, c) in self.classes else None

        for stmt in ctx.tree.body:
            if not isinstance(stmt, ast.ClassDef):
                continue
            types = self.attr_types.setdefault((mod, stmt.name), {})
            for meth in stmt.body:
                if not isinstance(meth, _FUNC_NODES):
                    continue
                ann_of_param: Dict[str, Tuple[str, str]] = {}
                for arg in (meth.args.posonlyargs + meth.args.args
                            + meth.args.kwonlyargs):
                    if arg.annotation is not None:
                        hit = resolve_cls(arg.annotation)
                        if hit:
                            ann_of_param[arg.arg] = hit
                for node in ast.walk(meth):
                    target = None
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1:
                        target = node.targets[0]
                    elif isinstance(node, ast.AnnAssign):
                        target = node.target
                    if not (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        continue
                    hit = None
                    value = getattr(node, "value", None)
                    if isinstance(node, ast.AnnAssign) \
                            and node.annotation is not None:
                        hit = resolve_cls(node.annotation)
                    if hit is None and isinstance(value, ast.Call):
                        hit = resolve_cls(value.func)
                    if hit is None and isinstance(value, ast.Name):
                        hit = ann_of_param.get(value.id)
                    if hit:
                        types.setdefault(target.attr, hit)

    def _lock_id(self, expr: ast.AST, mod: str,
                 cls: Optional[str]) -> str:
        """'' or the project-wide identity of a `with` lock expr."""
        if isinstance(expr, ast.Call) and isinstance(expr.func,
                                                     ast.Attribute) \
                and expr.func.attr == "shared":
            return self._lock_id(expr.func.value, mod, cls)
        if isinstance(expr, ast.Name):
            lid = f"{mod}.{expr.id}"
            return lid if lid in self.locks else ""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                lid = f"{mod}.{cls}.{expr.attr}"
                return lid if lid in self.locks else ""
            if isinstance(base, ast.Name):
                head = self.aliases[mod].get(base.id)
                if head:
                    lid = f"{head}.{expr.attr}"
                    return lid if lid in self.locks else ""
        return ""

    def _scan_function(self, ctx: FileContext, mod: str,
                       cls: Optional[str], fn: ast.AST) -> None:
        qname = f"{mod}:{cls}.{fn.name}" if cls else f"{mod}:{fn.name}"
        info = FunctionInfo(qname, mod, cls, fn, ctx.display_path)
        self.functions[qname] = info

        def visit(stmts: Sequence[ast.stmt],
                  held: Tuple[str, ...]) -> None:
            for stmt in stmts:
                if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
                    continue  # nested scope: not this function's body
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    self._scan_exprs(
                        info, [i.context_expr for i in stmt.items],
                        mod, held)
                    new_held = held
                    for item in stmt.items:
                        lid = self._lock_id(item.context_expr, mod, cls)
                        if lid:
                            info.acquires.append(
                                (lid, item.context_expr, new_held))
                            new_held = new_held + (lid,)
                    visit(stmt.body, new_held)
                    continue
                # header expressions first (test/iter/targets), then
                # nested statement lists under the SAME held set
                header: List[ast.AST] = []
                for field in ("test", "iter", "target", "value",
                              "targets", "exc", "cause", "msg",
                              "subject"):
                    v = getattr(stmt, field, None)
                    if isinstance(v, ast.AST):
                        header.append(v)
                    elif isinstance(v, list):
                        header.extend(x for x in v
                                      if isinstance(x, ast.AST))
                self._scan_exprs(info, header, mod, held)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and sub \
                            and isinstance(sub[0], ast.stmt):
                        visit(sub, held)
                for h in getattr(stmt, "handlers", []):
                    visit(h.body, held)
                for c in getattr(stmt, "cases", []):
                    visit(c.body, held)

        visit(fn.body, ())
        for g, key, node in info.reads:
            self.config_reads.append((g, key, node, ctx.display_path))

    @staticmethod
    def _self_attr(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    def _scan_exprs(self, info: FunctionInfo, exprs: Sequence[ast.AST],
                    mod: str, held: Tuple[str, ...]) -> None:
        """Collect calls + config reads + self-attribute accesses from
        expression trees (never descends into nested statement bodies
        — exprs carry none)."""
        for expr in exprs:
            # ast.walk is parent-before-child, so a mutation parent
            # (subscript store, mutating method call, attribute-store
            # base) claims its base attribute before the base itself
            # is visited as a plain load
            consumed: set = set()
            for node in ast.walk(expr):
                if isinstance(node, ast.Lambda):
                    continue
                if isinstance(node, ast.Subscript) \
                        and isinstance(node.ctx, (ast.Store, ast.Del)) \
                        and self._self_attr(node.value):
                    info.attr_ops.append(
                        ("write", node.value.attr, node, held))
                    consumed.add(id(node.value))
                    continue
                if isinstance(node, ast.Attribute):
                    if isinstance(node.ctx, (ast.Store, ast.Del)) \
                            and self._self_attr(node.value):
                        # self.obj.field = v mutates self.obj's referent
                        info.attr_ops.append(
                            ("write", node.value.attr, node, held))
                        consumed.add(id(node.value))
                    if self._self_attr(node) \
                            and id(node) not in consumed:
                        kind = "write" if isinstance(
                            node.ctx, (ast.Store, ast.Del)) else "read"
                        info.attr_ops.append(
                            (kind, node.attr, node, held))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in _MUTATING_METHODS \
                        and self._self_attr(func.value):
                    info.attr_ops.append(
                        ("write", func.value.attr, func.value, held))
                    consumed.add(id(func.value))
                if isinstance(func, ast.Attribute):
                    if func.attr in _GETTERS and node.args \
                            and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str) \
                            and node.args[0].value.startswith("hpx."):
                        info.reads.append(
                            (func.attr, node.args[0].value, node))
                    base = func.value
                    if isinstance(base, ast.Name):
                        if base.id == "self":
                            info.calls.append(
                                (("self", func.attr), node, held))
                        else:
                            info.calls.append(
                                (("dotted", base.id, func.attr),
                                 node, held))
                    elif (isinstance(base, ast.Attribute)
                          and isinstance(base.value, ast.Name)
                          and base.value.id == "self"):
                        info.calls.append(
                            (("selfattr", base.attr, func.attr),
                             node, held))
                elif isinstance(func, ast.Name):
                    info.calls.append((("name", func.id), node, held))

    # -- call resolution ----------------------------------------------------

    def resolve_call(self, info: FunctionInfo,
                     desc: tuple) -> List[str]:
        """Candidate qnames in the linted set for one call descriptor."""
        mod, cls = info.module, info.cls
        kind = desc[0]
        out: List[str] = []
        if kind == "name":
            name = desc[1]
            if f"{mod}:{name}" in self.functions:
                out.append(f"{mod}:{name}")
            else:
                dotted = self.aliases[mod].get(name)
                if dotted:
                    m, _, f = dotted.rpartition(".")
                    if f"{m}:{f}" in self.functions:
                        out.append(f"{m}:{f}")
        elif kind == "self" and cls:
            if f"{mod}:{cls}.{desc[1]}" in self.functions:
                out.append(f"{mod}:{cls}.{desc[1]}")
        elif kind == "selfattr" and cls:
            hit = self.attr_types.get((mod, cls), {}).get(desc[1])
            if hit and f"{hit[0]}:{hit[1]}.{desc[2]}" in self.functions:
                out.append(f"{hit[0]}:{hit[1]}.{desc[2]}")
        elif kind == "dotted":
            head = self.aliases[mod].get(desc[1])
            if head and f"{head}:{desc[2]}" in self.functions:
                out.append(f"{head}:{desc[2]}")
        return out


# ---------------------------------------------------------------------------
# HPX013 — lock-order inversion
# ---------------------------------------------------------------------------

@register
class LockOrderInversion(ProjectRule):
    """HPX013: two Mutex/Spinlock locks are acquired in both orders on
    different call paths — a textbook ABBA deadlock across threads.
    Fix: pick one global order (document it next to the lock fields)
    and restructure the later-acquired side to drop its lock first, or
    move the cross-calling work outside the critical section."""

    id = "HPX013"
    name = "lock-order-inversion"
    severity = "error"

    def check_project(self, index: ProjectIndex) -> Iterable[Finding]:
        # transitive locks-acquired per function, with witness chains.
        # raw threading.Lock/RLock identities exist for HPX019's
        # guarded-by inference only — the ordering contract is between
        # the runtime's registered Mutex family, so drop raw locks here
        raw = index.raw_locks

        def no_raw(held: Tuple[str, ...]) -> Tuple[str, ...]:
            return tuple(h for h in held if h not in raw)

        via: Dict[str, Dict[str, Tuple[str, ...]]] = {
            q: {} for q in index.functions}
        resolved: Dict[str, List[Tuple[List[str], ast.AST,
                                       Tuple[str, ...]]]] = {}
        for q in sorted(index.functions):
            info = index.functions[q]
            for lid, _node, _held in info.acquires:
                if lid not in raw:
                    via[q].setdefault(lid, (q,))
            resolved[q] = [(index.resolve_call(info, d), n, no_raw(h))
                           for d, n, h in info.calls]
        changed = True
        while changed:
            changed = False
            for q in sorted(index.functions):
                for callees, _node, _held in resolved[q]:
                    for callee in callees:
                        for lid, chain in via[callee].items():
                            if lid not in via[q]:
                                via[q][lid] = (q,) + chain
                                changed = True

        # edges held -> acquired, first witness wins (deterministic)
        edges: Dict[Tuple[str, str],
                    Tuple[Tuple[str, ...], ast.AST, str]] = {}
        for q in sorted(index.functions):
            info = index.functions[q]
            for lid, node, held in info.acquires:
                if lid in raw:
                    continue
                for b in no_raw(held):
                    if b != lid and (b, lid) not in edges:
                        edges[(b, lid)] = ((q,), node, info.path)
            for callees, node, held in resolved[q]:
                for callee in callees:
                    for lid, chain in via[callee].items():
                        for b in held:
                            if b != lid and (b, lid) not in edges:
                                edges[(b, lid)] = (
                                    (q,) + chain, node, info.path)

        # reachability with path reconstruction over the edge set
        succ: Dict[str, List[str]] = {}
        for (a, b) in edges:
            succ.setdefault(a, []).append(b)
        for a in succ:
            succ[a].sort()

        def witness(src: str, dst: str) -> Optional[Tuple[str, ...]]:
            seen = {src}
            queue: List[Tuple[str, Tuple[str, ...]]] = [(src, ())]
            while queue:
                cur, chain = queue.pop(0)
                for nxt in succ.get(cur, ()):
                    step = edges[(cur, nxt)][0]
                    merged = chain + tuple(
                        f for f in step if not (chain and f == chain[-1]))
                    if nxt == dst:
                        return merged
                    if nxt not in seen:
                        seen.add(nxt)
                        queue.append((nxt, merged))
            return None

        reported: Set[Tuple[str, str]] = set()
        for (a, b) in sorted(edges):
            pair = (min(a, b), max(a, b))
            if pair in reported:
                continue
            back = witness(b, a)
            if back is None:
                continue
            fwd = witness(a, b)
            if fwd is None:
                continue
            reported.add(pair)
            x, y = pair
            wx = fwd if (a, b) == (x, y) else back
            wy = back if (a, b) == (x, y) else fwd
            _chain0, node, path = edges[(a, b)]
            yield self.finding_at(
                path, node,
                f"lock-order inversion between {x} and {y}: "
                f"{x} -> {y} via {' -> '.join(wx)}; "
                f"{y} -> {x} via {' -> '.join(wy)}")


# ---------------------------------------------------------------------------
# HPX014 — config-key schema
# ---------------------------------------------------------------------------

def _schema_from_index(index: ProjectIndex
                       ) -> Optional[Tuple[Dict[str, dict], str]]:
    """Parse declare() calls out of a config_schema module in the
    linted set: {key: {type, reserved, node}} plus its display path."""
    for path, ctx in index.contexts.items():
        if not (path.endswith("core/config_schema.py")
                or _module_name(path).split(".")[-1] == "config_schema"):
            continue
        entries: Dict[str, dict] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "declare"):
                continue
            args = node.args
            if not (args and isinstance(args[0], ast.Constant)
                    and isinstance(args[0].value, str)):
                continue
            ktype = ""
            if len(args) > 1 and isinstance(args[1], ast.Constant):
                ktype = str(args[1].value)
            reserved = False
            if len(args) > 4 and isinstance(args[4], ast.Constant):
                reserved = bool(args[4].value)
            for kw in node.keywords:
                if kw.arg == "reserved" \
                        and isinstance(kw.value, ast.Constant):
                    reserved = bool(kw.value.value)
                elif kw.arg == "type" \
                        and isinstance(kw.value, ast.Constant):
                    ktype = str(kw.value.value)
            entries[args[0].value] = {
                "type": ktype, "reserved": reserved, "node": node}
        return entries, path
    return None


def _schema_fallback() -> Dict[str, dict]:
    """Outside a whole-tree lint (single-file fixtures), fall back to
    the real installed registry — pure stdlib, never imports jax."""
    try:
        from ..core import config_schema
    except Exception:  # pragma: no cover — analysis must stay usable
        return {}
    return {k: {"type": e.type, "reserved": e.reserved, "node": None}
            for k, e in config_schema.all_keys().items()}


@register
class ConfigKeySchema(ProjectRule):
    """HPX014: stringly-typed config drift — a ``cfg.get*("hpx....")``
    read of a key missing from core/config_schema.py (typo'd knobs
    silently answer their default), a declared key nothing reads, or a
    getter whose type contradicts the declaration. Fix: declare the
    key (type, default, doc) in config_schema.py before reading it;
    delete or mark ``reserved=True`` keys kept only for HPX parity;
    align the getter with the declared type."""

    id = "HPX014"
    name = "config-key-schema"
    severity = "error"

    def check_project(self, index: ProjectIndex) -> Iterable[Finding]:
        local = _schema_from_index(index)
        if local is not None:
            schema, schema_path = local
        else:
            schema, schema_path = _schema_fallback(), None
        if not schema:
            return
        read_keys: Set[str] = set()
        for getter, key, node, path in index.config_reads:
            read_keys.add(key)
            entry = schema.get(key)
            if entry is None:
                yield self.finding_at(
                    path, node,
                    f"config key '{key}' read via {getter}() is not "
                    "declared in core/config_schema.py")
                continue
            want = _GETTERS[getter]
            if want is not None and entry["type"] != want:
                yield self.finding_at(
                    path, node,
                    f"config key '{key}' is declared '{entry['type']}' "
                    f"but read via {getter}()")
        if schema_path is not None:
            # dead-key check only makes sense when the whole tree (and
            # the registry itself) is in the linted set
            for key in sorted(schema):
                entry = schema[key]
                if entry["reserved"] or key in read_keys:
                    continue
                yield self.finding_at(
                    schema_path, entry["node"],
                    f"config key '{key}' is declared but never read "
                    "(wire a reader or mark it reserved=True)")


# ---------------------------------------------------------------------------
# HPX015 — refcount balance
# ---------------------------------------------------------------------------

_ACQ_OPS = {"incref": "decref", "pin": "unpin",
            "checkout": "checkin"}
# putback is the abort-path release of a checkout (cache/tier.py): the
# entry returns to the tier instead of being consumed, but either way
# the caller no longer owns it
_REL_OPS = {"decref": "incref", "unpin": "pin",
            "checkin": "checkout", "putback": "checkout"}
_HPX015_SUBPATHS = ("hpx_tpu/cache/", "hpx_tpu/models/")
_MAX_STATES = 64


def _refcount_key(call: ast.Call, loop_iters: Dict[str, str]) -> str:
    """Stable identity of the refcounted operand. Inside a loop whose
    target is the operand, the ITERABLE names the population
    (``for bid in pins: incref(bid)`` pairs with a later loop over the
    same list, not with every other ``bid``)."""
    if not call.args:
        return "<none>"
    arg = call.args[0]
    if isinstance(arg, ast.Name) and arg.id in loop_iters:
        return loop_iters[arg.id]
    try:
        return ast.unparse(arg)
    except Exception:  # pragma: no cover
        return "<expr>"


class _FlowState:
    """Immutable per-path refcount deltas: {(op_family, key): delta}."""

    __slots__ = ("deltas",)

    def __init__(self, deltas: Tuple[Tuple[Tuple[str, str], int], ...]
                 = ()) -> None:
        self.deltas = deltas

    def bump(self, family: str, key: str, amount: int) -> "_FlowState":
        d = dict(self.deltas)
        k = (family, key)
        d[k] = d.get(k, 0) + amount
        if d[k] == 0:
            del d[k]
        return _FlowState(tuple(sorted(d.items())))

    def positives(self) -> List[Tuple[str, str, int]]:
        return [(fam, key, n) for (fam, key), n in self.deltas if n > 0]

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _FlowState) \
            and self.deltas == other.deltas

    def __hash__(self) -> int:
        return hash(self.deltas)


class _RefcountWalker:
    """Path-sensitive walk of one function body. Loops run 0-or-1
    times (a pinning loop pairs with its releasing loop, not with
    itself N times); If branches fork; Try handlers start from every
    intermediate body state; Return/Raise snapshot exit states."""

    def __init__(self) -> None:
        self.exits: Set[_FlowState] = set()
        self.acquire_nodes: Dict[Tuple[str, str], ast.AST] = {}
        self.release_families: Set[Tuple[str, str]] = set()
        self.bailed = False

    def _ops_in(self, expr: ast.AST,
                loop_iters: Dict[str, str]
                ) -> List[Tuple[str, str, ast.Call]]:
        out = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in _ACQ_OPS or attr in _REL_OPS:
                    out.append((attr, _refcount_key(node, loop_iters),
                                node))
        return out

    def _apply_exprs(self, states: Set[_FlowState],
                     exprs: Sequence[ast.AST],
                     loop_iters: Dict[str, str]) -> Set[_FlowState]:
        for expr in exprs:
            for attr, key, node in self._ops_in(expr, loop_iters):
                if attr in _ACQ_OPS:
                    fam = attr
                    self.acquire_nodes.setdefault((fam, key), node)
                    states = {s.bump(fam, key, +1) for s in states}
                else:
                    fam = _REL_OPS[attr]
                    self.release_families.add((fam, key))
                    states = {s.bump(fam, key, -1) for s in states}
        return states

    def walk(self, stmts: Sequence[ast.stmt],
             states: Set[_FlowState],
             loop_iters: Dict[str, str]) -> Set[_FlowState]:
        for stmt in stmts:
            if self.bailed:
                return states
            if len(states) > _MAX_STATES:
                self.bailed = True
                return states
            states = self._step(stmt, states, loop_iters)
            if not states:
                return states  # all paths exited
        return states

    def _step(self, stmt: ast.stmt, states: Set[_FlowState],
              loop_iters: Dict[str, str]) -> Set[_FlowState]:
        if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
            return states
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                states = self._apply_exprs(states, [stmt.value],
                                           loop_iters)
            self.exits |= states
            return set()
        if isinstance(stmt, ast.Raise):
            exprs = [e for e in (stmt.exc, stmt.cause) if e is not None]
            states = self._apply_exprs(states, exprs, loop_iters)
            self.exits |= states
            return set()
        if isinstance(stmt, ast.If):
            states = self._apply_exprs(states, [stmt.test], loop_iters)
            taken = self.walk(stmt.body, set(states), loop_iters)
            other = self.walk(stmt.orelse, set(states), loop_iters) \
                if stmt.orelse else set(states)
            return taken | other
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            states = self._apply_exprs(states, [stmt.iter], loop_iters)
            inner = dict(loop_iters)
            if isinstance(stmt.target, ast.Name):
                try:
                    inner[stmt.target.id] = ast.unparse(stmt.iter)
                except Exception:  # pragma: no cover
                    pass
            once = self.walk(stmt.body, set(states), inner)
            after = states | once  # 0 or 1 iterations
            if stmt.orelse:
                after = self.walk(stmt.orelse, after, loop_iters)
            return after
        if isinstance(stmt, ast.While):
            states = self._apply_exprs(states, [stmt.test], loop_iters)
            once = self.walk(stmt.body, set(states), loop_iters)
            after = states | once
            if stmt.orelse:
                after = self.walk(stmt.orelse, after, loop_iters)
            return after
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            states = self._apply_exprs(
                states, [i.context_expr for i in stmt.items], loop_iters)
            return self.walk(stmt.body, states, loop_iters)
        if isinstance(stmt, ast.Try):
            pre_exits = set(self.exits)
            entry = set(states)
            mid: Set[_FlowState] = set(entry)
            cur = entry
            for s in stmt.body:
                cur = self._step(s, cur, loop_iters)
                mid |= cur
                if self.bailed or not cur:
                    break
            after = self.walk(stmt.orelse, cur, loop_iters) \
                if (cur and stmt.orelse) else cur
            for handler in stmt.handlers:
                after |= self.walk(handler.body, set(mid), loop_iters)
            if stmt.finalbody:
                # a return/raise inside the try runs the finally BEFORE
                # leaving the function, so exits recorded during the
                # body/handler walks are rerouted through the finally's
                # deltas instead of escaping with their pre-finally
                # state (`incref; try: return x; finally: decref` is
                # balanced)
                escaped = self.exits - pre_exits
                self.exits = pre_exits
                after = self.walk(stmt.finalbody, after, loop_iters)
                if escaped:
                    self.exits |= self.walk(stmt.finalbody, escaped,
                                            loop_iters)
            return after
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return states
        # simple statement: scan every expression it carries
        exprs = [n for n in ast.iter_child_nodes(stmt)
                 if isinstance(n, ast.expr)]
        more = []
        for n in ast.iter_child_nodes(stmt):
            if isinstance(n, list):  # pragma: no cover — ast never does
                more.extend(n)
        return self._apply_exprs(states, exprs + more, loop_iters)


@register
class RefcountBalance(ProjectRule):
    """HPX015: a block reference taken via incref()/pin() — or a host
    tier entry taken via checkout() — escapes on some exit path
    without the matching decref()/unpin()/checkin() (putback counts as
    the abort-path release of a checkout) — the static twin of
    BlockAllocator.leaked_blocks() and HostTier.leaked_buffers().
    Functions that only acquire (ownership transfer to a tree/table,
    released elsewhere) are exempt; the rule fires when the SAME
    function does release the population on other paths but misses
    one. Fix: release in a finally/except mirror of the acquire, or
    hand the reference to an owner that retires it."""

    id = "HPX015"
    name = "refcount-balance"
    severity = "error"

    def check_project(self, index: ProjectIndex) -> Iterable[Finding]:
        for qname in sorted(index.functions):
            info = index.functions[qname]
            if not any(s in info.path for s in _HPX015_SUBPATHS):
                continue
            fn = info.node
            if not isinstance(fn, _FUNC_NODES):
                continue
            walker = _RefcountWalker()
            final = walker.walk(fn.body, {_FlowState()}, {})
            if walker.bailed:
                continue
            walker.exits |= final
            flagged: Set[Tuple[str, str]] = set()
            for state in walker.exits:
                for fam, key, _n in state.positives():
                    if (fam, key) not in walker.release_families:
                        continue  # pure ownership transfer
                    if (fam, key) in flagged:
                        continue
                    flagged.add((fam, key))
                    yield self.finding_at(
                        info.path, walker.acquire_nodes[(fam, key)],
                        f"{fam}({key}) in {qname.split(':', 1)[1]} is "
                        f"not matched by {_ACQ_OPS[fam]}() on every "
                        "exit path")


# ---------------------------------------------------------------------------
# HPX023 — quantile scans on the serving hot path
# ---------------------------------------------------------------------------

# hot-path roots, by method/function NAME: the decode/prefill loops
# and the flush boundary. Anything reachable from one of these runs
# once per step (or per flush tick) — O(buckets) histogram scans do
# not belong there.
_HPX023_ROOTS = {
    "step", "_step_inner", "submit", "generate", "_flush",
    "_tune_signals", "_pump_decodes", "_advance_prefills",
    "_dispatch_prefills"}

# the HistogramCounter methods that walk every bucket (quantile) or
# merge whole snapshot dicts (merged_hist)
_HPX023_SCANS = {"quantile", "merged_hist"}


@register
class QuantileInHotPath(ProjectRule):
    """HPX023: a HistogramCounter.quantile()/merged_hist() call is
    reachable from the serving hot path (step/submit/_flush and the
    router pump family). quantile() walks every bucket under the
    counter's GIL window and merged_hist() merges whole snapshot
    dicts — a per-step O(buckets) scan the decode loop would pay on
    every token. Fix: take a snapshot()/delta() at the flush boundary
    and run the scan on the detached
    HistogramCounter.from_snapshot() copy, or move it behind a
    metrics/debug endpoint. Suppress a deliberate site with
    ``# hpxlint: disable=HPX023 — <why>``."""

    id = "HPX023"
    name = "quantile-in-hot-path"
    severity = "warning"

    def check_project(self, index: ProjectIndex) -> Iterable[Finding]:
        # resolve every call once, then fixpoint the reachable set out
        # of the named hot-path roots — the HPX013 propagation
        # machinery without the lock context.
        def leaf(q: str) -> str:
            return q.split(":", 1)[1].rsplit(".", 1)[-1]

        resolved: Dict[str, List[str]] = {}
        for q in sorted(index.functions):
            info = index.functions[q]
            resolved[q] = [c for d, _n, _h in info.calls
                           for c in index.resolve_call(info, d)]
        reach = {q for q in sorted(index.functions)
                 if leaf(q) in _HPX023_ROOTS}
        frontier = sorted(reach)
        while frontier:
            q = frontier.pop()
            for callee in resolved[q]:
                if callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)

        for q in sorted(reach):
            info = index.functions[q]
            for desc, node, _held in info.calls:
                meth = desc[-1]
                if meth in _HPX023_SCANS:
                    yield self.finding_at(
                        info.path, node,
                        f"{meth}() is reachable from the serving hot "
                        f"path in {q.split(':', 1)[1]} — snapshot at "
                        "the flush boundary and scan the detached "
                        "HistogramCounter.from_snapshot() copy "
                        "instead")
