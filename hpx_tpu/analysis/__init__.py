"""hpxlint — AST-based static analysis for the hpx_tpu runtime.

The dynamic VERIFY_LOCKS analog (`hpx_tpu.synchronization`) only fires
on the paths a test happens to execute; this package is its static
complement.  A small stdlib-`ast` framework (rule registry, per-rule
severity, file/line findings, inline ``# hpxlint: disable=RULE``
suppressions, committed baseline) runs three tiers of rules:

Per-file tier (rules.py) — each rule sees one parsed file:

* HPX001 lock-held-wait      — future/latch/CV waits lexically inside a
  ``with Mutex():`` region (the classic AMT deadlock, SURVEY.md §5.2).
* HPX002 host-sync-hot-path  — ``np.asarray`` / ``.item()`` /
  ``block_until_ready`` / ``jax.device_get`` in executor/continuation
  code under ``hpx_tpu/{futures,exec,algo,ops}`` (the "task granularity
  chasm": a hidden device sync stalls the whole dispatch pipeline).
* HPX003 dropped-future      — ``async_()/async_many()/dataflow()`` or
  ``.then()`` results discarded as expression statements (the captured
  exception is silently lost; ``post()`` is the fire-and-forget API and
  is deliberately not flagged — it returns ``None`` by design).
* HPX004 raw-sync-primitive  — raw ``threading.Lock``/``time.sleep``/
  ``queue.Queue`` in runtime layers above ``hpx_tpu.synchronization``
  (which futures/, runtime/ and core/ sit *below* — they stay on the raw
  substrate and are exempt).
* HPX005 jit-in-loop         — ``jax.jit`` constructed inside a loop
  body (a fresh jitted callable per iteration defeats the trace cache).
* HPX006 bare-except         — ``except:`` swallows future exceptions
  (and KeyboardInterrupt/SystemExit) on the completion path.
* HPX007–HPX012              — see the README lint table.
* HPX016 counter-name-discipline — counter names that fail the
  ``/object{locality#N/instance}/counter`` registry grammar, and bare
  ``h.record()`` statements that drop the histogram timing context
  manager unrecorded.
* HPX018 tunable-knob-mutation — direct writes to the knob attributes
  backing ``tunable=`` config keys outside ``__init__`` /
  ``_reload_knobs`` (they race the adaptive tuner; see svc/autotune).

Whole-program tier (project.py) — every file is parsed once into a
shared :class:`~.project.ProjectIndex` (symbol table, class-level lock
identities, intra-package call graph) and cross-module rules run over
it:

* HPX013 lock-order-inversion — Mutex/Spinlock pairs acquired in both
  orders on different call paths, with both witness chains.
* HPX014 config-key-schema   — every ``cfg.get*("hpx....")`` read must
  be declared in ``core/config_schema.py``; flags undeclared reads,
  dead keys, and getter/type mismatches.
* HPX015 refcount-balance    — incref/pin without a matching
  decref/unpin on every exit path (static twin of
  ``BlockAllocator.leaked_blocks()``), in ``cache/`` and ``models/``.

Dataflow tier (dataflow.py) — per-function reaching-definitions /
def-use chains over the same parsed trees, plus one-level
interprocedural summaries from the call graph:

* HPX019 unguarded-shared-state  — a ``self.attr`` mutated bare while
  a strict majority of its mutation sites hold the same lock (the
  inferred guarded-by contract), in svc/, models/, cache/, dist/.
* HPX020 donation-use-after-donate — a binding passed at a
  ``donate_argnums`` position of a jitted call and used again after.
* HPX021 mesh-axis-consistency  — collective axis names and
  PartitionSpec fragments inside ``shard_map`` bodies that the
  enclosing mesh/specs never declare.
* HPX022 flow-sensitive-host-sync — a device-origin value (on every
  reaching definition) flowing into ``float()/int()/bool()/np.array``
  in hot-path code; the def-use re-founding of HPX002.

Run it: ``python -m hpx_tpu.analysis [paths...]`` or the installed
``hpxlint`` script (defaults to ``hpx_tpu/``; run from the repo root so
baseline paths line up).  ``--changed`` lints only git-dirty files and
``--only HPX0NN`` restricts the rule set — the ~1s pre-commit path;
``tools/lint.py`` is the full three-tier CI gate.
"""

from .engine import (
    Finding,
    LintResult,
    ProjectRule,
    Rule,
    all_rules,
    apply_baseline,
    lint_paths,
    lint_source,
    lint_sources,
    load_baseline,
    register,
    stale_entries,
    update_baseline_file,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintResult",
    "ProjectRule",
    "Rule",
    "all_rules",
    "apply_baseline",
    "lint_paths",
    "lint_source",
    "lint_sources",
    "load_baseline",
    "register",
    "stale_entries",
    "update_baseline_file",
    "write_baseline",
]
