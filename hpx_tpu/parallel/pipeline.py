"""Pipeline parallelism: GPipe-style microbatched stage execution.

Reference analog: SURVEY.md §2.9 — HPX expresses pipelines as futures/
dataflow chains with channel handoff between stages (1d_stencil_8
pattern). TPU-first: each STAGE lives on its own device; microbatches
flow through per-stage jitted programs; XLA's per-device async dispatch
queues overlap stage s of microbatch m with stage s+1 of microbatch
m-1 — the dataflow futures ARE the pipeline schedule, no bubbles
beyond GPipe's fill/drain.

Training: GPipe-with-remat — forward keeps each stage's INPUT resident
on the stage's device; backward walks stages in reverse per microbatch,
rematerializing the stage forward inside a jitted vjp and accumulating
stage-local param grads. Semantics verified equal to the unpipelined
model (tests/test_plugins_pipeline.py).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["PipelineStage", "Pipeline"]


class PipelineStage:
    """One stage: fn(params, x) -> y, pinned to a device."""

    def __init__(self, fn: Callable[[Any, Any], Any], params: Any,
                 device: Any = None) -> None:
        self.fn = fn
        self.device = device
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        # computation follows its operands: params live on `device`, so
        # the jitted stage runs there (no deprecated jit(device=...))
        self._fwd = jax.jit(fn)
        # training backward: rematerialize the stage forward inside the
        # vjp (GPipe-with-remat — keeps both passes fully jitted; a
        # jitted fn can't RETURN a pullback closure, and an unjitted
        # vjp forward would run op-by-op)
        def bwd(params, x, cot):
            _y, pullback = jax.vjp(fn, params, x)
            return pullback(cot)
        self._bwd = jax.jit(bwd)

    def to_device(self, x: Any) -> Any:
        return jax.device_put(x, self.device) if self.device is not None \
            else x


class Pipeline:
    """A chain of stages over distinct devices.

        pipe = Pipeline([(fn0, p0), (fn1, p1)], devices=jax.devices()[:2])
        ys = pipe.forward(microbatches)              # inference
        loss, grads = pipe.train_step(mbs, tgts, loss_fn)

    forward() dispatches every (stage, microbatch) cell eagerly; jax's
    async dispatch pipelines them across devices (stage k of mb i runs
    while stage k+1 of mb i-1 runs) — the GPipe schedule emerges from
    the dataflow rather than being hand-scheduled.
    """

    def __init__(self, stage_defs: Sequence[Tuple[Callable, Any]],
                 devices: Optional[Sequence[Any]] = None) -> None:
        if devices is None:
            devices = jax.devices()
        n = len(stage_defs)
        if len(devices) < n:
            # fewer devices than stages: wrap around (still correct,
            # just less parallel)
            devices = [devices[i % len(devices)] for i in range(n)]
        self.stages = [PipelineStage(fn, p, devices[i])
                       for i, (fn, p) in enumerate(stage_defs)]
        self._loss_grad_cache: dict = {}

    def _loss_grad(self, loss_fn: Callable) -> Callable:
        """Jit value_and_grad(loss_fn) once per loss function — a fresh
        wrapper per train_step call would retrace the hot path every
        training iteration."""
        lg = self._loss_grad_cache.get(loss_fn)
        if lg is None:
            lg = jax.jit(jax.value_and_grad(loss_fn))
            self._loss_grad_cache[loss_fn] = lg
        return lg

    @property
    def params(self) -> List[Any]:
        return [s.params for s in self.stages]

    # -- inference -----------------------------------------------------------
    def forward(self, microbatches: Sequence[Any]) -> List[Any]:
        outs = []
        for mb in microbatches:
            x = mb
            for st in self.stages:
                x = st._fwd(st.params, st.to_device(x))
            outs.append(x)
        return outs

    # -- training ------------------------------------------------------------
    def train_step(self, microbatches: Sequence[Any],
                   targets: Sequence[Any],
                   loss_fn: Callable[[Any, Any], Any],
                   ) -> Tuple[Any, List[Any]]:
        """GPipe: forward all microbatches (saving pullbacks), backward
        all, accumulate grads per stage. Returns (mean loss, grads per
        stage). Gradient == the unpipelined gradient of
        mean_mb(loss_fn(model(x), t))."""
        nmb = len(microbatches)
        # forward: fill the pipeline, saving each stage's INPUT (the
        # backward rematerializes the stage forward — GPipe-with-remat)
        stage_inputs: List[List[Any]] = [[] for _ in self.stages]
        acts: List[Any] = []
        for mb in microbatches:
            x = mb
            for si, st in enumerate(self.stages):
                x_in = st.to_device(x)
                stage_inputs[si].append(x_in)
                x = st._fwd(st.params, x_in)
            acts.append(x)

        loss_grad = self._loss_grad(loss_fn)
        losses = []
        grads: List[Any] = [None] * len(self.stages)
        for mi in range(nmb):
            lval, gy = loss_grad(acts[mi], targets[mi])
            losses.append(lval)
            cot = jax.tree.map(lambda g: g / nmb, gy)
            # backward: drain stages in reverse
            for si in range(len(self.stages) - 1, -1, -1):
                st = self.stages[si]
                gparams, gx = st._bwd(st.params, stage_inputs[si][mi],
                                      st.to_device(cot))
                grads[si] = gparams if grads[si] is None else \
                    jax.tree.map(jnp.add, grads[si], gparams)
                cot = gx
        mean_loss = sum(jnp.asarray(l) for l in losses) / nmb
        return mean_loss, grads

    def apply_grads(self, grads: List[Any], lr: float) -> None:
        for st, g in zip(self.stages, grads):
            st.params = jax.tree.map(lambda p, gg: p - lr * gg,
                                     st.params, g)
