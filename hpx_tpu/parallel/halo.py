"""Halo exchange over the device mesh — the neighbor-ring substrate.

Reference analog: the distributed stencil halo exchange of
examples/1d_stencil/1d_stencil_8.cpp (channels between neighboring
localities) and hpx::lcos::local::receive_buffer. TPU-first: the ring is
lax.ppermute over ICI inside shard_map — compiled, deadlock-free, and the
same primitive ring attention / context parallelism rides (SURVEY.md
§5.7); ring_attention (M10) builds on exactly this exchange.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Send x to the neighbor `shift` steps up the ring (periodic).

    Inside shard_map only. shift=+1: each shard receives its LEFT
    neighbor's payload (data moves right).
    """
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def halo_exchange_1d(u_local: jax.Array, axis_name: str):
    """Return (left_ghost, right_ghost) 1-element arrays for a 1-D shard.

    left_ghost = left neighbor's last element, right_ghost = right
    neighbor's first element (periodic ring over the mesh axis).
    """
    left_ghost = ring_shift(u_local[-1:], axis_name, +1)
    right_ghost = ring_shift(u_local[:1], axis_name, -1)
    return left_ghost, right_ghost


def sharded_heat_step(mesh: Mesh, axis: str = "x",
                      halo_steps: int = 1) -> Callable:
    """Build a jitted SPMD heat step: shard_map body does `halo_steps`
    local updates per exchange (ghost width = halo_steps — the classic
    communication-avoiding trapezoid).

    The returned fn(u_sharded, coef) keeps u sharded over `axis`;
    ICI traffic is 2 * halo_steps elements per shard per call.
    """
    from ..utils.jaxcompat import shard_map

    w = halo_steps

    def body(u, coef):
        lg = ring_shift(u[-w:], axis, +1)   # left neighbor's tail
        rg = ring_shift(u[:w], axis, -1)    # right neighbor's head
        ext = jnp.concatenate([lg, u, rg])
        for _ in range(w):
            ext = ext[1:-1] + coef * (ext[:-2] - 2.0 * ext[1:-1] + ext[2:])
        return ext

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis), P()),
                   out_specs=P(axis))
    return jax.jit(fn)


def sharded_multistep(mesh: Mesh, axis: str, steps: int,
                      halo_steps: int = 1) -> Callable:
    """T-step sharded stencil: fori_loop of exchange+update inside ONE
    jitted program — the whole time loop is a single XLA computation with
    ICI collectives compiled in (no host round-trips)."""
    from ..utils.jaxcompat import shard_map

    w = halo_steps
    outer = steps // w
    assert steps % w == 0, "steps must be a multiple of halo_steps"

    def body(u, coef):
        def one(_i, s):
            lg = ring_shift(s[-w:], axis, +1)
            rg = ring_shift(s[:w], axis, -1)
            ext = jnp.concatenate([lg, s, rg])
            for _ in range(w):
                ext = ext[1:-1] + coef * (
                    ext[:-2] - 2.0 * ext[1:-1] + ext[2:])
            return ext
        return jax.lax.fori_loop(0, outer, one, u)

    fn = shard_map(body, mesh=mesh, in_specs=(P(axis), P()),
                   out_specs=P(axis))
    return jax.jit(fn)
