"""SPMD blocks — hpx::parallel::spmd_block analog, two planes.

Reference analog: hpx's `define_spmd_block` (quickstart/examples and
`partitioned_vector_view` SPMD access, SURVEY.md §2.6, §5.7): run the
same function as N "images", each knowing its rank, with `sync_all`
barriers between phases.

Two TPU-native planes:

  * HOST plane (`define_spmd_block`): images = host tasks (one per
    image on this locality, or one per locality when distributed=True).
    Good for orchestration logic. Barriers are futures-based
    (local AndGate) or the distributed barrier.

  * DEVICE plane (`device_spmd_block`): images = mesh devices; the
    block body runs inside `shard_map`, `block.sync_all()` is free
    (XLA's SPMD execution is bulk-synchronous per program), and
    `block.image_id()` is the mesh coordinate. This is the idiomatic
    home of SPMD on TPU: the reference's spmd_block pattern collapses
    into a sharded program.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

from ..futures.combinators import when_all
from ..futures.future import Future
from ..futures.async_ import async_

__all__ = ["SpmdBlock", "define_spmd_block", "device_spmd_block"]


class _LocalBarrier:
    """Reusable generation barrier for N host images."""

    def __init__(self, n: int) -> None:
        self._n = n
        self._count = 0
        self._gen = 0
        self._cv = threading.Condition()

    def arrive_and_wait(self, timeout: float = 60.0) -> None:
        with self._cv:
            gen = self._gen
            self._count += 1
            if self._count == self._n:
                self._count = 0
                self._gen += 1
                self._cv.notify_all()
                return
            if not self._cv.wait_for(lambda: self._gen != gen, timeout):
                from ..core.errors import Error, HpxError
                raise HpxError(Error.deadlock,
                               "spmd_block sync_all timed out")


class SpmdBlock:
    """Handle passed to each image (reference: hpx::spmd_block)."""

    def __init__(self, name: str, image_id: int, num_images: int,
                 barrier: Any) -> None:
        self._name = name
        self._image = image_id
        self._num = num_images
        self._barrier = barrier

    def get_block_name(self) -> str:
        return self._name

    def this_image(self) -> int:
        return self._image

    def get_num_images(self) -> int:
        return self._num

    # HPX spelling
    image_id = this_image

    def sync_all(self) -> None:
        self._barrier()


def define_spmd_block(name: str, num_images: int,
                      fn: Callable[..., Any], *args: Any,
                      distributed: bool = False) -> Future:
    """Run fn(block, *args) as num_images SPMD images.

    distributed=False: images are host tasks on THIS locality (the
    reference's single-locality spmd_block over its thread pool).
    Returns future<list> of the images' return values.

    distributed=True: call this ON EVERY participating locality (SPMD
    style, like the reference's multi-locality blocks); this locality
    runs image `find_here()`, barriers ride the distributed runtime.
    Returns future<value> of the local image.
    """
    if distributed:
        from ..dist.runtime import find_here, get_num_localities, get_runtime
        nloc = get_num_localities()
        if num_images != nloc:
            from ..core.errors import Error, HpxError
            raise HpxError(Error.bad_parameter,
                           f"distributed spmd_block needs one image per "
                           f"locality ({nloc}), got {num_images}")
        rt = get_runtime()
        gen_box = [0]

        def dist_barrier() -> None:
            gen_box[0] += 1
            rt.barrier(f"spmd/{name}/{gen_box[0]}")

        block = SpmdBlock(name, find_here(), num_images, dist_barrier)
        return async_(fn, block, *args)

    # dedicated pool, one thread per image: images block in sync_all, so
    # running them on the shared bounded pool would deadlock whenever
    # num_images exceeds the pool width (no stackful coroutines to
    # suspend, unlike the reference)
    from ..exec.executors import ThreadPoolExecutor
    ex = ThreadPoolExecutor(num_images)
    bar = _LocalBarrier(num_images)
    futs: List[Future] = []
    for i in range(num_images):
        block = SpmdBlock(name, i, num_images, bar.arrive_and_wait)
        futs.append(ex.async_execute(fn, block, *args))

    def collect(f: Future) -> List[Any]:
        try:
            return [x.get() for x in f.get()]
        finally:
            # this continuation runs ON one of ex's own workers: a pool
            # cannot join itself — hand the teardown to the default pool
            from ..runtime.threadpool import default_pool
            default_pool().submit(ex.shutdown)

    return when_all(futs).then(collect)


def device_spmd_block(fn: Callable[..., Any], mesh: Any = None,
                      axis: str = "x",
                      in_specs: Any = None, out_specs: Any = None):
    """Lower an SPMD block onto the device mesh.

    fn(block, *arrays) runs per-shard inside shard_map; block.this_image()
    is a traced mesh coordinate (`lax.axis_index`), block.get_num_images()
    the axis size, and sync_all() a no-op (XLA programs are already
    bulk-synchronous across shards — the reference's sync_all maps to
    "end of fused region").  Returns the jitted callable.

        step = device_spmd_block(body, mesh, "x", in_specs=(P("x"),),
                                 out_specs=P("x"))
        out = step(sharded_array)
    """
    import jax
    from ..utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        from .mesh import default_mesh
        mesh = default_mesh()
    if in_specs is None:
        in_specs = P(axis)
    if out_specs is None:
        out_specs = P(axis)

    def body(*arrays: Any):
        idx = jax.lax.axis_index(axis)
        n = mesh.shape[axis]
        block = SpmdBlock(f"device/{axis}", idx, n, lambda: None)
        return fn(block, *arrays)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))
