from .mesh import make_mesh, replicated, shard_1d  # noqa: F401
from .halo import (  # noqa: F401
    halo_exchange_1d,
    ring_shift,
    sharded_heat_step,
    sharded_multistep,
)
from .spmd import SpmdBlock, define_spmd_block, device_spmd_block  # noqa: F401
from .pipeline import Pipeline, PipelineStage  # noqa: F401
from . import multihost  # noqa: F401
