"""Device mesh helpers.

Reference analog: HPX's resource partitioner + topology (libs/core/
resource_partitioner, libs/core/topology) decide which cores run what;
on TPU the analogous resource is the device mesh and its named axes.
Localities (M5) map onto mesh coordinates.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("x",),
              devices=None):
    """Create a jax.sharding.Mesh. Default: all devices on one axis."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),)
    arr = np.array(devs).reshape(tuple(shape))
    if len(axis_names) != arr.ndim:
        axis_names = tuple(f"ax{i}" for i in range(arr.ndim))
    return Mesh(arr, tuple(axis_names))


_default_mesh = None


def default_mesh():
    """The cached all-devices 1-D mesh ('x'). Sharing one Mesh object
    lets compiled-program caches keyed on meshes hit across callers."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def shard_1d(arr, mesh, axis: str = "x"):
    """Place a 1-D array sharded across the given mesh axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def replicated(arr, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr, NamedSharding(mesh, P()))
