"""Device mesh helpers.

Reference analog: HPX's resource partitioner + topology (libs/core/
resource_partitioner, libs/core/topology) decide which cores run what;
on TPU the analogous resource is the device mesh and its named axes.
Localities (M5) map onto mesh coordinates.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


_mesh_cache = {}


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("x",),
              devices=None):
    """Create a jax.sharding.Mesh. Default: all devices on one axis.

    All-device meshes are cached per (shape, axis_names) so that
    compiled-program caches keyed on meshes hit across callers, whatever
    the axis is called; explicit device subsets are not cached.
    """
    import jax
    from jax.sharding import Mesh

    explicit = devices is not None
    devs = list(devices) if explicit else jax.devices()
    if shape is None:
        shape = (len(devs),)
    shape = tuple(shape)
    arr = np.array(devs).reshape(shape)
    if len(axis_names) != arr.ndim:
        axis_names = tuple(f"ax{i}" for i in range(arr.ndim))
    axis_names = tuple(axis_names)
    if explicit:
        return Mesh(arr, axis_names)
    key = (shape, axis_names)
    mesh = _mesh_cache.get(key)
    if mesh is None:
        mesh = _mesh_cache.setdefault(key, Mesh(arr, axis_names))
    return mesh


def default_mesh():
    """The cached all-devices 1-D mesh ('x')."""
    return make_mesh()


def shard_1d(arr, mesh, axis: str = "x"):
    """Place a 1-D array sharded across the given mesh axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def replicated(arr, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr, NamedSharding(mesh, P()))
