"""2-D halo exchange over a 2-D device mesh — config #5's substrate.

Reference analog: the ghost-zone exchange of examples/jacobi/ and
examples/jacobi_smp/ (row-block dataflow dependencies), generalized to a
2-D decomposition. TPU-first: both halo directions are lax.ppermute over
ICI inside one shard_map body; the whole Jacobi sweep — exchange, 5-point
update, boundary masking, residual psum — compiles to a single XLA
program per dispatch. Non-periodic edges fall out of ppermute semantics:
a shard with no source in the permutation receives zeros, which is
exactly the zero-Dirichlet ghost value; interior masking keeps true
boundary cells fixed.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def edge_shift(x: jax.Array, axis_name: str, shift: int) -> jax.Array:
    """Non-periodic neighbor shift along a mesh axis (inside shard_map).

    shift=+1: each shard receives the payload of the neighbor BELOW it in
    index order (data moves toward higher mesh index); the shard at the
    low edge receives zeros. shift=-1 is the mirror.
    """
    n = jax.lax.axis_size(axis_name)
    if shift >= 0:
        perm = [(i, i + shift) for i in range(n - shift)]
    else:
        perm = [(i, i + shift) for i in range(-shift, n)]
    return jax.lax.ppermute(x, axis_name, perm)


def halo_exchange_2d(u: jax.Array, ax: str, ay: str
                     ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Exchange 1-cell ghost edges of a (h, w) local block.

    Returns (north, south, west, east) ghost strips: north = the last row
    of the neighbor at mesh index-1 along `ax` (zeros at the boundary),
    etc. Corners are not exchanged (5-point stencils don't need them).
    """
    north = edge_shift(u[-1:, :], ax, +1)
    south = edge_shift(u[:1, :], ax, -1)
    west = edge_shift(u[:, -1:], ay, +1)
    east = edge_shift(u[:, :1], ay, -1)
    return north, south, west, east


def _interior_mask(local_shape: Tuple[int, int], grid: Tuple[int, int],
                   ax: str, ay: str) -> jax.Array:
    """Boolean (h, w) mask of cells that are interior in GLOBAL coords."""
    h, w = local_shape
    nx, ny = grid
    gr = jax.lax.axis_index(ax) * h + jnp.arange(h)
    gc = jax.lax.axis_index(ay) * w + jnp.arange(w)
    rows = (gr > 0) & (gr < nx - 1)
    cols = (gc > 0) & (gc < ny - 1)
    return rows[:, None] & cols[None, :]


def jacobi_local_sweep(u: jax.Array, mask: jax.Array,
                       ax: str, ay: str) -> jax.Array:
    """One 5-point Jacobi sweep of a local block with halo exchange.

    u_new = mean of 4 neighbors on interior cells; boundary cells are
    carried through unchanged (Dirichlet).
    """
    north, south, west, east = halo_exchange_2d(u, ax, ay)
    vert = jnp.concatenate([north, u, south], axis=0)
    horz = jnp.concatenate([west, u, east], axis=1)
    new = 0.25 * (vert[:-2, :] + vert[2:, :] + horz[:, :-2] + horz[:, 2:])
    return jnp.where(mask, new, u)


def sharded_jacobi_step(mesh: Mesh, grid: Tuple[int, int],
                        ax: str = "x", ay: str = "y") -> Callable:
    """Jitted SPMD Jacobi step over a 2-D mesh: fn(u) -> (u_new, residual).

    residual = global sum of squared cell updates (psum over both axes) —
    the convergence diagnostic, computed on-device so the host never syncs
    unless it reads it.
    """
    from ..utils.jaxcompat import shard_map

    nx, ny = grid
    npx, npy = mesh.shape[ax], mesh.shape[ay]
    assert nx % npx == 0 and ny % npy == 0, (grid, dict(mesh.shape))
    local = (nx // npx, ny // npy)

    def body(u):
        mask = _interior_mask(local, grid, ax, ay)
        new = jacobi_local_sweep(u, mask, ax, ay)
        res = jax.lax.psum(jnp.sum((new - u) ** 2), (ax, ay))
        return new, res

    fn = shard_map(body, mesh=mesh, in_specs=P(ax, ay),
                   out_specs=(P(ax, ay), P()))
    return jax.jit(fn)


def sharded_jacobi_multistep(mesh: Mesh, grid: Tuple[int, int], steps: int,
                             ax: str = "x", ay: str = "y") -> Callable:
    """`steps` Jacobi sweeps fused into ONE XLA program (fori_loop inside
    shard_map): per-sweep halo exchange rides ICI with no host round-trip.
    fn(u) -> (u_new, last_residual).
    """
    from ..utils.jaxcompat import shard_map

    nx, ny = grid
    npx, npy = mesh.shape[ax], mesh.shape[ay]
    assert nx % npx == 0 and ny % npy == 0, (grid, dict(mesh.shape))
    local = (nx // npx, ny // npy)

    def body(u):
        mask = _interior_mask(local, grid, ax, ay)

        def one(_i, carry):
            s, _ = carry
            new = jacobi_local_sweep(s, mask, ax, ay)
            res = jax.lax.psum(jnp.sum((new - s) ** 2), (ax, ay))
            return new, res

        return jax.lax.fori_loop(0, steps, one,
                                 (u, jnp.zeros((), u.dtype)))

    fn = shard_map(body, mesh=mesh, in_specs=P(ax, ay),
                   out_specs=(P(ax, ay), P()))
    return jax.jit(fn)
